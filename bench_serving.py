"""Serving benchmark: HTTP -> continuous batching -> pjit inference on the
real chip (the reference's serving story is DistributedHTTPSource feeding
CNTKModel, SURVEY.md §2.4/§3.5 — no published latency/throughput numbers).

Measures end-to-end client-observed latency (p50/p99) and sustained
throughput for a ResNet-20 scorer behind `serve_pipeline`, with uint8 image
payloads (the wire format TpuModel.transferDtype optimizes). Prints one
JSON line per load level; the last line is the headline.

``--open-loop`` runs the PRODUCTION-SHAPED benchmark instead: an
open-loop arrival process (Poisson, or bursty on/off — requests arrive on
the schedule whether or not earlier ones finished, unlike the closed loop
above whose clients self-throttle) drives BOTH serving engines over the
same model and schedule:

  * ``polling``    — the seed's micro-batch loop (`serve_pipeline`:
                     getBatch drains whatever arrived, per-row f32 host
                     decode);
  * ``continuous`` — the shape-bucket continuous-batching engine
                     (`io/serving`: max-wait bucket formation, fused
                     decode->pad->pjit->unpad step, AOT-warm buckets);

and reports **goodput** (200-replies within the deadline per second) and
p50/p99/p999 latency under saturation. The last line is one
``mmlspark-bench/v1`` document, so the perf gate records
`serving_open_loop_*` as first-round metrics and gates them thereafter.

``--chaos`` runs the resilience scenario instead: the PROCESS fleet
(`serve_fleet` + FleetSupervisor) under a 10% injected `fleet.poll` error
rate plus one mid-run worker kill. Clients post through a RetryPolicy (the
documented client contract under worker loss) and the report adds
`recovery_s` — wall time from the kill until the restarted worker's URL
serves a request again — plus the retry/restart counters.
"""

import argparse
import base64
import json
import threading
import time

import numpy as np


class _ImageScorer:
    """(id, value) -> reply: decode base64 uint8 image batch, score.

    ``prepare`` (the per-row base64 decode + feature assembly) is split
    from ``transform`` (the pjit score) so the serving loop's prefetch
    thread decodes the NEXT micro-batch while the current one runs on
    device."""

    def __init__(self, cfg=None, params=None):
        import jax
        from mmlspark_tpu.models import TpuModel, build_model
        cfg = cfg or {"type": "resnet", "num_classes": 10}
        module = build_model(cfg)
        if params is None:
            params = module.init(jax.random.PRNGKey(0),
                                 np.zeros((1, 32, 32, 3), np.float32))
        self.model = (TpuModel().setModelConfig(cfg).setModelParams(params)
                      .setInputCol("features").setTransferDtype("bfloat16")
                      .setInputShape((3, 32, 32)))
        from mmlspark_tpu import DataFrame
        from mmlspark_tpu.core.utils import object_column
        ex = DataFrame({"features": object_column(
            [np.zeros(32 * 32 * 3, np.float32)])})
        self.model.warmup(ex, max_rows=256)  # no request pays a compile

    def prepare(self, df):
        from mmlspark_tpu.core.utils import object_column
        imgs = [np.frombuffer(base64.b64decode(v), dtype=np.uint8)
                .reshape(32, 32, 3).astype(np.float32).ravel()
                for v in df.col("value")]
        return df.withColumn("features", object_column(imgs))

    def transform(self, df):
        from mmlspark_tpu.core.utils import object_column
        scored = self.model.transform(df)
        replies = [json.dumps({"label": int(np.argmax(s))})
                   for s in scored.col("scores")]
        return scored.withColumn("reply", object_column(replies))


class _ChaosScorer(_ImageScorer):
    """Fleet transformer: prepare + transform fused (the ReplayServingLoop
    has no separate prepare stage)."""

    def transform(self, df):
        return super().transform(self.prepare(df))


def chaos_main(fault_rate: float = 0.1, clients: int = 8,
               per_client: int = 30, trace: bool = False):
    """Fleet chaos run: injected poll faults + one worker kill mid-run.
    ``trace=True`` additionally enables distributed tracing in every
    process (workers inherit MMLSPARK_TPU_TELEMETRY), collects each
    process's span buffer at the end, and merges them into one
    per-request Chrome trace (serving_trace.jsonl)."""
    import os
    import tempfile
    from mmlspark_tpu import telemetry
    from mmlspark_tpu.io.http.fleet import serve_fleet
    from mmlspark_tpu.resilience import faults
    from mmlspark_tpu.resilience.policy import RetryPolicy
    import urllib.request

    telemetry.enable()
    if trace:
        # spawned worker processes read the env at import — this is how
        # their ingress spans (and the traceparent envelope) turn on
        os.environ["MMLSPARK_TPU_TELEMETRY"] = "1"
    if fault_rate > 0:
        faults.configure(f"fleet.poll:error:{fault_rate}", seed=0)
    rng = np.random.default_rng(0)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())

    source, loop = serve_fleet(_ChaosScorer(), n_workers=2, supervise=True,
                               probe_interval=0.1)
    urls = [w.url for w in source.workers]

    def post(url, timeout=30.0):
        req = urllib.request.Request(url, data=payload)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            assert r.status == 200, r.status
            return r.read()

    try:
        post(urls[0], timeout=180)       # warmup: compile on worker 0
        post(urls[1], timeout=60)

        lat: list = []
        failures: list = []
        lock = threading.Lock()

        def worker(ci):
            policy = RetryPolicy(name="bench.client", max_attempts=60,
                                 base_delay=0.05, max_delay=0.5,
                                 deadline=60.0, seed=ci)
            mine, bad = [], []
            for _ in range(per_client):
                t0 = time.perf_counter()
                try:
                    policy.run(lambda _a: post(urls[ci % 2], timeout=5.0))
                    mine.append(time.perf_counter() - t0)
                except Exception as e:
                    bad.append(repr(e))
            with lock:
                lat.extend(mine)
                failures.extend(bad)

        threads = [threading.Thread(target=worker, args=(ci,))
                   for ci in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(1.0)
        t_kill = time.perf_counter()
        source.killWorker(0)             # the mid-run worker kill
        # recovery = kill -> the same URL serves again (supervisor restart)
        recovery = None
        deadline = time.monotonic() + 60
        while recovery is None and time.monotonic() < deadline:
            try:
                post(urls[0], timeout=2.0)
                recovery = time.perf_counter() - t_kill
            except Exception:
                time.sleep(0.05)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if failures:
            raise RuntimeError(f"{len(failures)} lost requests under "
                               f"chaos, e.g. {failures[0]}")
        snap = telemetry.snapshot()

        def total(name):
            return sum(s["value"]
                       for s in snap.get(name, {}).get("series", []))

        lat_ms = np.sort(np.array(lat)) * 1e3
        result = {
            "metric": "serving_resnet20_fleet_chaos",
            "fault_rate": fault_rate,
            "clients": clients,
            "requests": len(lat),
            "lost": 0,
            "throughput_rps": round(len(lat) / wall, 1),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            "recovery_s": None if recovery is None else round(recovery, 2),
            "faults_injected": total("mmlspark_faults_injected_total"),
            "retries": total("mmlspark_retry_attempts_total"),
            "worker_restarts": total(
                "mmlspark_supervisor_worker_restarts_total"),
        }
        if trace:
            # one Chrome-trace file per process -> one merged per-request
            # tree: every hop of a request shares its trace_id, spans
            # nest via parent_span_id (load in Perfetto)
            tdir = tempfile.mkdtemp(prefix="fleet_trace_")
            paths = source.collect_traces(tdir)
            out = "serving_trace.jsonl"
            merged = telemetry.merge_traces(paths, out)
            traced = {(e.get("args") or {}).get("trace_id")
                      for e in merged} - {None}
            result.update(trace_file=out, trace_events=len(merged),
                          trace_processes=len(paths),
                          requests_traced=len(traced))
        print(json.dumps(result))
        return result
    finally:
        loop.stop()
        faults.clear()
        telemetry.disable()


def arrival_times(process: str, rate: float, duration: float,
                  seed: int = 0, burst_duty: float = 0.25,
                  burst_period: float = 1.0) -> np.ndarray:
    """Open-loop arrival schedule (seconds from t0).

    ``poisson``: exponential inter-arrivals at ``rate``/s. ``bursty``:
    the same MEAN rate delivered as on/off square-wave bursts —
    ``burst_duty`` of each ``burst_period`` carries Poisson arrivals at
    ``rate / burst_duty`` (4x the mean by default), the rest is silent;
    the tail-latency scenario continuous batching + admission control
    exist for. Deterministic per (process, rate, duration, seed)."""
    rng = np.random.default_rng(seed)
    out = []
    if process == "poisson":
        t = rng.exponential(1.0 / rate)
        while t < duration:
            out.append(t)
            t += rng.exponential(1.0 / rate)
    elif process == "bursty":
        on_rate = rate / burst_duty
        k = 0
        while k * burst_period < duration:
            t = k * burst_period + rng.exponential(1.0 / on_rate)
            stop = min(k * burst_period + burst_duty * burst_period,
                       duration)
            while t < stop:
                out.append(t)
                t += rng.exponential(1.0 / on_rate)
            k += 1
    else:
        raise ValueError(f"arrival process must be poisson|bursty, "
                         f"got {process!r}")
    return np.asarray(out)


def run_open_loop(url, payload: bytes, schedule: np.ndarray,
                  deadline: float = 1.0, pool: int = 64) -> dict:
    """Drive one serving URL with an open-loop schedule from a bounded
    client pool; returns goodput + latency percentiles + failure
    taxonomy. A reply counts toward GOODPUT only when it is a 200 within
    ``deadline`` of its scheduled arrival; 503 sheds, late replies,
    errors, and timeouts all count offered-but-not-good. When every pool
    client is busy the schedule slips (recorded as ``slipped`` — the
    practical bound on offered concurrency). ``url`` may be a callable
    ``() -> url`` so elastic-fleet scenarios pick a live replica per
    request."""
    import urllib.error
    import urllib.request

    idx = {"i": 0}
    lock = threading.Lock()
    lat: list = []        # good-reply latencies (from scheduled arrival)
    counts = {"good": 0, "shed": 0, "late": 0, "error": 0, "slipped": 0}

    def client():
        while True:
            with lock:
                i = idx["i"]
                if i >= len(schedule):
                    return
                idx["i"] = i + 1
            target = t0 + schedule[i]
            now = time.perf_counter()
            if now < target:
                time.sleep(target - now)
            elif now - target > 0.001:
                with lock:
                    counts["slipped"] += 1
            try:
                u = url() if callable(url) else url
                req = urllib.request.Request(u, data=payload)
                with urllib.request.urlopen(req, timeout=deadline) as r:
                    ok = r.status == 200
                    r.read()
            except urllib.error.HTTPError as e:
                with lock:
                    counts["shed" if e.code == 503 else "error"] += 1
                continue
            except Exception:
                with lock:
                    counts["error"] += 1
                continue
            dt = time.perf_counter() - target
            with lock:
                if ok and dt <= deadline:
                    counts["good"] += 1
                    lat.append(dt)
                else:
                    counts["late" if ok else "error"] += 1

    threads = [threading.Thread(target=client) for _ in range(pool)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat_ms = np.sort(np.asarray(lat)) * 1e3 if lat else np.array([0.0])
    return {
        "offered": len(schedule),
        "offered_rps": round(len(schedule) / wall, 1),
        "goodput_rps": round(counts["good"] / wall, 1),
        "good": counts["good"], "shed": counts["shed"],
        "late": counts["late"], "errors": counts["error"],
        "slipped": counts["slipped"],
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
        "p999_ms": round(float(np.percentile(lat_ms, 99.9)), 1),
        "wall_s": round(wall, 2),
    }


def open_loop_main(rate: float, duration: float, arrival: str = "poisson",
                   deadline: float = 1.0, pool: int = 64,
                   smoke: bool = False, max_batch: int = 256,
                   max_wait: float = 0.005, max_queue_depth: int = 1024,
                   engines=("polling", "continuous")):
    """The production-shaped comparison: same model, same payloads, same
    open-loop schedule against the polling loop and the continuous-
    batching engine; prints one JSON line per engine and the
    mmlspark-bench/v1 document last."""
    import jax
    from mmlspark_tpu.io.http import serve_pipeline
    from mmlspark_tpu.io.serving import (BucketPolicy, FusedServingStep,
                                         serve_continuous)
    from mmlspark_tpu.models import build_model

    cfg = ({"type": "convnet", "channels": (4, 4), "dense": 16,
            "num_classes": 10} if smoke
           else {"type": "resnet", "num_classes": 10})
    module = build_model(cfg)
    params = module.init(jax.random.PRNGKey(0),
                         np.zeros((1, 32, 32, 3), np.float32))
    rng = np.random.default_rng(0)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())
    schedule = arrival_times(arrival, rate, duration)
    results: dict = {}

    if "polling" in engines:
        scorer = _ImageScorer(cfg, params)   # warmup() precompiles
        source, loop = serve_pipeline(scorer, max_batch=max_batch,
                                      prepare=scorer.prepare,
                                      max_queue_depth=max_queue_depth)
        try:
            results["polling"] = run_open_loop(source.url, payload,
                                               schedule, deadline, pool)
        finally:
            loop.stop()
            source.close()
        print(json.dumps({"engine": "polling", "arrival": arrival,
                          "rate": rate, **results["polling"]}))

    if "continuous" in engines:
        import urllib.request
        from mmlspark_tpu import telemetry
        from mmlspark_tpu.telemetry.federation import (FederatedSampler,
                                                       FleetScraper)
        from mmlspark_tpu.telemetry.timeseries import \
            percentile_from_buckets
        step = FusedServingStep(cfg, params,
                                policy=BucketPolicy(max_batch=max_batch),
                                row_shape=(32, 32, 3),
                                in_dtype=np.uint8, output="argmax")
        source, loop = serve_continuous(step, max_wait=max_wait,
                                        max_queue_depth=max_queue_depth)
        try:
            for _ in range(4):      # compile + settle before either run
                try:
                    urllib.request.urlopen(
                        urllib.request.Request(source.url, data=payload),
                        timeout=30).read()
                except Exception:
                    pass
            # attribution-off baseline: telemetry dark, tail sampling
            # disarmed. Ledger stamping itself is always on, so the p50
            # delta against the instrumented run below prices span
            # emission + the phase histogram + tail sampling — the
            # attribution overhead docs/observability.md budgets at
            # <= 2% on p50.
            base = run_open_loop(source.url, payload, schedule, deadline,
                                 pool)
            # fleet-view vs driver-view: sample the server's own request
            # histogram and scrape it back over HTTP, exactly the way
            # fleet federation sees a worker — the divergence between the
            # merged (server-side) percentiles and the client-observed
            # ones is the part of latency the server never sees (connect
            # + queueing in the kernel + bucket-grid quantization)
            telemetry.timeseries.start(interval=0.25)
            telemetry.trace.enable_tail_sampling(quantile=0.95,
                                                 max_retained=128)
            scraper = FleetScraper(
                targets=[("serving", f"{source.url}timeseries")],
                interval=0.25, sampler=FederatedSampler(interval=0.25))
            scraper.scrape_once()   # seed round: baselines, zero deltas
            cont = results["continuous"] = run_open_loop(
                source.url, payload, schedule, deadline, pool)
            time.sleep(0.6)         # let the sampler tick the last rows
            scraper.scrape_once()
            for q, label in ((0.50, "p50"), (0.99, "p99")):
                p = scraper.sampler.worker_percentile(
                    "serving", "mmlspark_http_request_seconds", q,
                    window=duration + 120.0)
                if p is not None:
                    cont[f"fleet_{label}_ms"] = round(p * 1e3, 1)
            if base["p50_ms"] > 0:
                cont["attribution_overhead_pct"] = round(
                    (cont["p50_ms"] - base["p50_ms"])
                    / base["p50_ms"] * 100.0, 2)
            # per-phase breakdown from the ledger-fed histogram: the
            # instrumented run is the only traffic since telemetry came
            # up, so cumulative bucket counts ARE the run's deltas
            snap = telemetry.registry.snapshot()
            fam = snap.get("mmlspark_serving_phase_seconds", {})
            for s in fam.get("series", []):
                phase = s.get("labels", {}).get("phase")
                if phase not in ("queue", "pad", "device", "readback"):
                    continue
                for q, label in ((0.50, "p50"), (0.99, "p99")):
                    p = percentile_from_buckets(s["buckets"], q)
                    if p is not None:
                        cont[f"phase_{phase}_{label}_ms"] = round(
                            p * 1e3, 2)
            # the ledger phases partition each request, so their _sum
            # totals reconcile with the server-observed request-latency
            # _sum (ratio < 1: the slice after "reply" — the reply-write
            # syscall — is the only part the ledger never sees)
            phase_sum = sum(s.get("sum", 0.0)
                            for s in fam.get("series", []))
            req_sum = sum(
                s.get("sum", 0.0)
                for s in snap.get("mmlspark_http_request_seconds",
                                  {}).get("series", []))
            if req_sum > 0:
                cont["phase_sum_ratio"] = round(phase_sum / req_sum, 3)
            cont["exemplar_linked"] = int(
                ' # {trace_id="' in scraper.sampler.prometheus_text())
            fetched = 0
            for tid in reversed(telemetry.trace.retained_ids()):
                try:
                    with urllib.request.urlopen(
                            f"{source.url}debug/trace/{tid}",
                            timeout=5) as r:
                        fetched = int(r.status == 200
                                      and bool(json.loads(r.read())
                                               .get("events")))
                    break
                except Exception:
                    continue
            cont["trace_fetch_ok"] = fetched
        finally:
            telemetry.trace.disable_tail_sampling()
            telemetry.timeseries.stop()
            loop.stop()
            source.close()
        print(json.dumps({"engine": "continuous", "arrival": arrival,
                          "rate": rate, **results["continuous"]}))

    metrics = []
    cont = results.get("continuous")
    poll = results.get("polling")
    if cont:
        extra = {}
        if poll and poll["goodput_rps"]:
            extra["vs_polling"] = round(
                cont["goodput_rps"] / poll["goodput_rps"], 2)
        metrics.append({"metric": "serving_open_loop_goodput_rps",
                        "value": cont["goodput_rps"], "unit": "req/s",
                        "arrival": arrival, "rate": rate, **extra})
        for q in ("p50", "p99", "p999"):
            metrics.append({"metric": f"serving_open_loop_{q}_ms",
                            "value": cont[f"{q}_ms"], "unit": "ms",
                            "arrival": arrival, "rate": rate})
        for q in ("p50", "p99"):
            if f"fleet_{q}_ms" not in cont:
                continue
            metrics.append({"metric": f"serving_open_loop_fleet_{q}_ms",
                            "value": cont[f"fleet_{q}_ms"], "unit": "ms",
                            "arrival": arrival, "rate": rate})
            metrics.append(
                {"metric": f"serving_open_loop_view_divergence_{q}_ms",
                 "value": round(cont[f"{q}_ms"] - cont[f"fleet_{q}_ms"],
                                1),
                 "unit": "ms", "arrival": arrival, "rate": rate})
        for phase in ("queue", "pad", "device", "readback"):
            for q in ("p50", "p99"):
                key = f"phase_{phase}_{q}_ms"
                if key in cont:
                    metrics.append(
                        {"metric": f"serving_open_loop_{key}",
                         "value": cont[key], "unit": "ms",
                         "arrival": arrival, "rate": rate})
        if "phase_sum_ratio" in cont:
            metrics.append({"metric": "serving_open_loop_phase_sum_ratio",
                            "value": cont["phase_sum_ratio"],
                            "unit": "ratio", "arrival": arrival,
                            "rate": rate})
        if "attribution_overhead_pct" in cont:
            ov = cont["attribution_overhead_pct"]
            metrics.append(
                {"metric": "serving_open_loop_attribution_overhead_pct",
                 "value": ov, "unit": "%", "budget_pct": 2.0,
                 "ok": bool(ov <= 2.0), "arrival": arrival,
                 "rate": rate})
        for key in ("exemplar_linked", "trace_fetch_ok"):
            if key in cont:
                metrics.append({"metric": f"serving_open_loop_{key}",
                                "value": cont[key], "unit": "bool",
                                "arrival": arrival, "rate": rate})
    if poll:
        metrics.append({"metric": "serving_open_loop_polling_goodput_rps",
                        "value": poll["goodput_rps"], "unit": "req/s",
                        "arrival": arrival, "rate": rate})
    doc = {"schema": "mmlspark-bench/v1", "bench": "serving_open_loop",
           "backend": jax.default_backend(), "metrics": metrics}
    print(json.dumps(doc))
    return doc


def chaos_serve_main(rate: float = 300.0, duration: float = 8.0,
                     deadline: float = 0.5, pool: int = 48,
                     smoke: bool = False, seed: int = 0):
    """The elastic-serving chaos scenario: one bursty open-loop load
    against the SLO-driven autoscaled fleet, with a throttled-straggler
    window and a mid-run worker kill -9 layered on top. The fleet must
    GROW under the spike (new workers warm from the AOT bundle — zero
    compiles), reconcile the killed worker back into the same lineage,
    and SHRINK by graceful drain once the load ends. Emits
    ``serving_chaos_{recovery_seconds,goodput_rps}`` in one
    mmlspark-bench/v1 doc for the perf gate."""
    import tempfile
    import urllib.request
    import jax
    from mmlspark_tpu import telemetry
    from mmlspark_tpu.io.http.fleet import ProcessHTTPSource, _Worker
    from mmlspark_tpu.io.http.worker import WorkerServer
    from mmlspark_tpu.io.serving import (BucketPolicy, FusedServingStep,
                                         save_bundle)
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.resilience import faults
    from mmlspark_tpu.resilience.autoscale import ServingAutoscaler
    from mmlspark_tpu.resilience.reconciler import FleetReconciler
    from mmlspark_tpu.telemetry.slo import SLOEngine
    from mmlspark_tpu.telemetry.timeseries import TimeSeriesSampler

    telemetry.enable()
    cfg = ({"type": "convnet", "channels": (4, 4), "dense": 16,
            "num_classes": 10} if smoke
           else {"type": "resnet", "num_classes": 10})
    module = build_model(cfg)
    params = module.init(jax.random.PRNGKey(0),
                         np.zeros((1, 32, 32, 3), np.float32))
    step = FusedServingStep(cfg, params,
                            policy=BucketPolicy(max_batch=64,
                                                min_bucket=8),
                            row_shape=(32, 32, 3), in_dtype=np.uint8,
                            output="argmax")
    bundle_dir = tempfile.mkdtemp(prefix="serving_chaos_bundle_")
    save_bundle(bundle_dir, step)

    def compiles():
        snap = telemetry.snapshot()
        return sum(s["value"] for s in snap.get(
            "mmlspark_profiler_compiles", {}).get("series", []))

    compiles0 = compiles()
    # in-process bundle workers: the warm-start + drain semantics of the
    # subprocess fleet without paying a JAX import per spawned replica
    servers: list = []

    def spawn(wi, old):
        if old is not None:
            for ws in servers:
                if ws.control_port == old.control:
                    try:
                        ws.close()
                    except Exception:
                        pass
        ws = WorkerServer("127.0.0.1",
                          port=old.port if old is not None else 0,
                          control_port=old.control if old is not None
                          else 0, bundle=bundle_dir)
        servers.append(ws)
        return _Worker("127.0.0.1", ws.source.port, ws.control_port,
                       spawn=False)

    source = ProcessHTTPSource(workers=[spawn(0, None)])
    sampler = TimeSeriesSampler(interval=0.2).start()
    slo = SLOEngine([{"name": "serve-latency", "kind": "latency",
                      "hist": "mmlspark_http_request_seconds",
                      "threshold_s": deadline / 5.0, "target": 0.99,
                      "windows": (0.8, 1.6)}], sampler=sampler)
    rec = FleetReconciler(source, 1, spawn=spawn, min_workers=1,
                          max_workers=3, interval=0.05,
                          probe_interval=0.05,
                          drain_timeout=15.0).start()
    rec.supervisor.probe_timeout = 0.5
    rec.supervisor.restart_backoff = 0.05
    asc = ServingAutoscaler(slo, rec, grow_window=0.4,
                            shrink_window=2.0, cooldown=1.0,
                            idle_rows_per_worker=0.5,
                            interval=0.1).start()

    rng = np.random.default_rng(seed)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())
    schedule = arrival_times("bursty", rate, duration, seed=seed)
    pick = {"i": 0}

    def url():
        urls = source.urls
        if not urls:
            return f"http://127.0.0.1:{source.workers[0].port}/"
        pick["i"] += 1
        return urls[pick["i"] % len(urls)]

    recovery = {"s": None}

    def scenario():
        # straggler window: the serving path slows (alive, just slow)
        time.sleep(duration * 0.3)
        faults.configure("serving.batch:delay:0.5:0.05", seed=seed)
        time.sleep(duration * 0.2)
        faults.clear()
        # kill -9 worker 0 mid-load; recovery = kill -> same URL serves
        port0 = source.workers[0].port
        servers[0].close()
        t_kill = time.perf_counter()
        dead_url = f"http://127.0.0.1:{port0}/"
        deadline_t = time.monotonic() + 30
        while time.monotonic() < deadline_t:
            try:
                req = urllib.request.Request(dead_url, data=payload)
                with urllib.request.urlopen(req, timeout=1.0) as r:
                    if r.status == 200:
                        recovery["s"] = time.perf_counter() - t_kill
                        return
            except Exception:
                time.sleep(0.05)

    chaos = threading.Thread(target=scenario)
    chaos.start()
    result = run_open_loop(url, payload, schedule, deadline, pool)
    chaos.join(timeout=60)

    # idle: the fleet shrinks back to the floor by graceful drain
    deadline_t = time.monotonic() + 20
    while not (rec.observed() == 1 and rec.converged()) \
            and time.monotonic() < deadline_t:
        time.sleep(0.1)
    snap = telemetry.snapshot()

    def total(name):
        return sum(s["value"] for s in snap.get(name, {}).get(
            "series", []))

    verdicts = {tuple(sorted(s["labels"].items()))[0][1]: s["value"]
                for s in snap.get("mmlspark_autoscale_verdicts",
                                  {}).get("series", [])}
    headline = {
        "metric": "serving_chaos", "arrival": "bursty", "rate": rate,
        **result,
        "recovery_s": (None if recovery["s"] is None
                       else round(recovery["s"], 2)),
        "grow_verdicts": int(verdicts.get("grow", 0)),
        "shrink_verdicts": int(verdicts.get("shrink", 0)),
        "workers_retired": int(total("mmlspark_fleet_workers_retired")),
        "final_workers": rec.observed(),
        "compiles_during_traffic": int(compiles() - compiles0),
    }
    print(json.dumps(headline))
    asc.stop()
    rec.stop()
    sampler.stop()
    for ws in servers:
        try:
            ws.close()
        except Exception:
            pass
    source.close()
    faults.clear()
    telemetry.disable()
    metrics = [{"metric": "serving_chaos_goodput_rps",
                "value": result["goodput_rps"], "unit": "req/s",
                "arrival": "bursty", "rate": rate},
               {"metric": "serving_chaos_recovery_seconds",
                "value": headline["recovery_s"], "unit": "s"}]
    doc = {"schema": "mmlspark-bench/v1", "bench": "serving_chaos",
           "backend": jax.default_backend(), "metrics": metrics}
    print(json.dumps(doc))
    return doc


def main():
    import requests
    from mmlspark_tpu.io.http import serve_pipeline

    rng = np.random.default_rng(0)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())

    scorer = _ImageScorer()
    source, loop = serve_pipeline(scorer, max_batch=256,
                                  prepare=scorer.prepare)
    try:
        # warmup (compile)
        r = requests.post(source.url, data=payload, timeout=120)
        assert r.status_code == 200, r.text

        headline = None
        for clients, per_client in ((4, 50), (16, 50), (64, 25)):
            lat: list[float] = []
            failures: list[str] = []
            lock = threading.Lock()

            def worker():
                mine, bad = [], []
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    r = requests.post(source.url, data=payload, timeout=60)
                    mine.append(time.perf_counter() - t0)
                    if r.status_code != 200:
                        bad.append(f"{r.status_code}: {r.text[:120]}")
                with lock:
                    lat.extend(mine)
                    failures.extend(bad)

            threads = [threading.Thread(target=worker)
                       for _ in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if failures:   # fail loudly; never print numbers over a
                raise RuntimeError(  # silently shrunken sample
                    f"{len(failures)} failed requests, e.g. {failures[0]}")
            assert len(lat) == clients * per_client
            lat_ms = np.sort(np.array(lat)) * 1e3
            result = {
                "metric": "serving_resnet20_http",
                "clients": clients,
                "throughput_rps": round(len(lat) / wall, 1),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            }
            print(json.dumps(result))
            headline = result
        return headline
    finally:
        loop.stop()
        source.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--chaos", action="store_true",
                    help="fleet chaos mode: 10%% injected poll faults + "
                         "one mid-run worker kill; reports p50/p99 and "
                         "recovery time")
    ap.add_argument("--fault-rate", type=float, default=0.1)
    ap.add_argument("--clients", type=int, default=8,
                    help="concurrent chaos/trace clients")
    ap.add_argument("--per-client", type=int, default=30,
                    help="requests per chaos/trace client")
    ap.add_argument("--trace", action="store_true",
                    help="distributed-tracing mode: runs the fleet "
                         "scenario with per-process span capture and "
                         "merges every hop into serving_trace.jsonl "
                         "(one trace_id per request; combine with "
                         "--chaos for the fault-injected run)")
    ap.add_argument("--chaos-serve", action="store_true",
                    help="elastic-fleet chaos scenario: bursty spike + "
                         "throttled straggler + worker kill -9 against "
                         "the SLO-driven autoscaled fleet; reports "
                         "goodput, recovery seconds, grow/shrink "
                         "verdicts and emits an mmlspark-bench/v1 doc "
                         "(serving_chaos_*) for the perf gate")
    ap.add_argument("--open-loop", action="store_true",
                    help="open-loop arrival benchmark: polling loop vs "
                         "continuous-batching engine over the same "
                         "Poisson/bursty schedule; reports goodput + "
                         "p50/p99/p999 and emits an mmlspark-bench/v1 "
                         "doc for the perf gate")
    ap.add_argument("--rate", type=float, default=500.0,
                    help="open-loop mean arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="open-loop schedule length (s)")
    ap.add_argument("--arrival", default="poisson",
                    choices=("poisson", "bursty"))
    ap.add_argument("--deadline-ms", type=float, default=1000.0,
                    help="goodput SLO: a reply counts only if it is a "
                         "200 within this many ms of its scheduled "
                         "arrival")
    ap.add_argument("--pool", type=int, default=64,
                    help="open-loop client pool size (the offered-"
                         "concurrency bound)")
    ap.add_argument("--max-wait", type=float, default=0.005,
                    help="continuous batcher max-wait deadline (s)")
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny convnet + short schedule (CPU CI "
                         "validation of the open-loop harness)")
    args = ap.parse_args()
    if args.chaos_serve:
        chaos_serve_main(rate=args.rate, duration=args.duration,
                         deadline=args.deadline_ms / 1e3,
                         pool=args.pool, smoke=args.smoke)
    elif args.open_loop:
        open_loop_main(rate=args.rate, duration=args.duration,
                       arrival=args.arrival,
                       deadline=args.deadline_ms / 1e3, pool=args.pool,
                       smoke=args.smoke, max_batch=args.max_batch,
                       max_wait=args.max_wait)
    elif args.chaos or args.trace:
        chaos_main(fault_rate=args.fault_rate if args.chaos else 0.0,
                   clients=args.clients, per_client=args.per_client,
                   trace=args.trace)
    else:
        main()
