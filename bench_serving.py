"""Serving benchmark: HTTP -> continuous batching -> pjit inference on the
real chip (the reference's serving story is DistributedHTTPSource feeding
CNTKModel, SURVEY.md §2.4/§3.5 — no published latency/throughput numbers).

Measures end-to-end client-observed latency (p50/p99) and sustained
throughput for a ResNet-20 scorer behind `serve_pipeline`, with uint8 image
payloads (the wire format TpuModel.transferDtype optimizes). Prints one
JSON line per load level; the last line is the headline.
"""

import base64
import json
import threading
import time

import numpy as np


class _ImageScorer:
    """(id, value) -> reply: decode base64 uint8 image batch, score.

    ``prepare`` (the per-row base64 decode + feature assembly) is split
    from ``transform`` (the pjit score) so the serving loop's prefetch
    thread decodes the NEXT micro-batch while the current one runs on
    device."""

    def __init__(self):
        import jax
        from mmlspark_tpu.models import TpuModel, build_model
        cfg = {"type": "resnet", "num_classes": 10}
        module = build_model(cfg)
        params = module.init(jax.random.PRNGKey(0),
                             np.zeros((1, 32, 32, 3), np.float32))
        self.model = (TpuModel().setModelConfig(cfg).setModelParams(params)
                      .setInputCol("features").setTransferDtype("bfloat16")
                      .setInputShape((3, 32, 32)))
        from mmlspark_tpu import DataFrame
        from mmlspark_tpu.core.utils import object_column
        ex = DataFrame({"features": object_column(
            [np.zeros(32 * 32 * 3, np.float32)])})
        self.model.warmup(ex, max_rows=256)  # no request pays a compile

    def prepare(self, df):
        from mmlspark_tpu.core.utils import object_column
        imgs = [np.frombuffer(base64.b64decode(v), dtype=np.uint8)
                .reshape(32, 32, 3).astype(np.float32).ravel()
                for v in df.col("value")]
        return df.withColumn("features", object_column(imgs))

    def transform(self, df):
        from mmlspark_tpu.core.utils import object_column
        scored = self.model.transform(df)
        replies = [json.dumps({"label": int(np.argmax(s))})
                   for s in scored.col("scores")]
        return scored.withColumn("reply", object_column(replies))


def main():
    import requests
    from mmlspark_tpu.io.http import serve_pipeline

    rng = np.random.default_rng(0)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())

    scorer = _ImageScorer()
    source, loop = serve_pipeline(scorer, max_batch=256,
                                  prepare=scorer.prepare)
    try:
        # warmup (compile)
        r = requests.post(source.url, data=payload, timeout=120)
        assert r.status_code == 200, r.text

        headline = None
        for clients, per_client in ((4, 50), (16, 50), (64, 25)):
            lat: list[float] = []
            failures: list[str] = []
            lock = threading.Lock()

            def worker():
                mine, bad = [], []
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    r = requests.post(source.url, data=payload, timeout=60)
                    mine.append(time.perf_counter() - t0)
                    if r.status_code != 200:
                        bad.append(f"{r.status_code}: {r.text[:120]}")
                with lock:
                    lat.extend(mine)
                    failures.extend(bad)

            threads = [threading.Thread(target=worker)
                       for _ in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if failures:   # fail loudly; never print numbers over a
                raise RuntimeError(  # silently shrunken sample
                    f"{len(failures)} failed requests, e.g. {failures[0]}")
            assert len(lat) == clients * per_client
            lat_ms = np.sort(np.array(lat)) * 1e3
            result = {
                "metric": "serving_resnet20_http",
                "clients": clients,
                "throughput_rps": round(len(lat) / wall, 1),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            }
            print(json.dumps(result))
            headline = result
        return headline
    finally:
        loop.stop()
        source.close()


if __name__ == "__main__":
    main()
