"""Serving benchmark: HTTP -> continuous batching -> pjit inference on the
real chip (the reference's serving story is DistributedHTTPSource feeding
CNTKModel, SURVEY.md §2.4/§3.5 — no published latency/throughput numbers).

Measures end-to-end client-observed latency (p50/p99) and sustained
throughput for a ResNet-20 scorer behind `serve_pipeline`, with uint8 image
payloads (the wire format TpuModel.transferDtype optimizes). Prints one
JSON line per load level; the last line is the headline.

``--chaos`` runs the resilience scenario instead: the PROCESS fleet
(`serve_fleet` + FleetSupervisor) under a 10% injected `fleet.poll` error
rate plus one mid-run worker kill. Clients post through a RetryPolicy (the
documented client contract under worker loss) and the report adds
`recovery_s` — wall time from the kill until the restarted worker's URL
serves a request again — plus the retry/restart counters.
"""

import argparse
import base64
import json
import threading
import time

import numpy as np


class _ImageScorer:
    """(id, value) -> reply: decode base64 uint8 image batch, score.

    ``prepare`` (the per-row base64 decode + feature assembly) is split
    from ``transform`` (the pjit score) so the serving loop's prefetch
    thread decodes the NEXT micro-batch while the current one runs on
    device."""

    def __init__(self):
        import jax
        from mmlspark_tpu.models import TpuModel, build_model
        cfg = {"type": "resnet", "num_classes": 10}
        module = build_model(cfg)
        params = module.init(jax.random.PRNGKey(0),
                             np.zeros((1, 32, 32, 3), np.float32))
        self.model = (TpuModel().setModelConfig(cfg).setModelParams(params)
                      .setInputCol("features").setTransferDtype("bfloat16")
                      .setInputShape((3, 32, 32)))
        from mmlspark_tpu import DataFrame
        from mmlspark_tpu.core.utils import object_column
        ex = DataFrame({"features": object_column(
            [np.zeros(32 * 32 * 3, np.float32)])})
        self.model.warmup(ex, max_rows=256)  # no request pays a compile

    def prepare(self, df):
        from mmlspark_tpu.core.utils import object_column
        imgs = [np.frombuffer(base64.b64decode(v), dtype=np.uint8)
                .reshape(32, 32, 3).astype(np.float32).ravel()
                for v in df.col("value")]
        return df.withColumn("features", object_column(imgs))

    def transform(self, df):
        from mmlspark_tpu.core.utils import object_column
        scored = self.model.transform(df)
        replies = [json.dumps({"label": int(np.argmax(s))})
                   for s in scored.col("scores")]
        return scored.withColumn("reply", object_column(replies))


class _ChaosScorer(_ImageScorer):
    """Fleet transformer: prepare + transform fused (the ReplayServingLoop
    has no separate prepare stage)."""

    def transform(self, df):
        return super().transform(self.prepare(df))


def chaos_main(fault_rate: float = 0.1, clients: int = 8,
               per_client: int = 30, trace: bool = False):
    """Fleet chaos run: injected poll faults + one worker kill mid-run.
    ``trace=True`` additionally enables distributed tracing in every
    process (workers inherit MMLSPARK_TPU_TELEMETRY), collects each
    process's span buffer at the end, and merges them into one
    per-request Chrome trace (serving_trace.jsonl)."""
    import os
    import tempfile
    from mmlspark_tpu import telemetry
    from mmlspark_tpu.io.http.fleet import serve_fleet
    from mmlspark_tpu.resilience import faults
    from mmlspark_tpu.resilience.policy import RetryPolicy
    import urllib.request

    telemetry.enable()
    if trace:
        # spawned worker processes read the env at import — this is how
        # their ingress spans (and the traceparent envelope) turn on
        os.environ["MMLSPARK_TPU_TELEMETRY"] = "1"
    if fault_rate > 0:
        faults.configure(f"fleet.poll:error:{fault_rate}", seed=0)
    rng = np.random.default_rng(0)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())

    source, loop = serve_fleet(_ChaosScorer(), n_workers=2, supervise=True,
                               probe_interval=0.1)
    urls = [w.url for w in source.workers]

    def post(url, timeout=30.0):
        req = urllib.request.Request(url, data=payload)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            assert r.status == 200, r.status
            return r.read()

    try:
        post(urls[0], timeout=180)       # warmup: compile on worker 0
        post(urls[1], timeout=60)

        lat: list = []
        failures: list = []
        lock = threading.Lock()

        def worker(ci):
            policy = RetryPolicy(name="bench.client", max_attempts=60,
                                 base_delay=0.05, max_delay=0.5,
                                 deadline=60.0, seed=ci)
            mine, bad = [], []
            for _ in range(per_client):
                t0 = time.perf_counter()
                try:
                    policy.run(lambda _a: post(urls[ci % 2], timeout=5.0))
                    mine.append(time.perf_counter() - t0)
                except Exception as e:
                    bad.append(repr(e))
            with lock:
                lat.extend(mine)
                failures.extend(bad)

        threads = [threading.Thread(target=worker, args=(ci,))
                   for ci in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(1.0)
        t_kill = time.perf_counter()
        source.killWorker(0)             # the mid-run worker kill
        # recovery = kill -> the same URL serves again (supervisor restart)
        recovery = None
        deadline = time.monotonic() + 60
        while recovery is None and time.monotonic() < deadline:
            try:
                post(urls[0], timeout=2.0)
                recovery = time.perf_counter() - t_kill
            except Exception:
                time.sleep(0.05)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if failures:
            raise RuntimeError(f"{len(failures)} lost requests under "
                               f"chaos, e.g. {failures[0]}")
        snap = telemetry.snapshot()

        def total(name):
            return sum(s["value"]
                       for s in snap.get(name, {}).get("series", []))

        lat_ms = np.sort(np.array(lat)) * 1e3
        result = {
            "metric": "serving_resnet20_fleet_chaos",
            "fault_rate": fault_rate,
            "clients": clients,
            "requests": len(lat),
            "lost": 0,
            "throughput_rps": round(len(lat) / wall, 1),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            "recovery_s": None if recovery is None else round(recovery, 2),
            "faults_injected": total("mmlspark_faults_injected_total"),
            "retries": total("mmlspark_retry_attempts_total"),
            "worker_restarts": total(
                "mmlspark_supervisor_worker_restarts_total"),
        }
        if trace:
            # one Chrome-trace file per process -> one merged per-request
            # tree: every hop of a request shares its trace_id, spans
            # nest via parent_span_id (load in Perfetto)
            tdir = tempfile.mkdtemp(prefix="fleet_trace_")
            paths = source.collect_traces(tdir)
            out = "serving_trace.jsonl"
            merged = telemetry.merge_traces(paths, out)
            traced = {(e.get("args") or {}).get("trace_id")
                      for e in merged} - {None}
            result.update(trace_file=out, trace_events=len(merged),
                          trace_processes=len(paths),
                          requests_traced=len(traced))
        print(json.dumps(result))
        return result
    finally:
        loop.stop()
        faults.clear()
        telemetry.disable()


def main():
    import requests
    from mmlspark_tpu.io.http import serve_pipeline

    rng = np.random.default_rng(0)
    payload = base64.b64encode(
        rng.integers(0, 256, 32 * 32 * 3, dtype=np.uint8).tobytes())

    scorer = _ImageScorer()
    source, loop = serve_pipeline(scorer, max_batch=256,
                                  prepare=scorer.prepare)
    try:
        # warmup (compile)
        r = requests.post(source.url, data=payload, timeout=120)
        assert r.status_code == 200, r.text

        headline = None
        for clients, per_client in ((4, 50), (16, 50), (64, 25)):
            lat: list[float] = []
            failures: list[str] = []
            lock = threading.Lock()

            def worker():
                mine, bad = [], []
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    r = requests.post(source.url, data=payload, timeout=60)
                    mine.append(time.perf_counter() - t0)
                    if r.status_code != 200:
                        bad.append(f"{r.status_code}: {r.text[:120]}")
                with lock:
                    lat.extend(mine)
                    failures.extend(bad)

            threads = [threading.Thread(target=worker)
                       for _ in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if failures:   # fail loudly; never print numbers over a
                raise RuntimeError(  # silently shrunken sample
                    f"{len(failures)} failed requests, e.g. {failures[0]}")
            assert len(lat) == clients * per_client
            lat_ms = np.sort(np.array(lat)) * 1e3
            result = {
                "metric": "serving_resnet20_http",
                "clients": clients,
                "throughput_rps": round(len(lat) / wall, 1),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            }
            print(json.dumps(result))
            headline = result
        return headline
    finally:
        loop.stop()
        source.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--chaos", action="store_true",
                    help="fleet chaos mode: 10%% injected poll faults + "
                         "one mid-run worker kill; reports p50/p99 and "
                         "recovery time")
    ap.add_argument("--fault-rate", type=float, default=0.1)
    ap.add_argument("--clients", type=int, default=8,
                    help="concurrent chaos/trace clients")
    ap.add_argument("--per-client", type=int, default=30,
                    help="requests per chaos/trace client")
    ap.add_argument("--trace", action="store_true",
                    help="distributed-tracing mode: runs the fleet "
                         "scenario with per-process span capture and "
                         "merges every hop into serving_trace.jsonl "
                         "(one trace_id per request; combine with "
                         "--chaos for the fault-injected run)")
    args = ap.parse_args()
    if args.chaos or args.trace:
        chaos_main(fault_rate=args.fault_rate if args.chaos else 0.0,
                   clients=args.clients, per_client=args.per_client,
                   trace=args.trace)
    else:
        main()
