#!/usr/bin/env bash
# One-command verification of the OPTIONAL host integrations — run this on
# any machine that has real pyspark and/or R to upgrade the CI claims from
# shim-verified to host-verified (this zero-egress CI image has neither;
# see README "Integration evidence tiers").
#
#   bash tools/verify_host_integrations.sh            # runs what the host has
#
# Exit code 0 = everything present on this host passed; each missing
# integration is reported and skipped (not a failure) so the script is
# safe in any environment.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
PY="$(command -v python3 || command -v python)"
fail=0

echo "== pyspark integration =="
if "$PY" -c "import pyspark" 2>/dev/null; then
  "$PY" -c "import pyspark; print('pyspark', pyspark.__version__)"
  # the full adapter suite (incl. the barrier-stage distributed fit and
  # the Spark-driven serving stream) against REAL pyspark
  MMLTPU_TESTS=extended "$PY" -m pytest -q \
      tests/test_spark_adapter.py tests/test_spark_streaming.py \
      || fail=1
  # the literal spark-submit E2E (driver-side fit + executor transforms +
  # barrier-stage distributed fit demo)
  SUBMIT="$(command -v spark-submit || true)"
  if [ -z "$SUBMIT" ]; then
    SUBMIT="$("$PY" - <<'PY'
import os, pyspark
p = os.path.join(os.path.dirname(pyspark.__file__), "bin", "spark-submit")
print(p if os.path.exists(p) else "")
PY
)"
  fi
  if [ -n "$SUBMIT" ]; then
    PYTHONPATH="$REPO" "$SUBMIT" --master 'local[2]' \
        examples/spark_submit_101.py || fail=1
  else
    echo "spark-submit launcher not found; ran the pytest tier only"
  fi
else
  echo "pyspark not installed - SKIPPED (shim-verified only on this host)"
fi

echo "== R integration =="
if command -v Rscript >/dev/null 2>&1; then
  Rscript --version
  # executes the generated R wrappers end-to-end (tests/test_codegen.py
  # skips itself without Rscript)
  MMLTPU_TESTS=extended "$PY" -m pytest -q tests/test_codegen.py \
      || fail=1
else
  echo "Rscript not installed - SKIPPED (wrappers generated+linted only)"
fi

if [ "$fail" -eq 0 ]; then
  echo "HOST_INTEGRATIONS_OK"
else
  echo "HOST_INTEGRATIONS_FAILED"
fi
exit "$fail"
