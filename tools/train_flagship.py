"""Flagship ACCURACY run: train ResNet-20 from scratch on the richest
real 32x32 corpus available offline and report held-out accuracy.

`bench.py` proves the flagship path's SPEED on synthetic pixels; this
script proves it LEARNS — the reference's closest analog is notebook
401's CIFAR ConvNet demonstration. The corpus is all 10 classes of
sklearn's UCI handwritten-digit scans (the only real image data a
zero-egress image ships), split train/test at the ORIGINAL-scan level
and augmented to ~50k rows with label-preserving transforms
(testing.datagen.digits_rgb32_augmented); the held-out set is untouched
original scans. The committed number lives in BASELINE.md.

Reproduce (runs on the attached TPU; CPU works but is slow):

    python tools/train_flagship.py              # ~50k rows, 12 epochs
    python tools/train_flagship.py --total 20000 --epochs 8   # quicker
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--total", type=int, default=50_000,
                    help="augmented training rows")
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from mmlspark_tpu.testing.datagen import digits_rgb32_augmented
    t0 = time.perf_counter()
    xt, yt, xe, ye = digits_rgb32_augmented(total=args.total,
                                            seed=args.seed)
    t_corpus = time.perf_counter() - t0
    print(f"corpus: {len(xt)} augmented train rows from "
          f"{len(np.unique(yt))}-class real scans, {len(xe)} held-out "
          f"ORIGINAL scans ({t_corpus:.1f}s to build)")

    sys.path.insert(0, os.path.join(REPO, "tools"))
    from build_zoo import train_and_eval
    t0 = time.perf_counter()
    _, acc = train_and_eval({"type": "resnet", "num_classes": 10},
                            xt, yt, xe, ye, epochs=args.epochs,
                            batch=args.batch, lr=args.lr, seed=args.seed)
    t_train = time.perf_counter() - t0
    print(json.dumps({
        "metric": "resnet20_real_digits10_heldout_accuracy",
        "value": round(acc, 4),
        "unit": f"accuracy on {len(xe)} untouched original scans "
                f"(train {t_train:.0f}s, {len(xt)} rows x "
                f"{args.epochs} epochs)",
        "vs_baseline": None,
    }))
    return 0 if acc > 0.97 else 1


if __name__ == "__main__":
    sys.exit(main())
