"""Round-5 probe: MXU node-histogram kernel prototype vs existing backends.

Timing: chained lax.fori_loop with data-dependent iterations + one scalar
fetch (the axon tunnel's block_until_ready is unreliable; see BASELINE.md
round-4 methodology).
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _split3(a):
    """Exact-ish 3-way bf16 split of f32: a ~= hi + mid + lo."""
    hi = a.astype(jnp.bfloat16)
    r1 = a - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    r2 = r1 - mid.astype(jnp.float32)
    lo = r2.astype(jnp.bfloat16)
    return hi, mid, lo


def _nh_kernel(bins_ref, node_ref, g_ref, h_ref, hg_ref, hh_ref, *,
               n_nodes: int, n_feat: int, width: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        hg_ref[:] = jnp.zeros_like(hg_ref)
        hh_ref[:] = jnp.zeros_like(hh_ref)

    node = node_ref[:]                       # (bn, 1) int32
    g = g_ref[:]                             # (bn, 1) f32
    h = h_ref[:]
    node1h = (node == jax.lax.broadcasted_iota(
        jnp.int32, (node.shape[0], n_nodes), 1))
    ag = jnp.where(node1h, g, 0.0)           # (bn, n_nodes) f32
    ah = jnp.where(node1h, h, 0.0)
    a = jnp.concatenate([ag, ah], axis=1)    # (bn, 2*n_nodes)
    hi, mid, lo = _split3(a)
    A = jnp.concatenate([hi, mid, lo], axis=1)   # (bn, 6*n_nodes) bf16

    for f in range(n_feat):
        bf = bins_ref[:, f][:, None]         # (bn, 1) int32
        B = (bf == jax.lax.broadcasted_iota(
            jnp.int32, (bf.shape[0], width), 1)).astype(jnp.bfloat16)
        out = jax.lax.dot_general(
            A, B, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # (6n, width)
        out = out.reshape(3, 2 * n_nodes, width).sum(axis=0)
        hg_ref[f * n_nodes:(f + 1) * n_nodes, :] += out[:n_nodes]
        hh_ref[f * n_nodes:(f + 1) * n_nodes, :] += out[n_nodes:]


def node_histogram(bins, node, g, h, *, n_nodes: int, n_bins: int = 256,
                   block_n: int = 2048, interpret=False):
    """bins (N,F) int32, node (N,) int32, g/h (N,) f32 ->
    (hg, hh) each (F, n_nodes, n_bins) f32."""
    N, F = bins.shape
    width = max(128, -(-n_bins // 128) * 128)
    pad = (-N) % block_n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node = jnp.pad(node, (0, pad), constant_values=n_nodes)  # no-op slot
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
    nblk = bins.shape[0] // block_n
    kernel = functools.partial(_nh_kernel, n_nodes=n_nodes, n_feat=F,
                               width=width)
    hg, hh = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((block_n, F), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=(pl.BlockSpec((F * n_nodes, width), lambda i: (0, 0)),
                   pl.BlockSpec((F * n_nodes, width), lambda i: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((F * n_nodes, width), jnp.float32),
                   jax.ShapeDtypeStruct((F * n_nodes, width), jnp.float32)),
        interpret=interpret,
    )(bins.astype(jnp.int32), node.astype(jnp.int32)[:, None],
      g.astype(jnp.float32)[:, None], h.astype(jnp.float32)[:, None])
    return (hg.reshape(F, n_nodes, width)[..., :n_bins],
            hh.reshape(F, n_nodes, width)[..., :n_bins])


def timed(fn, *args, iters=10, label=""):
    """Chained fori_loop: data-dependent iterations, one scalar sync."""
    @jax.jit
    def loop(args_, salt):
        def body(i, carry):
            s, = carry
            # salt the grad so no iteration can be CSE'd away
            out = fn(*args_[:-1], args_[-1] + s * 1e-30)
            s2 = jax.tree_util.tree_reduce(
                lambda acc, x: acc + x.astype(jnp.float32).sum(), out, 0.0)
            return (s2 * 1e-30,)
        return jax.lax.fori_loop(0, iters, body, (salt,))[0]

    r = float(loop(args, jnp.float32(0.0)))  # compile+warm
    t0 = time.perf_counter()
    r = float(loop(args, jnp.float32(r)))
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:48s} {dt*1e3:9.2f} ms/call")
    return dt


def main():
    import os
    aux_only = os.environ.get("PROBE_AUX_ONLY") == "1"
    N, F = 1_000_000, 28
    rng = np.random.default_rng(0)
    bins_np = rng.integers(0, 256, (N, F), dtype=np.uint8)
    g_np = rng.normal(size=N).astype(np.float32)
    h_np = rng.random(N).astype(np.float32)

    bins_u8 = jnp.asarray(bins_np)
    bins_i32 = jnp.asarray(bins_np.astype(np.int32))
    g = jnp.asarray(g_np)
    h = jnp.asarray(h_np)

    import sys
    sys.path.insert(0, "/root/repo")
    from mmlspark_tpu.ops.pallas_kernels import (compare_reduce_histogram,
                                                 segment_histogram)

    for n_nodes in () if aux_only else (1, 2, 16):
        node_np = rng.integers(0, n_nodes, N, dtype=np.int32)
        node = jnp.asarray(node_np)

        # correctness vs segment (reference)
        comb = node[:, None] * 256 + bins_i32
        ref_g, ref_h = segment_histogram(comb, g, h, n_bins=n_nodes * 256)
        ref_g = ref_g.reshape(F, n_nodes, 256)
        hg, hh = node_histogram(bins_i32, node, g, h, n_nodes=n_nodes)
        err = float(jnp.max(jnp.abs(hg - ref_g)))
        rel = err / float(jnp.max(jnp.abs(ref_g)))
        print(f"n_nodes={n_nodes}: max abs err {err:.3e} rel {rel:.3e}")

        timed(lambda b, nd, gg: node_histogram(b, nd, gg, h,
                                               n_nodes=n_nodes),
              bins_i32, node, g,
              label=f"mxu node_histogram n_nodes={n_nodes}")
        timed(lambda c, gg: segment_histogram(c, gg, h,
                                              n_bins=n_nodes * 256),
              comb, g, label=f"segment_sum ids={n_nodes*256}")
        if n_nodes == 1:
            timed(lambda b, gg: compare_reduce_histogram(b, gg, h,
                                                         n_bins=256),
                  bins_u8, g, label="compare_reduce ids=256")

    # block sweep for the best n_nodes=16 config
    node = jnp.asarray(rng.integers(0, 16, N, dtype=np.int32))
    for bn in () if aux_only else (1024, 2048, 4096, 8192):
        try:
            timed(lambda b, nd, gg: node_histogram(b, nd, gg, h, n_nodes=16,
                                                   block_n=bn),
                  bins_i32, node, g, label=f"mxu n=16 block_n={bn}")
        except Exception as e:
            print(f"block_n={bn}: {type(e).__name__}: {str(e)[:120]}")

    # aux op costs at 1M (last arg is the salted f32 array)
    timed(lambda b, nd, gg: (jnp.take_along_axis(
        b, (nd + gg[:1].astype(jnp.int32))[:, None] % F, axis=1)[:, 0]
        > 128,),
          bins_i32, node, g, label="routing gather take_along_axis")

    def route_cols(b, nd, gg):
        # per-node column compare: (n, n_nodes) matrix then select by node
        cols = jnp.stack([b[:, k % F] for k in range(16)], axis=1)
        thr = gg[:16].astype(jnp.int32)
        m = cols > thr[None, :]
        return (jnp.take_along_axis(m, nd[:, None] % 16, axis=1)[:, 0],)
    timed(route_cols, bins_i32, node, g,
          label="routing via 16 column compares")
    leaf_tbl = jnp.asarray(rng.normal(size=32).astype(np.float32))

    def leaf_sums_onehot(nd, gg):
        oh = (nd[:, None] == jnp.arange(32)).astype(jnp.float32)
        return (oh.T @ gg[:, None],)
    timed(leaf_sums_onehot, node, g, label="leaf sums one-hot matmul (32)")
    timed(lambda nd, gg: (jax.ops.segment_sum(gg, nd, num_segments=32),),
          node, g, label="leaf sums segment_sum (32)")
    timed(lambda nd, gg: (leaf_tbl[nd] * gg,), node, g,
          label="leaf gather leaf[node]")
    timed(lambda nd, gg: (jnp.nonzero(nd < 8, size=N // 2,
                                      fill_value=N)[0].astype(jnp.float32)
                          + gg[0],),
          node, g, label="nonzero(size=n/2) compaction index")
    # 10M-scale check of the kernel (linearity)
    N2 = 10_000_000
    bins2 = jnp.asarray(rng.integers(0, 256, (N2, F), dtype=np.uint8)
                        .astype(np.int32))
    node2 = jnp.asarray(rng.integers(0, 16, N2, dtype=np.int32))
    g2 = jnp.asarray(rng.normal(size=N2).astype(np.float32))
    h2 = jnp.asarray(rng.random(N2).astype(np.float32))
    timed(lambda b, nd, gg: node_histogram(b, nd, gg, h2, n_nodes=16),
          bins2, node2, g2, iters=5, label="mxu n_nodes=16 @ 10M")
    timed(lambda c, gg: segment_histogram(c, gg, h2, n_bins=16 * 256),
          node2[:, None] * 256 + bins2, g2, iters=3,
          label="segment ids=4096 @ 10M")


if __name__ == "__main__":
    main()
