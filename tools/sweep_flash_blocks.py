import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time
import jax
import jax.numpy as jnp
import numpy as np
from mmlspark_tpu.ops.pallas_kernels import flash_attention

B, T, H, D = 8, 4096, 4, 128   # same H*D=512 as the round-4 (8,4096,8,64)
rng = np.random.default_rng(0)
q = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32)).astype(jnp.bfloat16)
k = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32)).astype(jnp.bfloat16)
v = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32)).astype(jnp.bfloat16)

def tfs(dt, causal):
    fl = 4 * B * T * T * H * D * (0.5 if causal else 1.0)
    return fl / dt / 1e12

for causal in (True, False):
    for bq, bk in ((512, 512), (512, 1024), (1024, 512), (1024, 1024),
                   (2048, 512), (1024, 2048)):
        try:
            @jax.jit
            def loop(qx):
                def body(i, carry):
                    o = flash_attention(carry, k, v, causal, None, bq, bk)
                    return o * 1e-3 + carry * (1 - 1e-3)  # data dependence
                return jax.lax.fori_loop(0, 20, body, qx)
            r = loop(q)
            float(jnp.sum(r.astype(jnp.float32)))
            t0 = time.perf_counter()
            r = loop(q)
            float(jnp.sum(r.astype(jnp.float32)))
            dt = (time.perf_counter() - t0) / 20
            print(f"causal={causal} {bq}x{bk}: {dt*1e3:7.2f} ms "
                  f"{tfs(dt, causal):6.1f} TF/s", flush=True)
        except Exception as e:
            print(f"causal={causal} {bq}x{bk}: {type(e).__name__} "
                  f"{str(e)[:80]}", flush=True)
