#!/usr/bin/env bash
# TPU-VM provisioning (reference analog: tools/deployment/* ARM templates +
# docs/gpu-setup.md provision N-series GPU VMs for CNTK/MPI; here one gcloud
# call provisions a TPU slice and the JAX runtime needs no driver setup).
#
# Usage: tools/tpu-vm-setup.sh NAME [ZONE] [TYPE] [VERSION]
#   NAME     TPU VM name
#   ZONE     default us-central1-a
#   TYPE     default v5litepod-8   (one host, 8 chips — the bench target)
#   VERSION  default tpu-ubuntu2204-base
#   MMLTPU_DRYRUN=1 prints the gcloud commands instead of executing them
#   (what CI runs; no gcloud credentials needed).
set -euo pipefail

NAME="${1:?usage: tpu-vm-setup.sh NAME [ZONE] [TYPE] [VERSION]}"
ZONE="${2:-us-central1-a}"
TYPE="${3:-v5litepod-8}"
VERSION="${4:-tpu-ubuntu2204-base}"

run() {
  if [ -n "${MMLTPU_DRYRUN:-}" ]; then
    printf 'DRYRUN:'; printf ' %q' "$@"; printf '\n'
  else
    "$@"
  fi
}

run gcloud compute tpus tpu-vm create "$NAME" \
  --zone="$ZONE" --accelerator-type="$TYPE" --version="$VERSION"

# install the framework on every host of the slice (multi-host slices run
# the same command on each worker; the MMLTPU_* env contract in
# mmlspark_tpu.parallel.distributed handles rendezvous at run time)
run gcloud compute tpus tpu-vm ssh "$NAME" --zone="$ZONE" --worker=all --command='
  set -e
  python3 -m pip install -q "jax[tpu]" flax optax
  python3 -m pip install -q mmlspark-tpu  # or: pip install <wheel you scp>
  python3 -c "import jax; print(jax.devices())"
'
echo "TPU VM $NAME ready. Run jobs with tools/bin/mmltpu-run."
