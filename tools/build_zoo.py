"""Build the committed model zoo: train the pretrained nets, pack, index.

The reference ships a CDN repository of pretrained CNTK models with hashes
and layerNames (downloader/.../Schema.scala:54-72, DefaultModelRepo at
ModelDownloader.scala:109) that ImageFeaturizer consumes for transfer
learning. This zero-egress build publishes its own repository in ``zoo/``
(a LocalRepo directory that doubles as a RemoteRepo when served over HTTP:
MANIFEST + metas + blobs), with models trained on:

  * **digits8** — REAL data: sklearn's bundled UCI handwritten-digits
    corpus (1,797 scanned digits), classes 0-7, upscaled 8x8 -> 32x32 RGB.
    The classes 8/9 are deliberately HELD OUT of pretraining so e303 can
    demonstrate transfer to a genuinely unseen real downstream task.
    (CIFAR-10 — the reference notebooks' teacher — is not obtainable in
    this zero-egress environment; digits is the real-image corpus the
    environment ships.)
  * **shapes10** — the procedural corpus (`testing.datagen.make_shapes10`,
    deterministic from a seed, so the artifact is re-evaluable anywhere).

Run on a TPU host: ``python tools/build_zoo.py [--epochs 8]``. Rewrites
zoo/ and prints the held-out accuracies that go into zoo/README.md.
"""

import argparse
import hashlib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def train_and_eval(cfg, x, y, xv, yv, epochs, batch, lr=0.05, seed=0):
    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.core.schema import make_image_row
    from mmlspark_tpu.core.utils import object_column
    from mmlspark_tpu.models import TpuLearner

    size = x.shape[1]

    def frame(xa, ya):
        rows = object_column([make_image_row(f"s{i}", size, size, 3, xa[i])
                              for i in range(len(xa))])
        return DataFrame({"image": rows, "label": ya})

    learner = (TpuLearner().setFeaturesCol("image")
               .setModelConfig(cfg)
               .setEpochs(epochs)
               .setBatchSize(min(batch, max(32, len(x) // 8)))
               .setOptimizer("momentum").setLearningRate(lr).setSeed(seed))
    model = learner.fit(frame(x, y))
    out = model.setInputCol("image").transform(frame(xv, yv))
    preds = np.stack(list(out.col("scores"))).argmax(axis=1)
    return model, float((preds == yv).mean())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20000,
                    help="procedural shapes10 corpus size")
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--digits-epochs", type=int, default=80,
                    help="digits is small (1.4k rows); more epochs, same "
                         "wall-clock ballpark")
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--n224", type=int, default=6000,
                    help="224x224 augmented-corpus size (digits224 job)")
    ap.add_argument("--epochs224", type=int, default=30)
    ap.add_argument("--skip", nargs="*", default=(),
                    help="jobs to skip retraining, as Name or Name/dataset "
                         "(e.g. --skip ResNet32/digits8); a skipped job's "
                         "existing artifact, MANIFEST line, and README row "
                         "are preserved")
    ap.add_argument("--out", default=os.path.join(REPO, "zoo"))
    args = ap.parse_args()

    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.models.downloader import (LocalRepo, MANIFEST,
                                                ModelSchema,
                                                canonical_model_filename,
                                                pack_model)
    from mmlspark_tpu.testing.datagen import digits_rgb32, make_shapes10

    # ---- training jobs: (name, dataset, cfg, data, epochs, lr, note) ----
    xd, yd = digits_rgb32()
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(xd))
    n_tr = int(0.85 * len(xd))
    dig_train = (xd[perm[:n_tr]], yd[perm[:n_tr]])
    dig_val = (xd[perm[n_tr:]], yd[perm[n_tr:]])

    xs, ys = make_shapes10(args.n, seed=7)
    xsv, ysv = make_shapes10(4000, seed=8)

    jobs = [
        ("ResNet20", "digits8",
         {"type": "resnet", "num_classes": 8},
         dig_train, dig_val, args.digits_epochs, 0.05,
         "REAL sklearn/UCI handwritten digits, classes 0-7 "
         "(8x8 scans upscaled to 32x32; classes 8/9 held out for the "
         "e303 transfer task)"),
        ("ResNet32", "digits8",
         {"type": "resnet", "num_classes": 8, "blocks_per_stage": 5},
         dig_train, dig_val, args.digits_epochs, 0.05,
         "deeper truncatable backbone, same REAL digits corpus"),
        ("ResNet26b", "digits8",
         {"type": "resnet", "num_classes": 8, "block": "bottleneck",
          "blocks_per_stage": [2, 2, 2, 2],
          "widths": [64, 128, 256, 512]},
         # the wide bottleneck needs a gentler lr and longer schedule than
         # the basic-block nets (0.05/80ep plateaus at ~0.74 held-out;
         # 0.01/160ep reaches 1.00)
         dig_train, dig_val, args.digits_epochs * 2, 0.01,
         "BOTTLENECK backbone (the ResNet-50 block family the reference's "
         "ImageFeaturizer truncates, ImageFeaturizer.scala:117-142) on the "
         "same REAL digits corpus — exercises bottleneck-stage layer "
         "truncation with trained weights"),
        ("ResNet20", "shapes10",
         {"type": "resnet", "num_classes": 10},
         (xs, ys), (xsv, ysv), args.epochs, 0.05,
         "procedural corpus (`testing.datagen.make_shapes10`), "
         "deterministic from a seed"),
    ]

    if {"ResNet26b", "ResNet26b/digits224"} & set(args.skip):
        # placeholders only — the loop's skip check fires before training
        x224 = xv224 = np.empty((0, 224, 224, 3), np.uint8)
        y224 = yv224 = np.empty(0, np.int64)
    else:
        # the 224x224 ImageNet-resolution artifact (the reference's
        # ModelDownloader serves CDN nets at this input size); the corpus
        # is ~1 GB of uint8 at total=6000, so build it only when training
        from mmlspark_tpu.testing.datagen import digits_rgb224_augmented
        x224, y224, xv224, yv224 = digits_rgb224_augmented(total=args.n224)
    jobs.append(
        ("ResNet26b", "digits224",
         # imagenet stem (7x7/2 + pool): at 224x224 the cifar stem would
         # keep stage 1 at full resolution — (batch, 224, 224, 64)
         # activations are 3 GB each at batch 512 and OOM the chip
         {"type": "resnet", "num_classes": 10, "block": "bottleneck",
          "blocks_per_stage": [2, 2, 2, 2], "stem": "imagenet",
          "widths": [64, 128, 256, 512]},
         (x224, y224), (xv224, yv224), args.epochs224, 0.01,
         "224x224 REAL-data pretraining: augmented UCI digit strokes "
         "composited over disjoint crops of sklearn's real photo scans "
         "(train = left photo halves, held-out = untouched original "
         "scans over right halves; "
         "`testing.datagen.digits_rgb224_augmented`)"))

    repo = LocalRepo(args.out)
    # previous README rows, for jobs whose retrain is skipped
    old_rows = {}
    readme_path = os.path.join(args.out, "README.md")
    if os.path.exists(readme_path):
        for line in open(readme_path):
            parts = [p.strip() for p in line.split("|")]
            if len(parts) >= 5 and parts[1] and not parts[1].startswith(
                    ("model", "---")):
                # key by (name, dataset): the same backbone trained on two
                # corpora must keep two distinct rows
                old_rows[(parts[1], parts[2].split()[0])] = line.rstrip("\n")
    manifest_lines = []
    table_rows = []
    for name, dataset, cfg, (x, y), (xv, yv), epochs, lr, note in jobs:
        if name in args.skip or f"{name}/{dataset}" in args.skip:
            fn = canonical_model_filename(name, dataset)
            if os.path.exists(os.path.join(args.out, fn + ".meta")):
                manifest_lines.append(fn + ".meta")
                if (name, dataset) in old_rows:
                    table_rows.append(old_rows[(name, dataset)])
                print(f"skipping {name}/{dataset} (existing artifact and "
                      f"README row preserved)")
            else:
                print(f"skipping {name}/{dataset} — NO existing artifact; "
                      f"it will be absent from the zoo")
            continue
        print(f"training {name}/{dataset} ({len(x)} rows, "
              f"{epochs} epochs)...")
        # 224x224 activations bound the batch (ResNet-50-class train was
        # measured at batch 128/256; 512 OOMs HBM)
        batch = min(args.batch, 128) if dataset == "digits224" else args.batch
        model, acc = train_and_eval(cfg, x, y, xv, yv, epochs, batch,
                                    lr=lr)
        blob = pack_model(cfg, model.getModelParams())
        module = build_model(cfg)
        schema = ModelSchema(
            name=name, dataset=dataset, modelType="image",
            hash=hashlib.sha256(blob).hexdigest(), size=len(blob),
            numLayers=len(module.layer_names()),
            layerNames=module.layer_names())
        repo.addBytes(schema, blob)
        fn = canonical_model_filename(name, dataset)
        manifest_lines.append(fn + ".meta")
        table_rows.append(
            f"| {name} | {dataset} ({note}) | {acc:.4f} | "
            f"{len(blob)//1024} KiB |")
        print(f"  held-out acc {acc:.4f}, {len(blob)//1024} KiB")

    with open(os.path.join(args.out, MANIFEST), "w") as f:
        f.write("\n".join(manifest_lines) + "\n")
    with open(os.path.join(args.out, "README.md"), "w") as f:
        f.write(
            "# Model zoo\n\n"
            "Pretrained artifacts served by `models.downloader` (LocalRepo "
            "on this directory, or RemoteRepo over any static HTTP server "
            "pointed here — MANIFEST + `.meta` schemas + `.model` blobs, "
            "sha256-verified on every transfer). Built by "
            "`tools/build_zoo.py` on 1x TPU v5e.\n\n"
            "| model | dataset | held-out acc | size |\n"
            "|---|---|---|---|\n"
            + "\n".join(table_rows) + "\n\n"
            "`ImageFeaturizer` consumes these for transfer learning "
            "(examples e303/e305); `TpuModel.setModelSchema` serves them "
            "directly. digits8 = REAL scanned digits (sklearn's bundled "
            "UCI corpus), classes 0-7 only — 8/9 stay unseen so the e303 "
            "transfer task is genuinely downstream. CIFAR-10 (the "
            "reference notebooks' teacher) is unreachable in this "
            "zero-egress build; digits is the real-image corpus the "
            "environment ships.\n")
    print(f"zoo written to {args.out}: {len(manifest_lines)} models")
    return 0


if __name__ == "__main__":
    sys.exit(main())
