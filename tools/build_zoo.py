"""Build the committed model zoo: train the flagship net, pack, index.

The reference ships a CDN repository of pretrained CNTK models with hashes
and layerNames (downloader/.../Schema.scala:54-72, DefaultModelRepo at
ModelDownloader.scala:109) that ImageFeaturizer consumes for transfer
learning. This zero-egress build publishes its own: ResNet-20 trained on the
procedurally generated shapes10 corpus (mmlspark_tpu.testing.datagen —
deterministic from a seed, so the artifact is evaluable on any machine),
packed as a .model zip and indexed with sha256 in ``zoo/`` (a LocalRepo
directory that doubles as a RemoteRepo when served over HTTP: MANIFEST +
metas + blobs).

Run on a TPU host: ``python tools/build_zoo.py [--epochs 8] [--n 20000]``.
Rewrites zoo/ and prints the held-out accuracy that goes into zoo/README.md.
"""

import argparse
import hashlib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20000)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--out", default=os.path.join(REPO, "zoo"))
    args = ap.parse_args()

    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.core.schema import make_image_row
    from mmlspark_tpu.models import TpuLearner, TpuModel, build_model
    from mmlspark_tpu.models.downloader import (LocalRepo, MANIFEST,
                                                ModelSchema,
                                                canonical_model_filename,
                                                pack_model)
    from mmlspark_tpu.testing.datagen import make_shapes10

    x, y = make_shapes10(args.n, seed=7)
    xv, yv = make_shapes10(4000, seed=8)

    from mmlspark_tpu.core.utils import object_column

    def frame(xa, ya):
        rows = object_column([make_image_row(f"s{i}", 32, 32, 3, xa[i])
                              for i in range(len(xa))])
        return DataFrame({"image": rows, "label": ya})

    cfg = {"type": "resnet", "num_classes": 10}
    learner = (TpuLearner().setFeaturesCol("image")
               .setModelConfig(cfg)
               .setEpochs(args.epochs).setBatchSize(args.batch)
               .setOptimizer("momentum").setLearningRate(0.05).setSeed(0))
    model = learner.fit(frame(x, y))
    out = model.setInputCol("image").transform(frame(xv, yv))
    preds = np.stack(list(out.col("scores"))).argmax(axis=1)
    acc = float((preds == yv).mean())
    print(f"held-out accuracy: {acc:.4f} (final loss "
          f"{model._final_loss:.4f})")

    blob = pack_model(cfg, model.getModelParams())
    module = build_model(cfg)
    schema = ModelSchema(
        name="ResNet20", dataset="shapes10", modelType="image",
        hash=hashlib.sha256(blob).hexdigest(), size=len(blob),
        numLayers=len(module.layer_names()),
        layerNames=module.layer_names())
    repo = LocalRepo(args.out)
    repo.addBytes(schema, blob)
    fn = canonical_model_filename(schema.name, schema.dataset)
    with open(os.path.join(args.out, MANIFEST), "w") as f:
        f.write(fn + ".meta\n")
    with open(os.path.join(args.out, "README.md"), "w") as f:
        f.write(
            "# Model zoo\n\n"
            "Pretrained artifacts served by `models.downloader` (LocalRepo "
            "on this directory, or RemoteRepo over any static HTTP server "
            "pointed here — MANIFEST + `.meta` schemas + `.model` blobs, "
            "sha256-verified on every transfer).\n\n"
            "| model | dataset | held-out acc | size | trained by |\n"
            "|---|---|---|---|---|\n"
            f"| ResNet20 | shapes10 (procedural, "
            f"`testing.datagen.make_shapes10`) | {acc:.4f} | "
            f"{len(blob)//1024} KiB | `tools/build_zoo.py --epochs "
            f"{args.epochs} --n {args.n}` on 1x TPU v5e |\n\n"
            "`ImageFeaturizer` consumes these for transfer learning "
            "(examples e303/e305); `TpuModel.setModelSchema` serves them "
            "directly.\n")
    print(f"zoo written to {args.out}: {fn} ({len(blob)//1024} KiB), "
          f"acc {acc:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
