"""Emit the sample notebooks (reference: notebooks/samples/*.ipynb).

The reference ships executable notebooks as its user-facing documentation
and runs them in CI via an nbconvert harness (tools/notebook/tester/
NotebookTestSuite.py). This script writes the TPU-native analogs into
``notebooks/`` as real .ipynb artifacts (committed); the runner is
tests/test_notebooks.py (extended tier).

Regenerate with ``python tools/make_notebooks.py`` after editing the cell
sources below.
"""

import os
import sys

import nbformat as nbf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "notebooks")

#: first cell of every notebook: pin the 8-device virtual CPU mesh before
#: any jax import (same trick as tests/conftest.py) and put the repo on the
#: path regardless of the kernel's cwd
BOOTSTRAP = """\
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
for up in (".", ".."):
    cand = os.path.abspath(up)
    if os.path.isdir(os.path.join(cand, "mmlspark_tpu")):
        sys.path.insert(0, cand)
        break
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
print("devices:", len(jax.devices()), jax.default_backend())"""


def nb(title: str, *cells):
    book = nbf.v4.new_notebook()
    book.metadata["kernelspec"] = {"name": "python3",
                                   "display_name": "Python 3",
                                   "language": "python"}
    book.cells = [nbf.v4.new_markdown_cell(f"# {title}"),
                  nbf.v4.new_code_cell(BOOTSTRAP)]
    for c in cells:
        kind, src = c
        book.cells.append(nbf.v4.new_markdown_cell(src) if kind == "md"
                          else nbf.v4.new_code_cell(src))
    return book


md = lambda s: ("md", s)
code = lambda s: ("code", s)


N103 = nb(
    "103 - Before and After mmlspark_tpu",
    md("The reference notebook contrasts a hand-assembled Spark ML pipeline "
       "with the one-stage MMLSpark flow (`notebooks/samples/103`). Same "
       "story here: **before** — index categoricals, assemble features, "
       "fit, score, and compute metrics by hand; **after** — "
       "`TrainClassifier` + `ComputeModelStatistics` do all of it."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
n = 400
education = np.array(["HS", "BSc", "MSc", "PhD"], dtype=object)[
    rng.integers(0, 4, n)]
hours = rng.integers(20, 60, n).astype(np.float64)
age = rng.integers(18, 70, n).astype(np.float64)
income = ((hours > 42) & (education != "HS")) ^ (rng.random(n) < 0.1)
df = DataFrame({"education": education, "hours": hours, "age": age,
                "income": income.astype(np.int64)})
train, test = df.randomSplit([0.75, 0.25], seed=1)
train.count(), test.count()"""),
    md("## Before: every step by hand"),
    code("""\
from mmlspark_tpu.automl import ValueIndexer
from mmlspark_tpu.stages import FastVectorAssembler
from mmlspark_tpu.models import LogisticRegression

vi = ValueIndexer().setInputCol("education").setOutputCol("edu_idx") \\
    .fit(train)
asm = FastVectorAssembler().setInputCols(("edu_idx", "hours", "age")) \\
    .setOutputCol("features")
prep = lambda d: asm.transform(vi.transform(d))
lr_model = (LogisticRegression().setLabelCol("income")
            .setMaxIter(120).fit(prep(train)))
scored = lr_model.transform(prep(test))
manual_acc = float((np.asarray(scored.col("prediction"))
                    == np.asarray(test.col("income"))).mean())
print("manual pipeline accuracy:", round(manual_acc, 3))"""),
    md("## After: one estimator"),
    code("""\
from mmlspark_tpu.automl import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.models import LogisticRegression

model = (TrainClassifier().setLabelCol("income")
         .setModel(LogisticRegression().setMaxIter(120)).fit(train))
out = model.transform(test)
stats = (ComputeModelStatistics().setLabelCol("income")
         .setScoredLabelsCol("scored_labels").transform(out))
auto_acc = float(stats.col("accuracy")[0])
print("TrainClassifier accuracy:", round(auto_acc, 3))
assert auto_acc > 0.75 and manual_acc > 0.7
print("103 OK")"""))


N104 = nb(
    "104 - Price Prediction Regression (Auto Imports)",
    md("Analog of `notebooks/samples/104`: the Auto Imports car dataset — "
       "mixed numeric/categorical columns with missing values — cleaned "
       "with `CleanMissingData`, auto-featurized inside `TrainRegressor`, "
       "and two learners compared with `ComputePerInstanceStatistics`."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(1)
n = 360
make = np.array(["toyota", "bmw", "audi", "mazda"], dtype=object)[
    rng.integers(0, 4, n)]
horsepower = rng.uniform(60, 260, n)
weight = rng.uniform(800, 2400, n)
price = (90 * horsepower + 12 * weight
         + 4000 * (make == "bmw") + 3000 * (make == "audi")
         + rng.normal(0, 900, n))
horsepower[rng.random(n) < 0.12] = np.nan      # the dataset's famous '?'s
df = DataFrame({"make": make, "horsepower": horsepower,
                "weight": weight, "price": price})
df.count()"""),
    code("""\
from mmlspark_tpu.stages import CleanMissingData
clean = CleanMissingData().setInputCols(("horsepower",)) \\
    .setCleaningMode("Mean").fit(df)
dfc = clean.transform(df)
assert not np.isnan(np.asarray(dfc.col("horsepower"))).any()
train, test = dfc.randomSplit([0.8, 0.2], seed=2)"""),
    code("""\
from mmlspark_tpu.automl import ComputePerInstanceStatistics, TrainRegressor
from mmlspark_tpu.models import GBTRegressor, LinearRegression

results = {}
for name, algo in [("linear", LinearRegression()),
                   ("gbt", GBTRegressor().setNumIterations(30))]:
    model = TrainRegressor().setLabelCol("price").setModel(algo).fit(train)
    out = model.transform(test)
    per = (ComputePerInstanceStatistics().setLabelCol("price")
           .setEvaluationMetric("regression").transform(out))
    rmse = float(np.sqrt(np.mean(np.asarray(per.col("L2_loss")))))
    results[name] = rmse
    print(name, "RMSE:", round(rmse, 1))
base = float(np.std(np.asarray(test.col("price"))))
assert min(results.values()) < 0.5 * base
print("104 OK")"""))


N105 = nb(
    "105 - Regression with DataConversion",
    md("Analog of `notebooks/samples/105`: columns arrive as STRINGS (the "
       "raw CSV reality); `DataConversion` casts them to typed columns and "
       "tags a categorical before `TrainRegressor` runs."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(2)
n = 320
rooms = rng.integers(1, 8, n)
sqm = rng.uniform(25, 180, n)
zone = np.array(["A", "B", "C"], dtype=object)[rng.integers(0, 3, n)]
rent = 9 * sqm + 120 * rooms + 300 * (zone == "A") + rng.normal(0, 80, n)
df = DataFrame({  # everything stringly-typed, like a raw CSV
    "rooms": np.array([str(v) for v in rooms], dtype=object),
    "sqm": np.array([f"{v:.1f}" for v in sqm], dtype=object),
    "zone": zone,
    "rent": rent})
print(df.dtypes())"""),
    code("""\
from mmlspark_tpu.stages import DataConversion
df2 = DataConversion().setCols(("rooms",)).setConvertTo("integer") \\
    .transform(df)
df2 = DataConversion().setCols(("sqm",)).setConvertTo("double") \\
    .transform(df2)
df2 = DataConversion().setCols(("zone",)).setConvertTo("toCategorical") \\
    .transform(df2)
assert df2.col("rooms").dtype.kind == "i"
assert df2.col("sqm").dtype.kind == "f"
from mmlspark_tpu.core.schema import CategoricalUtilities
assert CategoricalUtilities.getLevels(df2, "zone") is not None
print(df2.dtypes())"""),
    code("""\
from mmlspark_tpu.automl import ComputeModelStatistics, TrainRegressor
from mmlspark_tpu.models import GBTRegressor
train, test = df2.randomSplit([0.8, 0.2], seed=3)
model = (TrainRegressor().setLabelCol("rent")
         .setModel(GBTRegressor().setNumIterations(40)).fit(train))
out = model.transform(test)
stats = (ComputeModelStatistics().setLabelCol("rent")
         .setEvaluationMetric("regression").transform(out))
rmse = float(stats.col("rmse")[0])
print("RMSE:", round(rmse, 1))
assert rmse < 0.6 * float(np.std(np.asarray(test.col("rent"))))
print("105 OK")"""))


N302 = nb(
    "302 - Pipeline Image Transformations",
    md("Analog of `notebooks/samples/302`: chained image ops — resize, "
       "crop, flip, blur — as ONE `ImageTransformer` stage (the reference "
       "runs an OpenCV stage list per row; here the chain compiles to one "
       "fused XLA program per shape bucket), then `UnrollImage` for "
       "downstream learners."),
    code("""\
from mmlspark_tpu import DataFrame
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.core.utils import object_column
from mmlspark_tpu.testing.datagen import make_shapes10
x, y = make_shapes10(24, size=48, seed=3)
rows = object_column([make_image_row(f"img{i}", 48, 48, 3, x[i])
                      for i in range(len(x))])
df = DataFrame({"image": rows, "label": y})
df.count()"""),
    code("""\
from mmlspark_tpu.ops import ImageTransformer
it = (ImageTransformer().setInputCol("image").setOutputCol("proc")
      .resize(36, 36).crop(2, 2, 32, 32).flip(1).blur(3, 3))
out = it.transform(df)
first = out.col("proc")[0]
print("processed:", first["height"], "x", first["width"])
assert (first["height"], first["width"]) == (32, 32)"""),
    code("""\
from mmlspark_tpu.ops.image_stages import UnrollImage
un = UnrollImage().setInputCol("proc").setOutputCol("features")
flat = un.transform(out)
vec = flat.col("features")[0]
print("unrolled dim:", vec.shape)
assert vec.shape == (32 * 32 * 3,)
print("302 OK")"""))


N101 = nb(
    "101 - Adult Census Income Training",
    md("Analog of `notebooks/samples/101`: census-shaped mixed "
       "numeric/categorical columns; `TrainClassifier` auto-featurizes and "
       "fits, `ComputeModelStatistics` evaluates (source flow: "
       "examples/e101_automl_classification.py)."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
n = 400
hours = rng.uniform(10, 60, n)
education = np.array(["hs", "college", "masters"], dtype=object)[
    rng.integers(0, 3, n)]
age = rng.uniform(18, 70, n)
signal = 0.05 * hours + 0.8 * (education == "masters") + 0.02 * age
label = (signal + rng.normal(0, 0.3, n) > 2.7).astype(np.int64)
df = DataFrame({"age": age, "hours_per_week": hours,
                "education": education, "label": label})
train, test = df.randomSplit([0.75, 0.25], seed=1)
train.count(), test.count()"""),
    code("""\
from mmlspark_tpu.automl import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.models import LogisticRegression
model = TrainClassifier().setModel(LogisticRegression()).fit(train)
scored = model.transform(test)
row = ComputeModelStatistics().transform(scored).first()
print({k: round(float(v), 3) for k, v in row.items()
       if k in ("accuracy", "AUC")})
assert row["accuracy"] > 0.7
print("101 OK")"""))


N102 = nb(
    "102 - Regression Example with Flight Delay",
    md("Analog of `notebooks/samples/102`: flight-delay-shaped regression "
       "with `TrainRegressor`, candidate comparison via `FindBestModel`, "
       "and per-row diagnostics (source: "
       "examples/e102_regression_model_selection.py)."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
n = 300
carrier = np.array(["AA", "UA", "DL"], dtype=object)[rng.integers(0, 3, n)]
distance = rng.uniform(100, 3000, n)
dep_hour = rng.integers(5, 23, n).astype(np.int64)
delay = (0.01 * distance + 3.0 * (carrier == "UA") + 0.5 * dep_hour
         + rng.normal(0, 2.0, n))
df = DataFrame({"carrier": carrier, "distance": distance,
                "dep_hour": dep_hour, "label": delay})
train, test = df.randomSplit([0.8, 0.2], seed=1)"""),
    code("""\
from mmlspark_tpu.automl import (ComputePerInstanceStatistics,
                                 FindBestModel, TrainRegressor)
from mmlspark_tpu.models import (GBTRegressor, LinearRegression,
                                 RandomForestRegressor)
models = [TrainRegressor().setLabelCol("label").setModel(m).fit(train)
          for m in (LinearRegression(),
                    GBTRegressor().setNumIterations(25),
                    RandomForestRegressor().setNumIterations(20))]
best = (FindBestModel().setModels(tuple(models))
        .setEvaluationMetric("rmse").fit(test))
print("best metric:", round(best.getBestModelMetrics(), 2))
out = best.transform(test)
per = (ComputePerInstanceStatistics().setLabelCol("label")
       .setEvaluationMetric("regression").transform(out))
rmse = float(np.sqrt(np.mean(np.asarray(per.col("L2_loss")))))
assert rmse < 0.6 * float(np.std(np.asarray(test.col("label"))))
print("102 OK")"""))


N106 = nb(
    "106 - Quantile Regression with LightGBM",
    md("Analog of `notebooks/samples/106`: `LightGBMRegressor` with "
       "`application=quantile` on heteroscedastic data, plus a "
       "`LightGBMClassifier` fit — the reference's socket-collective "
       "boosting becomes XLA histogram kernels (source: "
       "examples/e106_gbdt_quantile.py)."),
    code("""\
from mmlspark_tpu import DataFrame
from mmlspark_tpu.core.utils import object_column
from mmlspark_tpu.models import LightGBMClassifier, LightGBMRegressor
rng = np.random.default_rng(0)
n = 500
x = rng.normal(size=(n, 6)).astype(np.float32)
feats = object_column([row for row in x])
y_reg = (2.0 * x[:, 0] - x[:, 1]
         + rng.normal(0, 0.5 + 0.5 * (x[:, 2] > 0), n))
reg_df = DataFrame({"features": feats, "label": y_reg.astype(np.float64)})
qlo = (LightGBMRegressor().setApplication("quantile").setAlpha(0.1)
       .setNumIterations(30).setNumLeaves(15).fit(reg_df))
qhi = (LightGBMRegressor().setApplication("quantile").setAlpha(0.9)
       .setNumIterations(30).setNumLeaves(15).fit(reg_df))
lo = np.asarray(qlo.transform(reg_df).col("prediction"))
hi = np.asarray(qhi.transform(reg_df).col("prediction"))
cover = float(((y_reg >= lo) & (y_reg <= hi)).mean())
print("10-90 interval coverage:", round(cover, 3))
assert 0.6 < cover <= 1.0"""),
    code("""\
y_cls = (x[:, 0] + 0.5 * x[:, 3] > 0).astype(np.float64)
cls_df = DataFrame({"features": feats, "label": y_cls})
clf = (LightGBMClassifier().setNumIterations(30).setNumLeaves(15)
       .fit(cls_df))
pred = np.asarray(clf.transform(cls_df).col("prediction"))
acc = float((pred == y_cls).mean())
print("classifier accuracy:", round(acc, 3))
assert acc > 0.9
print("106 OK")"""))


N201 = nb(
    "201 - Amazon Book Reviews - TextFeaturizer",
    md("Analog of `notebooks/samples/201`: review text through "
       "`TextFeaturizer` (tokenize, stopwords, n-grams, hashing TF, IDF) "
       "into a linear classifier."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
positive = ["great", "wonderful", "loved", "excellent", "gripping"]
negative = ["boring", "awful", "hated", "dull", "tedious"]
filler = ["book", "story", "plot", "read", "author", "the", "a"]
n = 400
texts, labels = [], []
for _ in range(n):
    lab = int(rng.random() < 0.5)
    words = list(rng.choice(positive if lab else negative, 3)) \
        + list(rng.choice(filler, 5))
    rng.shuffle(words)
    texts.append(" ".join(words))
    labels.append(lab)
df = DataFrame({"text": np.array(texts, dtype=object),
                "label": np.array(labels, dtype=np.int64)})
train, test = df.randomSplit([0.75, 0.25], seed=1)"""),
    code("""\
from mmlspark_tpu.models import LogisticRegression
from mmlspark_tpu.ops import TextFeaturizer
tf = (TextFeaturizer().setInputCol("text").setOutputCol("features")
      .setNumFeatures(512).setUseStopWordsRemover(True)).fit(train)
clf = LogisticRegression().setMaxIter(80).fit(tf.transform(train))
pred = clf.transform(tf.transform(test))
acc = float((np.asarray(pred.col("prediction"))
             == np.asarray(test.col("label"))).mean())
print("accuracy:", round(acc, 3))
assert acc > 0.85
print("201 OK")"""))


N202 = nb(
    "202 - Amazon Book Reviews - Word2Vec",
    md("Analog of `notebooks/samples/202`: Word2Vec embeddings (batched "
       "skip-gram negative sampling on device) averaged into document "
       "vectors, then a classifier (source: examples/e202_word2vec.py)."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
positive = ["great", "wonderful", "loved", "excellent", "gripping"]
negative = ["boring", "awful", "hated", "dull", "tedious"]
filler = ["book", "story", "plot", "read", "author", "chapter"]
n = 400
texts, labels = [], []
for _ in range(n):
    lab = int(rng.random() < 0.5)
    words = list(rng.choice(positive if lab else negative, 4)) \
        + list(rng.choice(filler, 6))
    rng.shuffle(words)
    texts.append(" ".join(words))
    labels.append(lab)
df = DataFrame({"text": np.array(texts, dtype=object),
                "label": np.array(labels, dtype=np.int64)})
train, test = df.randomSplit([0.75, 0.25], seed=1)"""),
    code("""\
from mmlspark_tpu.models import LogisticRegression
from mmlspark_tpu.ops import Word2Vec
w2v = (Word2Vec().setInputCol("text").setOutputCol("features")
       .setVectorSize(32).setMinCount(2).setWindowSize(4)
       .setMaxIter(3).setSeed(2)).fit(train)
syn = w2v.findSynonyms("great", 3)
print("synonyms of 'great':", list(syn.col("word")))
clf = LogisticRegression().setMaxIter(80).fit(w2v.transform(train))
pred = clf.transform(w2v.transform(test))
acc = float((np.asarray(pred.col("prediction"))
             == np.asarray(test.col("label"))).mean())
print("accuracy:", round(acc, 3))
assert acc > 0.8
print("202 OK")"""))


N203 = nb(
    "203 - Breast Cancer - Tune Hyperparameters",
    md("Analog of `notebooks/samples/203`: randomized k-fold search over "
       "several model families at once with `TuneHyperparameters` (source: "
       "examples/e203_tune_hyperparameters.py)."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
n = 300
y = rng.integers(0, 2, n)
base = rng.normal(size=(n, 6))
x = base + y[:, None] * np.array([1.2, 0.8, 0.0, 0.5, 1.0, 0.2])
feats = np.empty(n, dtype=object)
for i in range(n):
    feats[i] = x[i].astype(np.float32)
df = DataFrame({"features": feats, "label": y.astype(np.int64)})
train, test = df.randomSplit([0.75, 0.25], seed=1)"""),
    code("""\
from mmlspark_tpu.automl import TuneHyperparameters
from mmlspark_tpu.models import (LightGBMClassifier, LogisticRegression,
                                 RandomForestClassifier)
tuned = (TuneHyperparameters()
         .setModels((LogisticRegression(),
                     RandomForestClassifier().setNumIterations(15),
                     LightGBMClassifier().setNumIterations(15)))
         .setEvaluationMetric("accuracy")
         .setNumFolds(3).setNumRuns(6).setParallelism(2).setSeed(0)
         .fit(train))
print("best CV metric:", round(tuned.getBestMetric(), 3))
print("best setting:", {k: (round(v, 4) if isinstance(v, float) else v)
                        for k, v in list(tuned.getBestSetting().items())[:4]})
pred = tuned.transform(test)
acc = float((np.asarray(pred.col("prediction"))
             == np.asarray(test.col("label"))).mean())
print("held-out accuracy:", round(acc, 3))
assert acc > 0.8
print("203 OK")"""))


N301 = nb(
    "301 - CIFAR10 CNN Evaluation",
    md("Analog of `notebooks/samples/301`: images flow through "
       "`ImageTransformer` -> `UnrollImage` -> `TpuModel` batch inference "
       "— the reference's per-row JNI calls into CNTK become one fused XLA "
       "program per shape bucket (source: examples/e301_image_inference.py)."),
    code("""\
import jax
from mmlspark_tpu import DataFrame, Pipeline
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.core.utils import object_column
from mmlspark_tpu.models import TpuModel, build_model
from mmlspark_tpu.ops import ImageTransformer, UnrollImage
rng = np.random.default_rng(0)
n = 32
rows = object_column([make_image_row(
    f"img{i}", 40, 40, 3,
    rng.integers(0, 255, (40, 40, 3), dtype=np.uint8)) for i in range(n)])
df = DataFrame({"image": rows})
cfg = {"type": "convnet", "channels": [8, 8], "dense": 32,
       "num_classes": 10}
module = build_model(cfg)
params = module.init(jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3),
                                                     np.float32))
net = (TpuModel().setInputCol("features").setModelConfig(cfg)
       .setModelParams(params).setInputShape((3, 32, 32)))"""),
    code("""\
pipe = Pipeline().setStages((
    ImageTransformer().setInputCol("image").setOutputCol("proc")
        .resize(32, 32),
    UnrollImage().setInputCol("proc").setOutputCol("features"),
    net))
out = pipe.fit(df).transform(df)
scores = np.stack(list(out.col("scores")))
print("scores:", scores.shape)
assert scores.shape == (n, 10)
print("301 OK")"""))


_ZOO_BOOT = """\
import os
import mmlspark_tpu
REPO = os.path.dirname(os.path.dirname(os.path.abspath(
    mmlspark_tpu.__file__)))
ZOO = os.path.join(REPO, "zoo")
from mmlspark_tpu.models.downloader import ModelDownloader
print("zoo models:", [(s.name, s.dataset)
                      for s in ModelDownloader(ZOO).localModels()])"""


N303 = nb(
    "303 - Transfer Learning by DNN Featurization",
    md("Analog of `notebooks/samples/303`: `ModelDownloader` pulls a "
       "pretrained net from the model repo (served over HTTP with sha256 "
       "verification), `ImageFeaturizer` truncates it below the head, and "
       "a cheap classifier trains on the embeddings — beating the same "
       "architecture with random weights. The teacher is ResNet-20 "
       "trained on REAL data (sklearn's UCI handwritten-digit scans, "
       "classes 0-7 only); the downstream task is digits 8 vs 9, which "
       "the teacher never saw, from 56 labels (source: "
       "examples/e303_transfer_learning.py)."),
    code(_ZOO_BOOT),
    code("""\
import functools, http.server, tempfile, threading
handler = functools.partial(http.server.SimpleHTTPRequestHandler,
                            directory=ZOO)
server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
threading.Thread(target=server.serve_forever, daemon=True).start()
url = f"http://127.0.0.1:{server.server_address[1]}/"
local = tempfile.mkdtemp(prefix="zoo_local_")
downloader = ModelDownloader(local_path=local, server_url=url)
schema = downloader.downloadByName("ResNet20", "digits8")   # sha256-gated
print("downloaded:", schema.uri.split("/")[-1],
      "layers:", schema.layerNames[-2:])"""),
    code("""\
from mmlspark_tpu import DataFrame
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.core.utils import object_column
from mmlspark_tpu.models import (ImageFeaturizer, LogisticRegression,
                                 TpuModel, build_model)
from mmlspark_tpu.testing.datagen import digits_rgb32
import jax
x89, y89 = digits_rgb32(classes=(8, 9))   # REAL digits the teacher never saw
order = np.random.default_rng(42).permutation(len(x89))
xt, yt = x89[order[:56]], y89[order[:56]]
xe, ye = x89[order[56:]], y89[order[56:]]
def frame(xa, ya):
    rows = object_column([make_image_row(f"i{i}", 32, 32, 3, xa[i])
                          for i in range(len(xa))])
    return DataFrame({"image": rows, "label": ya})
def transfer_accuracy(backbone):
    feat = (ImageFeaturizer().setInputCol("image")
            .setOutputCol("features").setModel(backbone)
            .setCutOutputLayers(1))
    clf = LogisticRegression().setMaxIter(80).fit(
        feat.transform(frame(xt, yt)))
    pred = clf.transform(feat.transform(frame(xe, ye)))
    return float((np.asarray(pred.col("prediction")) == ye).mean())
pretrained = TpuModel().setModelSchema(schema)
acc_pre = transfer_accuracy(pretrained)
cfg = pretrained.getModelConfig()
rand_params = build_model(cfg).init(jax.random.PRNGKey(1),
                                    np.zeros((1, 32, 32, 3), np.float32))
acc_rand = transfer_accuracy(
    TpuModel().setModelConfig(cfg).setModelParams(rand_params))
print(f"pretrained {acc_pre:.3f} vs random-init {acc_rand:.3f}")
assert acc_pre > acc_rand
server.shutdown()
print("303 OK")"""))


N305 = nb(
    "305 - Flowers ImageFeaturizer",
    md("Analog of `notebooks/samples/305`: `ImageSetAugmenter` multiplies "
       "the training set with flips before DNN featurization + classifier "
       "training (source: examples/e305_flowers_featurizer.py)."),
    code(_ZOO_BOOT),
    code("""\
from mmlspark_tpu import DataFrame
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.core.utils import object_column
from mmlspark_tpu.models import ImageFeaturizer, LogisticRegression
from mmlspark_tpu.ops import ImageSetAugmenter
from mmlspark_tpu.testing.datagen import make_shapes10
x, labels = make_shapes10(64, seed=5, num_classes=2, class_offset=0)
rows = object_column([make_image_row(f"f{i}", 32, 32, 3, x[i])
                      for i in range(len(x))])
df = DataFrame({"image": rows, "label": labels})
train, test = df.randomSplit([0.7, 0.3], seed=1)
aug = (ImageSetAugmenter().setInputCol("image").setOutputCol("image")
       .setFlipLeftRight(True).setFlipUpDown(False))
augmented = aug.transform(train)
print(f"augmentation: {train.count()} -> {augmented.count()} rows")
assert augmented.count() == 2 * train.count()"""),
    code("""\
schema = ModelDownloader(ZOO).downloadByName("ResNet20", "shapes10")
featurizer = (ImageFeaturizer().setInputCol("image")
              .setOutputCol("features").setModelSchema(schema)
              .setCutOutputLayers(1))
clf = LogisticRegression().setMaxIter(60).fit(
    featurizer.transform(augmented))
pred = clf.transform(featurizer.transform(test))
acc = float((np.asarray(pred.col("prediction"))
             == np.asarray(test.col("label"))).mean())
print("accuracy:", round(acc, 3))
assert acc > 0.7
print("305 OK")"""))


N304 = nb(
    "304 - Medical Entity Extraction",
    md("Analog of `notebooks/samples/304`: token-level sequence tagging "
       "with a bidirectional recurrent tagger trained by `TpuLearner` "
       "(the reference evaluates a pretrained CNTK BiLSTM; source: "
       "examples/e304_sequence_tagging.py)."),
    code("""\
import importlib
e304 = importlib.import_module("examples.e304_sequence_tagging")
print("304 OK (module ran end-to-end)")"""))


N401 = nb(
    "401 - Distributed Training",
    md("Analog of the reference's GPU notebook (`gpu/401`): the SAME "
       "pipeline code a laptop runs scales to a fleet by launching worker "
       "processes that each ingest only their shard — here demonstrated "
       "single-process with the 8-device virtual mesh doing data-parallel "
       "training; the multi-process path is exercised in "
       "tests/test_dataplane.py (source: "
       "examples/e401_distributed_training.py)."),
    code("""\
from mmlspark_tpu import DataFrame
from mmlspark_tpu.automl import Featurize
from mmlspark_tpu.models import TpuLearner
rng = np.random.default_rng(0)
n = 4096
y = rng.integers(0, 2, n)
xs = rng.normal(size=(n, 16)) + y[:, None] * 1.5
cols = {f"x{i}": xs[:, i] for i in range(16)}
df = DataFrame({**cols, "label": y.astype(np.int64)})
fz = (Featurize().setInputCols(tuple(cols))
      .setOutputCol("features").fit(df))
feat = fz.transform(df)"""),
    code("""\
import jax
model = (TpuLearner()
         .setModelConfig({"type": "mlp", "hidden": [32],
                          "num_classes": 2})
         .setEpochs(3).setBatchSize(512).setLearningRate(0.05)
         .fit(feat))   # batch sharded over all 8 devices (dp)
out = model.transform(feat)
acc = float((np.stack(list(out.col("scores"))).argmax(1) == y).mean())
print("devices:", len(jax.devices()), "accuracy:", round(acc, 3))
assert acc > 0.9
print("401 OK")"""))


NB_SMOKE = nb(
    "Basic DataFrame Ops Smoke Test",
    md("Analog of the reference's `tests/BasicDFOpsSmokeTest.ipynb` — the "
       "notebook-infrastructure canary: build a frame from sklearn's iris "
       "(the reference's own corpus here), check shape/columns, and run "
       "the basic relational ops the data plane guarantees. The `spark`/"
       "`sc` globals it asserts become the framework's DataFrame + device "
       "mesh."),
    code("""\
assert len(jax.devices()) > 0          # the defaultParallelism analog

from sklearn.datasets import load_iris
from mmlspark_tpu import DataFrame
from mmlspark_tpu.core.utils import object_column

d = load_iris()
cols = {fname: d["data"][:, i].astype(np.float32)
        for i, fname in enumerate(d["feature_names"])}
cols["target"] = np.array([str(d["target_names"][t]) for t in d["target"]],
                          dtype=object)
df = DataFrame(cols)
assert df.count() == 150
expected = list(d["feature_names"]) + ["target"]
assert df.columns == expected, df.columns"""),
    code("""\
# the relational surface the reference smoke-checks via Spark SQL
by_class = df.groupBy("target").count().sort("target")
print(list(zip(by_class.col("target"), by_class.col("count"))))
assert list(by_class.col("count")) == [50, 50, 50]
wide = df.filter(df.col("sepal length (cm)") > 5.0)
assert 0 < wide.count() < 150
train, test = df.randomSplit([0.7, 0.3], seed=0)
assert train.count() + test.count() == 150
print("SMOKE OK")"""))


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    books = {"101_adult_census_income_training.ipynb": N101,
             "102_regression_flight_delay.ipynb": N102,
             "103_before_and_after.ipynb": N103,
             "104_price_prediction_auto_imports.ipynb": N104,
             "105_regression_with_dataconversion.ipynb": N105,
             "106_quantile_regression_lightgbm.ipynb": N106,
             "201_amazon_reviews_text_featurizer.ipynb": N201,
             "202_amazon_reviews_word2vec.ipynb": N202,
             "203_tune_hyperparameters.ipynb": N203,
             "301_cifar10_cnn_evaluation.ipynb": N301,
             "302_pipeline_image_transformations.ipynb": N302,
             "303_transfer_learning_dnn_featurization.ipynb": N303,
             "304_medical_entity_extraction.ipynb": N304,
             "305_flowers_image_featurizer.ipynb": N305,
             "401_distributed_training.ipynb": N401,
             "basic_df_ops_smoke_test.ipynb": NB_SMOKE}
    for name, book in books.items():
        path = os.path.join(OUT, name)
        nbf.write(book, path)
        print("wrote", path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
