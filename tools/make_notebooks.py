"""Emit the sample notebooks (reference: notebooks/samples/*.ipynb).

The reference ships executable notebooks as its user-facing documentation
and runs them in CI via an nbconvert harness (tools/notebook/tester/
NotebookTestSuite.py). This script writes the TPU-native analogs into
``notebooks/`` as real .ipynb artifacts (committed); the runner is
tests/test_notebooks.py (extended tier).

Regenerate with ``python tools/make_notebooks.py`` after editing the cell
sources below.
"""

import os
import sys

import nbformat as nbf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "notebooks")

#: first cell of every notebook: pin the 8-device virtual CPU mesh before
#: any jax import (same trick as tests/conftest.py) and put the repo on the
#: path regardless of the kernel's cwd
BOOTSTRAP = """\
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
for up in (".", ".."):
    cand = os.path.abspath(up)
    if os.path.isdir(os.path.join(cand, "mmlspark_tpu")):
        sys.path.insert(0, cand)
        break
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
print("devices:", len(jax.devices()), jax.default_backend())"""


def nb(title: str, *cells):
    book = nbf.v4.new_notebook()
    book.metadata["kernelspec"] = {"name": "python3",
                                   "display_name": "Python 3",
                                   "language": "python"}
    book.cells = [nbf.v4.new_markdown_cell(f"# {title}"),
                  nbf.v4.new_code_cell(BOOTSTRAP)]
    for c in cells:
        kind, src = c
        book.cells.append(nbf.v4.new_markdown_cell(src) if kind == "md"
                          else nbf.v4.new_code_cell(src))
    return book


md = lambda s: ("md", s)
code = lambda s: ("code", s)


N103 = nb(
    "103 - Before and After mmlspark_tpu",
    md("The reference notebook contrasts a hand-assembled Spark ML pipeline "
       "with the one-stage MMLSpark flow (`notebooks/samples/103`). Same "
       "story here: **before** — index categoricals, assemble features, "
       "fit, score, and compute metrics by hand; **after** — "
       "`TrainClassifier` + `ComputeModelStatistics` do all of it."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(0)
n = 400
education = np.array(["HS", "BSc", "MSc", "PhD"], dtype=object)[
    rng.integers(0, 4, n)]
hours = rng.integers(20, 60, n).astype(np.float64)
age = rng.integers(18, 70, n).astype(np.float64)
income = ((hours > 42) & (education != "HS")) ^ (rng.random(n) < 0.1)
df = DataFrame({"education": education, "hours": hours, "age": age,
                "income": income.astype(np.int64)})
train, test = df.randomSplit([0.75, 0.25], seed=1)
train.count(), test.count()"""),
    md("## Before: every step by hand"),
    code("""\
from mmlspark_tpu.automl import ValueIndexer
from mmlspark_tpu.stages import FastVectorAssembler
from mmlspark_tpu.models import LogisticRegression

vi = ValueIndexer().setInputCol("education").setOutputCol("edu_idx") \\
    .fit(train)
asm = FastVectorAssembler().setInputCols(("edu_idx", "hours", "age")) \\
    .setOutputCol("features")
prep = lambda d: asm.transform(vi.transform(d))
lr_model = (LogisticRegression().setLabelCol("income")
            .setMaxIter(120).fit(prep(train)))
scored = lr_model.transform(prep(test))
manual_acc = float((np.asarray(scored.col("prediction"))
                    == np.asarray(test.col("income"))).mean())
print("manual pipeline accuracy:", round(manual_acc, 3))"""),
    md("## After: one estimator"),
    code("""\
from mmlspark_tpu.automl import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.models import LogisticRegression

model = (TrainClassifier().setLabelCol("income")
         .setModel(LogisticRegression().setMaxIter(120)).fit(train))
out = model.transform(test)
stats = (ComputeModelStatistics().setLabelCol("income")
         .setScoredLabelsCol("scored_labels").transform(out))
auto_acc = float(stats.col("accuracy")[0])
print("TrainClassifier accuracy:", round(auto_acc, 3))
assert auto_acc > 0.75 and manual_acc > 0.7
print("103 OK")"""))


N104 = nb(
    "104 - Price Prediction Regression (Auto Imports)",
    md("Analog of `notebooks/samples/104`: the Auto Imports car dataset — "
       "mixed numeric/categorical columns with missing values — cleaned "
       "with `CleanMissingData`, auto-featurized inside `TrainRegressor`, "
       "and two learners compared with `ComputePerInstanceStatistics`."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(1)
n = 360
make = np.array(["toyota", "bmw", "audi", "mazda"], dtype=object)[
    rng.integers(0, 4, n)]
horsepower = rng.uniform(60, 260, n)
weight = rng.uniform(800, 2400, n)
price = (90 * horsepower + 12 * weight
         + 4000 * (make == "bmw") + 3000 * (make == "audi")
         + rng.normal(0, 900, n))
horsepower[rng.random(n) < 0.12] = np.nan      # the dataset's famous '?'s
df = DataFrame({"make": make, "horsepower": horsepower,
                "weight": weight, "price": price})
df.count()"""),
    code("""\
from mmlspark_tpu.stages import CleanMissingData
clean = CleanMissingData().setInputCols(("horsepower",)) \\
    .setCleaningMode("Mean").fit(df)
dfc = clean.transform(df)
assert not np.isnan(np.asarray(dfc.col("horsepower"))).any()
train, test = dfc.randomSplit([0.8, 0.2], seed=2)"""),
    code("""\
from mmlspark_tpu.automl import ComputePerInstanceStatistics, TrainRegressor
from mmlspark_tpu.models import GBTRegressor, LinearRegression

results = {}
for name, algo in [("linear", LinearRegression()),
                   ("gbt", GBTRegressor().setNumIterations(30))]:
    model = TrainRegressor().setLabelCol("price").setModel(algo).fit(train)
    out = model.transform(test)
    per = (ComputePerInstanceStatistics().setLabelCol("price")
           .setEvaluationMetric("regression").transform(out))
    rmse = float(np.sqrt(np.mean(np.asarray(per.col("L2_loss")))))
    results[name] = rmse
    print(name, "RMSE:", round(rmse, 1))
base = float(np.std(np.asarray(test.col("price"))))
assert min(results.values()) < 0.5 * base
print("104 OK")"""))


N105 = nb(
    "105 - Regression with DataConversion",
    md("Analog of `notebooks/samples/105`: columns arrive as STRINGS (the "
       "raw CSV reality); `DataConversion` casts them to typed columns and "
       "tags a categorical before `TrainRegressor` runs."),
    code("""\
from mmlspark_tpu import DataFrame
rng = np.random.default_rng(2)
n = 320
rooms = rng.integers(1, 8, n)
sqm = rng.uniform(25, 180, n)
zone = np.array(["A", "B", "C"], dtype=object)[rng.integers(0, 3, n)]
rent = 9 * sqm + 120 * rooms + 300 * (zone == "A") + rng.normal(0, 80, n)
df = DataFrame({  # everything stringly-typed, like a raw CSV
    "rooms": np.array([str(v) for v in rooms], dtype=object),
    "sqm": np.array([f"{v:.1f}" for v in sqm], dtype=object),
    "zone": zone,
    "rent": rent})
print(df.dtypes())"""),
    code("""\
from mmlspark_tpu.stages import DataConversion
df2 = DataConversion().setCols(("rooms",)).setConvertTo("integer") \\
    .transform(df)
df2 = DataConversion().setCols(("sqm",)).setConvertTo("double") \\
    .transform(df2)
df2 = DataConversion().setCols(("zone",)).setConvertTo("toCategorical") \\
    .transform(df2)
assert df2.col("rooms").dtype.kind == "i"
assert df2.col("sqm").dtype.kind == "f"
from mmlspark_tpu.core.schema import CategoricalUtilities
assert CategoricalUtilities.getLevels(df2, "zone") is not None
print(df2.dtypes())"""),
    code("""\
from mmlspark_tpu.automl import ComputeModelStatistics, TrainRegressor
from mmlspark_tpu.models import GBTRegressor
train, test = df2.randomSplit([0.8, 0.2], seed=3)
model = (TrainRegressor().setLabelCol("rent")
         .setModel(GBTRegressor().setNumIterations(40)).fit(train))
out = model.transform(test)
stats = (ComputeModelStatistics().setLabelCol("rent")
         .setEvaluationMetric("regression").transform(out))
rmse = float(stats.col("rmse")[0])
print("RMSE:", round(rmse, 1))
assert rmse < 0.6 * float(np.std(np.asarray(test.col("rent"))))
print("105 OK")"""))


N302 = nb(
    "302 - Pipeline Image Transformations",
    md("Analog of `notebooks/samples/302`: chained image ops — resize, "
       "crop, flip, blur — as ONE `ImageTransformer` stage (the reference "
       "runs an OpenCV stage list per row; here the chain compiles to one "
       "fused XLA program per shape bucket), then `UnrollImage` for "
       "downstream learners."),
    code("""\
from mmlspark_tpu import DataFrame
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.core.utils import object_column
from mmlspark_tpu.testing.datagen import make_shapes10
x, y = make_shapes10(24, size=48, seed=3)
rows = object_column([make_image_row(f"img{i}", 48, 48, 3, x[i])
                      for i in range(len(x))])
df = DataFrame({"image": rows, "label": y})
df.count()"""),
    code("""\
from mmlspark_tpu.ops import ImageTransformer
it = (ImageTransformer().setInputCol("image").setOutputCol("proc")
      .resize(36, 36).crop(2, 2, 32, 32).flip(1).blur(3, 3))
out = it.transform(df)
first = out.col("proc")[0]
print("processed:", first["height"], "x", first["width"])
assert (first["height"], first["width"]) == (32, 32)"""),
    code("""\
from mmlspark_tpu.ops.image_stages import UnrollImage
un = UnrollImage().setInputCol("proc").setOutputCol("features")
flat = un.transform(out)
vec = flat.col("features")[0]
print("unrolled dim:", vec.shape)
assert vec.shape == (32 * 32 * 3,)
print("302 OK")"""))


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    books = {"103_before_and_after.ipynb": N103,
             "104_price_prediction_auto_imports.ipynb": N104,
             "105_regression_with_dataconversion.ipynb": N105,
             "302_pipeline_image_transformations.ipynb": N302}
    for name, book in books.items():
        path = os.path.join(OUT, name)
        nbf.write(book, path)
        print("wrote", path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
