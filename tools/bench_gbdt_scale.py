import sys; sys.path.insert(0, "/root/repo")
import time, numpy as np
from mmlspark_tpu.models.gbdt.engine import GBDTParams, fit_gbdt

rng = np.random.default_rng(0)
n, d = 10_000_000, 28
x = rng.normal(size=(n, d)).astype(np.float32)
logit = x[:, 0] * 2 + x[:, 1] - x[:, 2] * 0.5 + rng.normal(0, 0.5, n)
y = (logit > 0).astype(np.float32)
print("data built", flush=True)

p = GBDTParams(num_iterations=10, max_depth=5, objective="binary")
for tag in ("cold", "warm"):
    t0 = time.perf_counter()
    ens = fit_gbdt(x, y, p)
    np.asarray(ens.leaf).sum()
    dt = time.perf_counter() - t0
    print(f"level-wise 10M {tag}: {dt:.1f}s total, {dt/10:.2f} s/iter "
          f"(incl fixed binning/upload cost)", flush=True)

p2 = GBDTParams(num_iterations=3, num_leaves=31, max_depth=0,
                objective="binary")
t0 = time.perf_counter()
ens = fit_gbdt(x, y, p2)
np.asarray(ens.leaf).sum()
dt = time.perf_counter() - t0
print(f"leaf-wise 10M cold: {dt:.1f}s / 3 iters = {dt/3:.2f} s/iter",
      flush=True)
