"""At-scale GBDT wall-clock measurements (the BASELINE.md scale rows).

Default: 10M x 28 level-wise (cold + warm 10-iter fits, synced) plus a
3-iter leaf-wise probe. LEAFWISE_1M=1 measures the 1M-row leaf-wise
per-iteration cost instead (the BASELINE leaf-wise row)."""

import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np


def _data(n, d=28):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logit = x[:, 0] * 2 + x[:, 1] - x[:, 2] * 0.5 + rng.normal(0, 0.5, n)
    return x, (logit > 0).astype(np.float32)


def _timed_fit(x, y, p, tag):
    from mmlspark_tpu.models.gbdt.engine import fit_gbdt
    t0 = time.perf_counter()
    ens = fit_gbdt(x, y, p)
    np.asarray(ens.leaf).sum()          # sync on the fitted trees
    dt = time.perf_counter() - t0
    print(f"{tag}: {dt:.1f}s total, {dt/p.num_iterations:.2f} s/iter "
          f"(incl fixed binning/upload cost)", flush=True)
    return dt


def main():
    from mmlspark_tpu.models.gbdt.engine import GBDTParams

    if os.environ.get("LEAFWISE_1M") == "1":
        x, y = _data(1_000_000)
        print("data built", flush=True)
        p = GBDTParams(num_iterations=10, num_leaves=31, max_depth=0,
                       objective="binary")
        _timed_fit(x, y, p, "leaf-wise 31L 1M cold")
        _timed_fit(x, y, p, "leaf-wise 31L 1M warm")
        return

    x, y = _data(10_000_000)
    print("data built", flush=True)
    p = GBDTParams(num_iterations=10, max_depth=5, objective="binary")
    _timed_fit(x, y, p, "level-wise 10M cold")
    _timed_fit(x, y, p, "level-wise 10M warm")
    p2 = GBDTParams(num_iterations=3, num_leaves=31, max_depth=0,
                    objective="binary")
    _timed_fit(x, y, p2, "leaf-wise 10M cold")


if __name__ == "__main__":
    main()
