"""Data-ingest benchmark: disk -> C++ threaded decode -> staging buffer ->
HBM (SURVEY.md §7 hard part (a): the reference's ingest is element-wise JNI
copies at CNTKModel.scala:67-74 plus scp/getmerge data movement; here whole
batches stream through `io/loader.py` + `native/csrc/loader.cc`).

Writes a synthetic JPEG corpus once, then measures images/sec into device
memory (decode + resize + transfer, pipelined). Prints one JSON line.
"""

import json
import os
import tempfile
import time

import numpy as np

N_IMAGES = 1024
SRC_HW = (256, 256)
OUT_HW = (224, 224)
BATCH = 128


def _corpus(tmp: str) -> list[str]:
    import cv2
    rng = np.random.default_rng(0)
    paths = []
    for i in range(N_IMAGES):
        img = rng.integers(0, 256, (*SRC_HW, 3), dtype=np.uint8)
        p = os.path.join(tmp, f"img_{i:05d}.jpg")
        cv2.imwrite(p, img)
        paths.append(p)
    return paths


def bench_arrow():
    """Arrow->staging->HBM host path (io/arrow.py + native interleave) vs
    the Python-row conversion it replaces. Prints one JSON line."""
    import jax
    try:
        import pyarrow as pa
    except ImportError:
        print(json.dumps({"metric": "arrow_ingest_host_path",
                          "skipped": "pyarrow not installed"}))
        return

    from mmlspark_tpu.io.arrow import batch_to_matrix
    from mmlspark_tpu.native import available

    n, d, chunk = 1 << 20, 32, 1 << 16
    rng = np.random.default_rng(0)
    t = pa.table({f"x{j}": rng.normal(size=n).astype(np.float32)
                  for j in range(d)})
    feats = [f"x{j}" for j in range(d)]
    batches = t.to_batches(max_chunksize=chunk)
    mb = n * d * 4 / 2**20

    # (a) the old shape of the path: per-row Python objects, then a stack
    b0 = batches[0]
    t0 = time.perf_counter()
    rows = [np.array([b0.column(j)[i].as_py() for j in range(d)],
                     dtype=np.float32) for i in range(b0.num_rows)]
    _ = np.stack(rows)
    t_rows = (time.perf_counter() - t0) * (n / b0.num_rows)

    # (b) columnar: zero-copy views + threaded C++ interleave into staging
    buf = np.empty((chunk, d), np.float32)
    t0 = time.perf_counter()
    for b in batches:
        batch_to_matrix(b, feats, out=buf)
    t_col = time.perf_counter() - t0

    # (c) + device transfer (tunnel-bound on this box; measured, stated)
    t0 = time.perf_counter()
    last = None
    for b in batches:
        last = jax.device_put(np.array(batch_to_matrix(b, feats, out=buf)))
    np.asarray(last)
    t_dev = time.perf_counter() - t0

    print(json.dumps({
        "metric": "arrow_ingest_host_path",
        "value": round(mb / t_col, 1),
        "unit": "MB/sec host-side (columnar+interleave)",
        "python_row_path_MBps": round(mb / t_rows, 1),
        "speedup_vs_row_conversion": round(t_rows / t_col, 1),
        "end_to_end_to_device_MBps": round(mb / t_dev, 1),
        "native_interleave": available(),
        "backend": jax.default_backend(),
        "config": f"{n} rows x {d} f32 cols, {chunk}-row record batches",
    }))


def bench_feed_overlap():
    """Feed-path overlap report: a short host-feed fit (deviceDataCap=1
    forces the per-step feed path) with the async prefetcher on, then the
    telemetry snapshot's time breakdown. Overlap is WORKING when the
    consumer-stall total (time the step loop waited on the prefetcher) is
    well under the host-prep total (index/pad/mask/H2D time, which runs on
    the prefetch thread behind device compute). Prints one JSON line."""
    import jax
    from mmlspark_tpu import telemetry
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.core.utils import object_column
    from mmlspark_tpu.models import TpuLearner

    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        rng = np.random.default_rng(0)
        n, bs, epochs = 4096, 512, 2
        x = rng.normal(size=(n, 3 * 32 * 32)).astype(np.float32)
        y = rng.integers(0, 10, size=n).astype(np.int64)
        df = DataFrame({"features": object_column([r for r in x]),
                        "label": y})
        learner = (TpuLearner()
                   .setModelConfig({"type": "convnet", "channels": [16, 32],
                                    "dense": 64, "num_classes": 10,
                                    "height": 32, "width": 32})
                   .setInputShape((3, 32, 32))
                   .setEpochs(epochs).setBatchSize(bs)
                   .setDeviceDataCap(1))      # force the host-feed path
        t0 = time.perf_counter()
        learner.fit(df)
        dt = time.perf_counter() - t0

        snap = telemetry.snapshot()

        def series_sum(name):
            fam = snap.get(name, {}).get("series") or [{}]
            return float(fam[0].get("sum", 0.0))

        host_prep = series_sum("mmlspark_prefetch_produce_seconds")
        step = series_sum("mmlspark_trainer_step_seconds")
        stall = series_sum("mmlspark_prefetch_consumer_stall_seconds")
        print(json.dumps({
            "metric": "feed_path_prefetch_overlap",
            "value": round(host_prep - stall, 3),
            "unit": "sec of host prep hidden behind device compute",
            "host_prep_sec": round(host_prep, 3),
            "step_sec": round(step, 3),
            "consumer_stall_sec": round(stall, 3),
            "overlap_ok": bool(stall < host_prep),
            "imgs_per_sec": round(epochs * (n // bs) * bs / dt, 1),
            "backend": jax.default_backend(),
            "config": f"{n} rows x 3072 f32, batch {bs}, {epochs} epochs, "
                      f"prefetchDepth=2",
        }))
    finally:
        if not was_enabled:
            telemetry.disable()


def main():
    import jax

    from mmlspark_tpu.io.loader import device_image_batches
    from mmlspark_tpu.native import available

    with tempfile.TemporaryDirectory() as tmp:
        paths = _corpus(tmp)
        # warmup pass primes file cache + threads; sync the final async
        # device_put so no in-flight transfer leaks into the timed region
        warm = None
        for warm, _, _ in device_image_batches(paths[:BATCH * 2], BATCH,
                                               *OUT_HW):
            pass
        if warm is not None:
            np.asarray(warm)

        t0 = time.perf_counter()
        total = 0
        last = None
        for dev_batch, ok, count in device_image_batches(
                paths, BATCH, *OUT_HW):
            total += int(ok[:count].sum())
            last = dev_batch
        _ = np.asarray(last)  # hard sync: the final transfer must land
        dt = time.perf_counter() - t0

        print(json.dumps({
            "metric": "ingest_jpeg_decode_resize_to_hbm",
            "value": round(total / dt, 1),
            "unit": "imgs/sec",
            "backend": jax.default_backend(),
            "native_decoder": available(),
            "images": total,
            "config": f"{SRC_HW[0]}px jpeg -> {OUT_HW[0]}px, batch {BATCH}",
        }))


if __name__ == "__main__":
    main()
    bench_arrow()
    bench_feed_overlap()
