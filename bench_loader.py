"""Data-ingest benchmark: disk -> C++ threaded decode -> staging buffer ->
HBM (SURVEY.md §7 hard part (a): the reference's ingest is element-wise JNI
copies at CNTKModel.scala:67-74 plus scp/getmerge data movement; here whole
batches stream through `io/loader.py` + `native/csrc/loader.cc`).

Writes a synthetic JPEG corpus once, then measures images/sec into device
memory (decode + resize + transfer, pipelined). Prints one JSON line.
"""

import json
import os
import tempfile
import time

import numpy as np

N_IMAGES = 1024
SRC_HW = (256, 256)
OUT_HW = (224, 224)
BATCH = 128


def _corpus(tmp: str) -> list[str]:
    import cv2
    rng = np.random.default_rng(0)
    paths = []
    for i in range(N_IMAGES):
        img = rng.integers(0, 256, (*SRC_HW, 3), dtype=np.uint8)
        p = os.path.join(tmp, f"img_{i:05d}.jpg")
        cv2.imwrite(p, img)
        paths.append(p)
    return paths


def main():
    import jax

    from mmlspark_tpu.io.loader import device_image_batches
    from mmlspark_tpu.native import available

    with tempfile.TemporaryDirectory() as tmp:
        paths = _corpus(tmp)
        # warmup pass primes file cache + threads; sync the final async
        # device_put so no in-flight transfer leaks into the timed region
        warm = None
        for warm, _, _ in device_image_batches(paths[:BATCH * 2], BATCH,
                                               *OUT_HW):
            pass
        if warm is not None:
            np.asarray(warm)

        t0 = time.perf_counter()
        total = 0
        last = None
        for dev_batch, ok, count in device_image_batches(
                paths, BATCH, *OUT_HW):
            total += int(ok[:count].sum())
            last = dev_batch
        _ = np.asarray(last)  # hard sync: the final transfer must land
        dt = time.perf_counter() - t0

        print(json.dumps({
            "metric": "ingest_jpeg_decode_resize_to_hbm",
            "value": round(total / dt, 1),
            "unit": "imgs/sec",
            "backend": jax.default_backend(),
            "native_decoder": available(),
            "images": total,
            "config": f"{SRC_HW[0]}px jpeg -> {OUT_HW[0]}px, batch {BATCH}",
        }))


if __name__ == "__main__":
    main()
