"""Long-context benchmark: causal transformer train step throughput vs
sequence length on one chip (flash attention + rematerialization — the
long-context stack SURVEY.md §5 notes the reference lacks entirely; its
only sequence model is a pre-trained BiLSTM evaluated via CNTKModel).

Prints one JSON line per length; tokens/sec counts every token in the
batch per optimizer step (fwd+bwd+update).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.models.trainer import make_loss

    rng = np.random.default_rng(0)
    loss_fn = make_loss("cross_entropy")

    for T, batch in ((4096, 8), (16384, 2), (32768, 1)):
        # sequence classifier head (num_classes=8): the metric is the
        # ATTENTION-STACK train throughput (embed + L causal flash blocks,
        # fwd+bwd+adam), not causal-LM training — a per-token 32k-vocab LM
        # head would add ~2*d*V FLOPs/token on top of these numbers
        # head_dim 128 (512/4): fills the MXU's 128-deep contraction — the
        # round-5 default the flash kernel's own sweep recommends (30.8
        # TF/s causal vs 19.5 at the round-4 head_dim-64 shape)
        cfg = {"type": "transformer", "vocab_size": 32000, "d_model": 512,
               "heads": 4, "layers": 4, "num_classes": 8,
               "max_len": T, "causal": True, "remat": True,
               "attn_impl": "flash"}
        module = build_model(cfg)
        x = jnp.asarray(rng.integers(0, 32000, size=(batch, T), dtype=np.int32))
        y = jnp.asarray(rng.integers(0, 8, size=batch, dtype=np.int32))
        params = module.init(jax.random.PRNGKey(0), x[:1])
        tx = optax.adam(1e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, xb, yb):
            def compute(p):
                return loss_fn(module.apply(p, xb), yb)
            loss, grads = jax.value_and_grad(compute)(params)
            upd, opt2 = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, upd), opt2, loss

        params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)  # hard sync (block_until_ready is unreliable on axon)
        n_steps = 5
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)
        dt = (time.perf_counter() - t0) / n_steps
        print(json.dumps({
            "metric": "longcontext_attention_stack_train",
            "seq_len": T,
            "batch": batch,
            "tokens_per_sec": round(batch * T / dt, 0),
            "step_ms": round(dt * 1e3, 1),
            "config": "d512 h4 L4 (head_dim 128), flash+remat, bf16-in-f32-out blocks",
        }))


if __name__ == "__main__":
    main()
