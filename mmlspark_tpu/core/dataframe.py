"""Columnar DataFrame: the framework's data plane.

The reference rides Spark SQL DataFrames (driver plans, executors hold row
partitions, native code is entered per-partition via mapPartitions — see
SURVEY.md §1/§3). This framework is TPU-native and Spark-free: the data plane
is an immutable columnar table of numpy arrays, designed so whole columns can
be shipped to TPU HBM in one ``jax.device_put`` instead of the reference's
element-wise JNI copies (reference: cntk-model/.../CNTKModel.scala:67-74).

Key properties:
  * columns are numpy arrays (numeric, string/object, or object-structs for
    images); zero-copy from/to pyarrow and pandas where dtypes allow;
  * per-column metadata dict — carries categorical levels and score-column
    tags the way the reference stores them in Spark column metadata under
    ``MMLTag`` (reference: core/schema/.../Categoricals.scala:16-60);
  * logical partitions (``npartitions``) so partition-parallel semantics
    (LightGBM workers, DistributedHTTP, PartitionSample) survive; batches are
    what actually feed the device.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


def _as_column(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    # any python sequence of per-row sequences/arrays becomes an object
    # column — ONE canonical representation for vector-valued columns,
    # regardless of whether rows arrive as lists, tuples, or ndarrays
    if isinstance(values, (list, tuple)) and values and \
            isinstance(values[0], (list, tuple, np.ndarray)):
        from .utils import object_column
        return object_column(values)
    try:
        arr = np.asarray(values)
    except ValueError:
        from .utils import object_column
        return object_column(values)
    if arr.dtype.kind == "U":  # normalize unicode to object for cheap appends
        arr = arr.astype(object)
    if arr.dtype.kind not in "bifuOSU" and arr.ndim == 0:
        raise TypeError(f"cannot build a column from {type(values)}")
    return arr


def _copy_meta(meta: dict[str, dict]) -> dict[str, dict]:
    """Deep-copy column metadata. Metadata is small nested dicts (MML_TAG ->
    {categorical: {...}, kind: ...}); sharing inner dicts across frames lets
    schema taggers mutate upstream frames, so copy all the way down."""
    import copy as _copy
    return {k: _copy.deepcopy(v) for k, v in meta.items()}


class DataFrame:
    """Immutable columnar table. All transforms return new frames (cheap —
    columns are shared, not copied)."""

    def __init__(self, data: dict[str, Any], metadata: Optional[dict[str, dict]] = None,
                 npartitions: int = 1):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for k, v in data.items():
            col = _as_column(v)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(f"column {k!r} length {len(col)} != {n}")
            self._cols[k] = col
        self._n = 0 if n is None else n
        self._meta: dict[str, dict] = _copy_meta(metadata or {})
        self.npartitions = max(1, int(npartitions))

    # ---- construction ----
    @staticmethod
    def fromPandas(pdf, npartitions: int = 1) -> "DataFrame":
        return DataFrame({c: pdf[c].to_numpy() for c in pdf.columns},
                         npartitions=npartitions)

    @staticmethod
    def fromArrow(table, npartitions: int = 1) -> "DataFrame":
        data = {}
        for name, col in zip(table.column_names, table.columns):
            data[name] = col.to_numpy(zero_copy_only=False)
        return DataFrame(data, npartitions=npartitions)

    @staticmethod
    def fromRows(rows: Sequence[dict], npartitions: int = 1) -> "DataFrame":
        if not rows:
            return DataFrame({})
        keys = list(rows[0].keys())
        return DataFrame({k: [r[k] for r in rows] for k in keys},
                         npartitions=npartitions)

    # ---- basic introspection ----
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def count(self) -> int:
        return self._n

    def __len__(self) -> int:
        return self._n

    def col(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(f"no column {name!r}; have {self.columns}")
        return self._cols[name]

    __getitem__ = col

    def dtypes(self) -> dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def metadata(self, name: str) -> dict:
        import copy as _copy
        return _copy.deepcopy(self._meta.get(name, {}))

    def schema(self) -> dict[str, dict]:
        return {k: {"dtype": str(v.dtype), "metadata": self.metadata(k)}
                for k, v in self._cols.items()}

    # ---- transforms (all return new DataFrames) ----
    def _derive(self, cols: dict[str, np.ndarray], meta: dict[str, dict]) -> "DataFrame":
        df = DataFrame({}, npartitions=self.npartitions)
        df._cols = cols
        df._n = len(next(iter(cols.values()))) if cols else 0
        df._meta = meta
        return df

    def select(self, *names: str) -> "DataFrame":
        flat: list[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        return self._derive({n: self.col(n) for n in flat},
                            _copy_meta({n: self._meta[n] for n in flat if n in self._meta}))

    def drop(self, *names: str) -> "DataFrame":
        dropset = set(names)
        return self._derive({k: v for k, v in self._cols.items() if k not in dropset},
                            _copy_meta({k: v for k, v in self._meta.items() if k not in dropset}))

    def withColumn(self, name: str, values, metadata: Optional[dict] = None) -> "DataFrame":
        col = _as_column(values)
        if self._cols and len(col) != self._n:
            raise ValueError(f"new column {name!r} length {len(col)} != {self._n}")
        cols = dict(self._cols)
        cols[name] = col
        meta = _copy_meta(self._meta)
        if metadata is not None:
            meta[name] = _copy_meta({name: metadata})[name]
        elif name in meta:
            del meta[name]  # replaced column loses stale metadata
        return self._derive(cols, meta)

    def withMetadata(self, name: str, metadata: dict) -> "DataFrame":
        self.col(name)
        meta = _copy_meta(self._meta)
        meta[name] = _copy_meta({name: metadata})[name]
        return self._derive(dict(self._cols), meta)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        cols = {}
        for k, v in self._cols.items():
            cols[new if k == old else k] = v
        meta = _copy_meta({(new if k == old else k): v for k, v in self._meta.items()})
        return self._derive(cols, meta)

    def filter(self, mask) -> "DataFrame":
        """mask: boolean array or row-dict predicate."""
        if callable(mask):
            mask = np.fromiter((bool(mask(r)) for r in self.iterRows()),
                               dtype=bool, count=self._n)
        mask = np.asarray(mask, dtype=bool)
        return self._derive({k: v[mask] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return self._derive({k: v[:n] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    def sort(self, name: str, ascending: bool = True) -> "DataFrame":
        order = np.argsort(self.col(name), kind="stable")
        if not ascending:
            order = order[::-1]
        return self._derive({k: v[order] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError("union requires identical column sets")
        cols = {k: np.concatenate([self._cols[k], other._cols[k]]) for k in self._cols}
        return self._derive(cols, _copy_meta(self._meta))

    def dropna(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        names = list(subset) if subset else self.columns
        mask = np.ones(self._n, dtype=bool)
        for nme in names:
            c = self.col(nme)
            if c.dtype.kind == "f":
                mask &= ~np.isnan(c)
            elif c.dtype.kind == "O":
                mask &= np.array([x is not None and x == x for x in c], dtype=bool)
        return self.filter(mask)

    def randomSplit(self, weights: Sequence[float], seed: int = 0) -> list["DataFrame"]:
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._n)
        bounds = np.floor(np.cumsum(w) * self._n).astype(int)
        bounds[-1] = self._n  # cumsum rounding must not drop tail rows
        out, start = [], 0
        for b in bounds:
            idx = np.sort(perm[start:b])
            out.append(self._derive({k: v[idx] for k, v in self._cols.items()},
                                    _copy_meta(self._meta)))
            start = b
        return out

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        mask = rng.random(self._n) < fraction
        return self.filter(mask)

    # ---- partition semantics ----
    def repartition(self, n: int) -> "DataFrame":
        df = self._derive(dict(self._cols), _copy_meta(self._meta))
        df.npartitions = max(1, int(n))
        return df

    coalesce = repartition

    def partitionBounds(self) -> list[tuple[int, int]]:
        edges = np.linspace(0, self._n, self.npartitions + 1).astype(int)
        return [(int(edges[i]), int(edges[i + 1])) for i in range(self.npartitions)]

    def partitions(self) -> Iterator["DataFrame"]:
        for lo, hi in self.partitionBounds():
            yield self._derive({k: v[lo:hi] for k, v in self._cols.items()},
                               _copy_meta(self._meta))

    def mapPartitions(self, fn: Callable[["DataFrame"], "DataFrame"]) -> "DataFrame":
        parts = [fn(p) for p in self.partitions()]
        parts = [p for p in parts if p is not None and len(p.columns)]
        if not parts:
            return DataFrame({})
        names = parts[0].columns
        for p in parts[1:]:
            if set(p.columns) != set(names):
                raise ValueError("mapPartitions results have differing columns")
        cols = {k: np.concatenate([p._cols[k] for p in parts]) for k in names}
        out = parts[0]._derive(cols, _copy_meta(parts[0]._meta))
        out.npartitions = self.npartitions
        return out

    # ---- no-op persistence hooks (API parity with Spark-side Cacher etc.) ----
    def cache(self) -> "DataFrame":
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        return self

    # ---- export ----
    def iterRows(self) -> Iterator[dict]:
        names = self.columns
        cols = [self._cols[n] for n in names]
        for i in range(self._n):
            yield {n: c[i] for n, c in zip(names, cols)}

    def collect(self) -> list[dict]:
        return list(self.iterRows())

    def head(self, n: int = 5) -> list[dict]:
        return self.limit(n).collect()

    def first(self) -> dict:
        if self._n == 0:
            raise IndexError("empty DataFrame")
        return next(self.iterRows())

    def toPandas(self):
        import pandas as pd
        return pd.DataFrame({k: list(v) if v.ndim > 1 or v.dtype.kind == "O" else v
                             for k, v in self._cols.items()})

    def toArrow(self):
        import pyarrow as pa
        return pa.table({k: pa.array(list(v)) if v.dtype.kind == "O" else pa.array(v)
                         for k, v in self._cols.items()})

    def iterBatches(self, batch_size: int) -> Iterator["DataFrame"]:
        for lo in range(0, self._n, batch_size):
            hi = min(lo + batch_size, self._n)
            yield self._derive({k: v[lo:hi] for k, v in self._cols.items()},
                               _copy_meta(self._meta))

    def __repr__(self):
        spec = ", ".join(f"{k}:{v.dtype}" for k, v in self._cols.items())
        return f"DataFrame[{self._n} rows, {self.npartitions} parts]({spec})"
