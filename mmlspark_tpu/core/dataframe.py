"""Columnar DataFrame: the framework's data plane.

The reference rides Spark SQL DataFrames (driver plans, executors hold row
partitions, native code is entered per-partition via mapPartitions — see
SURVEY.md §1/§3). This framework is TPU-native and Spark-free: the data plane
is an immutable columnar table of numpy arrays, designed so whole columns can
be shipped to TPU HBM in one ``jax.device_put`` instead of the reference's
element-wise JNI copies (reference: cntk-model/.../CNTKModel.scala:67-74).

Key properties:
  * columns are numpy arrays (numeric, string/object, or object-structs for
    images); zero-copy from/to pyarrow and pandas where dtypes allow;
  * per-column metadata dict — carries categorical levels and score-column
    tags the way the reference stores them in Spark column metadata under
    ``MMLTag`` (reference: core/schema/.../Categoricals.scala:16-60);
  * logical partitions (``npartitions``) so partition-parallel semantics
    (LightGBM workers, DistributedHTTP, PartitionSample) survive; batches are
    what actually feed the device.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


def _as_column(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    # any python sequence of per-row sequences/arrays becomes an object
    # column — ONE canonical representation for vector-valued columns,
    # regardless of whether rows arrive as lists, tuples, or ndarrays
    if isinstance(values, (list, tuple)) and values and \
            isinstance(values[0], (list, tuple, np.ndarray)):
        from .utils import object_column
        return object_column(values)
    try:
        arr = np.asarray(values)
    except ValueError:
        from .utils import object_column
        return object_column(values)
    if arr.dtype.kind == "U":  # normalize unicode to object for cheap appends
        arr = arr.astype(object)
    if arr.dtype.kind not in "bifuOSU" and arr.ndim == 0:
        raise TypeError(f"cannot build a column from {type(values)}")
    return arr


def _copy_meta(meta: dict[str, dict]) -> dict[str, dict]:
    """Deep-copy column metadata. Metadata is small nested dicts (MML_TAG ->
    {categorical: {...}, kind: ...}); sharing inner dicts across frames lets
    schema taggers mutate upstream frames, so copy all the way down."""
    import copy as _copy
    return {k: _copy.deepcopy(v) for k, v in meta.items()}


class DataFrame:
    """Immutable columnar table. All transforms return new frames (cheap —
    columns are shared, not copied)."""

    def __init__(self, data: dict[str, Any], metadata: Optional[dict[str, dict]] = None,
                 npartitions: int = 1):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for k, v in data.items():
            col = _as_column(v)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(f"column {k!r} length {len(col)} != {n}")
            self._cols[k] = col
        self._n = 0 if n is None else n
        self._meta: dict[str, dict] = _copy_meta(metadata or {})
        self.npartitions = max(1, int(npartitions))

    # ---- construction ----
    @staticmethod
    def fromPandas(pdf, npartitions: int = 1) -> "DataFrame":
        return DataFrame({c: pdf[c].to_numpy() for c in pdf.columns},
                         npartitions=npartitions)

    @staticmethod
    def fromArrow(table, npartitions: int = 1) -> "DataFrame":
        data = {}
        for name, col in zip(table.column_names, table.columns):
            data[name] = col.to_numpy(zero_copy_only=False)
        return DataFrame(data, npartitions=npartitions)

    @staticmethod
    def fromArrowStream(source) -> "DataFrame":
        """Materialize an Arrow record-batch stream (reader, table, batch
        iterable, or IPC file path) — columnar all the way, no Python rows
        (io.arrow; the streaming forms there feed fitStream out-of-core)."""
        from ..io.arrow import frame_from_arrow_stream
        return frame_from_arrow_stream(source)

    @staticmethod
    def fromRows(rows: Sequence[dict], npartitions: int = 1) -> "DataFrame":
        if not rows:
            return DataFrame({})
        keys = list(rows[0].keys())
        return DataFrame({k: [r[k] for r in rows] for k in keys},
                         npartitions=npartitions)

    # ---- basic introspection ----
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def count(self) -> int:
        return self._n

    def __len__(self) -> int:
        return self._n

    def col(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(f"no column {name!r}; have {self.columns}")
        return self._cols[name]

    __getitem__ = col

    def dtypes(self) -> dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def metadata(self, name: str) -> dict:
        import copy as _copy
        return _copy.deepcopy(self._meta.get(name, {}))

    def schema(self) -> dict[str, dict]:
        return {k: {"dtype": str(v.dtype), "metadata": self.metadata(k)}
                for k, v in self._cols.items()}

    # ---- transforms (all return new DataFrames) ----
    def _derive(self, cols: dict[str, np.ndarray], meta: dict[str, dict]) -> "DataFrame":
        df = DataFrame({}, npartitions=self.npartitions)
        df._cols = cols
        df._n = len(next(iter(cols.values()))) if cols else 0
        df._meta = meta
        return df

    def select(self, *names: str) -> "DataFrame":
        flat: list[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        return self._derive({n: self.col(n) for n in flat},
                            _copy_meta({n: self._meta[n] for n in flat if n in self._meta}))

    def drop(self, *names: str) -> "DataFrame":
        dropset = set(names)
        return self._derive({k: v for k, v in self._cols.items() if k not in dropset},
                            _copy_meta({k: v for k, v in self._meta.items() if k not in dropset}))

    def withColumn(self, name: str, values, metadata: Optional[dict] = None) -> "DataFrame":
        col = _as_column(values)
        if self._cols and len(col) != self._n:
            raise ValueError(f"new column {name!r} length {len(col)} != {self._n}")
        cols = dict(self._cols)
        cols[name] = col
        meta = _copy_meta(self._meta)
        if metadata is not None:
            meta[name] = _copy_meta({name: metadata})[name]
        elif name in meta:
            del meta[name]  # replaced column loses stale metadata
        return self._derive(cols, meta)

    def withMetadata(self, name: str, metadata: dict) -> "DataFrame":
        self.col(name)
        meta = _copy_meta(self._meta)
        meta[name] = _copy_meta({name: metadata})[name]
        return self._derive(dict(self._cols), meta)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        cols = {}
        for k, v in self._cols.items():
            cols[new if k == old else k] = v
        meta = _copy_meta({(new if k == old else k): v for k, v in self._meta.items()})
        return self._derive(cols, meta)

    def filter(self, mask) -> "DataFrame":
        """mask: boolean array or row-dict predicate."""
        if callable(mask):
            mask = np.fromiter((bool(mask(r)) for r in self.iterRows()),
                               dtype=bool, count=self._n)
        mask = np.asarray(mask, dtype=bool)
        return self._derive({k: v[mask] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return self._derive({k: v[:n] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    def sort(self, name: str, ascending: bool = True) -> "DataFrame":
        order = np.argsort(self.col(name), kind="stable")
        if not ascending:
            order = order[::-1]
        return self._derive({k: v[order] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError("union requires identical column sets")
        cols = {k: np.concatenate([self._cols[k], other._cols[k]]) for k in self._cols}
        return self._derive(cols, _copy_meta(self._meta))

    def dropna(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        names = list(subset) if subset else self.columns
        mask = np.ones(self._n, dtype=bool)
        for nme in names:
            c = self.col(nme)
            if c.dtype.kind == "f":
                mask &= ~np.isnan(c)
            elif c.dtype.kind == "O":
                mask &= np.array([x is not None and x == x for x in c], dtype=bool)
        return self.filter(mask)

    def randomSplit(self, weights: Sequence[float], seed: int = 0) -> list["DataFrame"]:
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._n)
        bounds = np.floor(np.cumsum(w) * self._n).astype(int)
        bounds[-1] = self._n  # cumsum rounding must not drop tail rows
        out, start = [], 0
        for b in bounds:
            idx = np.sort(perm[start:b])
            out.append(self._derive({k: v[idx] for k, v in self._cols.items()},
                                    _copy_meta(self._meta)))
            start = b
        return out

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        mask = rng.random(self._n) < fraction
        return self.filter(mask)

    # ---- relational ops (Spark surface; numpy-vectorized host ops — the
    # data plane's job is shaping tables, device kernels do the heavy math) --
    def _key_ids(self, names: Sequence[str]):
        """Factorize composite keys -> (int group id per row,
        first-occurrence row per group id)."""
        cols = [self.col(n) for n in names]
        seen: dict[tuple, int] = {}
        ids = np.empty(self._n, dtype=np.int64)
        firsts: list[int] = []
        rows = zip(*[[_hashable(v) for v in c.tolist()] for c in cols])
        for i, t in enumerate(rows):
            g = seen.setdefault(t, len(seen))
            if g == len(firsts):
                firsts.append(i)
            ids[i] = g
        return ids, np.asarray(firsts, dtype=np.int64)

    def groupBy(self, *names: str) -> "GroupedData":
        return GroupedData(self, list(names))

    def distinct(self) -> "DataFrame":
        _, firsts = self._key_ids(self.columns)
        return self._derive({k: v[firsts] for k, v in self._cols.items()},
                            _copy_meta(self._meta))

    def join(self, other: "DataFrame", on, how: str = "inner",
             suffix: str = "_right") -> "DataFrame":
        """Hash join on key column(s). ``how``: inner|left|right|outer.
        Non-key right columns colliding with left names get ``suffix``;
        unmatched rows null-fill (ints widen to float64 + NaN, Spark's
        nullable semantics)."""
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"how must be inner|left|right|outer, got {how!r}")
        on = [on] if isinstance(on, str) else list(on)
        for k in on:  # validate keys exist on both sides (col() raises)
            self.col(k)
            other.col(k)
        # SQL join semantics: a null key matches NOTHING (null = null is not
        # true), while NaN keys DO equate (Spark's join comparator) — so the
        # groupBy/distinct null sentinel must not flow into the hash maps
        rmap: dict[tuple, list[int]] = {}
        for j, t in enumerate(zip(*[[_hashable(v) for v in other.col(k).tolist()]
                                    for k in on])):
            if _NULL_SENTINEL not in t:
                rmap.setdefault(t, []).append(j)
        li: list[int] = []
        ri: list[int] = []
        matched: set[int] = set()
        for i, t in enumerate(zip(*[[_hashable(v) for v in self.col(k).tolist()]
                                    for k in on])):
            js = None if _NULL_SENTINEL in t else rmap.get(t)
            if js:
                for j in js:
                    li.append(i)
                    ri.append(j)
                if how in ("right", "outer"):
                    matched.update(js)
            elif how in ("left", "outer"):
                li.append(i)
                ri.append(-1)
        if how in ("right", "outer"):
            for j in range(other.count()):
                if j not in matched:
                    li.append(-1)
                    ri.append(j)
        lidx = np.asarray(li, dtype=np.int64)
        ridx = np.asarray(ri, dtype=np.int64)
        cols: dict[str, np.ndarray] = {}
        meta: dict[str, dict] = {}
        for k, v in self._cols.items():
            if k in on:
                # a key VALUE exists on >=1 side of every output row (null-
                # keyed rows emit with their own None key, object dtype), so
                # take raw values from whichever side matched — no NaN
                # widening of numeric keys
                rv = other.col(k)
                lg = _safe_take(v, lidx)
                rg = _safe_take(rv, ridx)
                if v.dtype == rv.dtype and v.dtype.kind != "O":
                    src = np.where(lidx >= 0, lg, rg)
                else:
                    src = np.array([a if i >= 0 else b for i, a, b
                                    in zip(lidx, lg, rg)], dtype=object)
            else:
                src = _gather_with_nulls(v, lidx)
            cols[k] = src
            if k in self._meta:
                meta[k] = self._meta[k]
        for k, v in other._cols.items():
            if k in on:
                continue
            name = k + suffix if k in cols else k
            cols[name] = _gather_with_nulls(v, ridx)
            if k in other._meta:
                meta[name] = other._meta[k]
        return DataFrame(cols, metadata=meta, npartitions=self.npartitions)

    # ---- partition semantics ----
    def repartition(self, n: int) -> "DataFrame":
        df = self._derive(dict(self._cols), _copy_meta(self._meta))
        df.npartitions = max(1, int(n))
        return df

    coalesce = repartition

    def partitionBounds(self) -> list[tuple[int, int]]:
        edges = np.linspace(0, self._n, self.npartitions + 1).astype(int)
        return [(int(edges[i]), int(edges[i + 1])) for i in range(self.npartitions)]

    def partitions(self) -> Iterator["DataFrame"]:
        for lo, hi in self.partitionBounds():
            yield self._derive({k: v[lo:hi] for k, v in self._cols.items()},
                               _copy_meta(self._meta))

    def mapPartitions(self, fn: Callable[["DataFrame"], "DataFrame"]) -> "DataFrame":
        parts = [fn(p) for p in self.partitions()]
        parts = [p for p in parts if p is not None and len(p.columns)]
        if not parts:
            return DataFrame({})
        names = parts[0].columns
        for p in parts[1:]:
            if set(p.columns) != set(names):
                raise ValueError("mapPartitions results have differing columns")
        cols = {k: np.concatenate([p._cols[k] for p in parts]) for k in names}
        out = parts[0]._derive(cols, _copy_meta(parts[0]._meta))
        out.npartitions = self.npartitions
        return out

    # ---- no-op persistence hooks (API parity with Spark-side Cacher etc.) ----
    def cache(self) -> "DataFrame":
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        return self

    # ---- export ----
    def iterRows(self) -> Iterator[dict]:
        names = self.columns
        cols = [self._cols[n] for n in names]
        for i in range(self._n):
            yield {n: c[i] for n, c in zip(names, cols)}

    def collect(self) -> list[dict]:
        return list(self.iterRows())

    def head(self, n: int = 5) -> list[dict]:
        return self.limit(n).collect()

    def first(self) -> dict:
        if self._n == 0:
            raise IndexError("empty DataFrame")
        return next(self.iterRows())

    def toPandas(self):
        import pandas as pd
        return pd.DataFrame({k: list(v) if v.ndim > 1 or v.dtype.kind == "O" else v
                             for k, v in self._cols.items()})

    def toArrow(self):
        import pyarrow as pa
        return pa.table({k: pa.array(list(v)) if v.dtype.kind == "O" else pa.array(v)
                         for k, v in self._cols.items()})

    def iterBatches(self, batch_size: int) -> Iterator["DataFrame"]:
        for lo in range(0, self._n, batch_size):
            hi = min(lo + batch_size, self._n)
            yield self._derive({k: v[lo:hi] for k, v in self._cols.items()},
                               _copy_meta(self._meta))

    def __repr__(self):
        spec = ", ".join(f"{k}:{v.dtype}" for k, v in self._cols.items())
        return f"DataFrame[{self._n} rows, {self.npartitions} parts]({spec})"


#: Dict-key stand-ins for NaN / null cells so grouping/distinct/join treat
#: all NaN keys as equal (Spark normalizes NaN equality in these ops; the
#: IEEE default nan != nan would otherwise make every NaN row its own group)
#: and all nulls as equal — but NaN and null stay DISTINCT groups, matching
#: Spark (null is absence, NaN is a float value).
_NAN_SENTINEL = ("__mmltpu_nan__",)
_NULL_SENTINEL = ("__mmltpu_null__",)


def _hashable(v):
    """Dict-key form of a cell value (vector cells -> bytes/tuples,
    struct cells like image rows -> sorted item tuples)."""
    if isinstance(v, np.ndarray):
        return (v.shape, v.tobytes())
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if v is None:
        return _NULL_SENTINEL
    if isinstance(v, float) and v != v:
        return _NAN_SENTINEL
    return v


def _safe_take(col: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """col[clip(idx)] that tolerates an EMPTY col (all idx are then -1 and
    the values are placeholders the caller masks out)."""
    if len(col) == 0:
        if col.dtype.kind == "O":
            return np.full(len(idx), None, dtype=object)
        return np.zeros(len(idx), dtype=col.dtype)
    return col[np.clip(idx, 0, None)]


def _gather_with_nulls(col: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """col[idx] where idx==-1 yields null: NaN for floats (ints widen to
    float64, Spark's nullable-column semantics), None for object columns."""
    if len(col) == 0:  # empty join side: every row is null
        if col.dtype.kind == "O":
            return np.full(len(idx), None, dtype=object)
        return np.full(len(idx), np.nan, dtype=np.float64)
    missing = idx < 0
    safe = np.clip(idx, 0, None)
    if not missing.any():
        return col[safe]
    if col.dtype.kind == "f":
        out = col[safe].copy()
        out[missing] = np.nan
        return out
    if col.dtype.kind in "iub":
        out = col[safe].astype(np.float64)
        out[missing] = np.nan
        return out
    out = col[safe].astype(object)
    out[missing] = None
    return out


_AGG_REDUCERS = {"sum": np.add, "min": np.minimum, "max": np.maximum}


class GroupedData:
    """Result of ``DataFrame.groupBy`` — Spark-style aggregation surface.

    Aggregations run sorted-by-group with ``ufunc.reduceat`` (one vectorized
    pass per (column, fn), no per-group Python loop). Functions: count, sum,
    mean, min, max, first, collect_list (object columns support the last
    three plus count).
    """

    def __init__(self, df: DataFrame, keys: list[str]):
        if not keys:
            raise ValueError("groupBy needs at least one key column")
        self._df = df
        self._keys = keys
        self._ids, self._firsts = df._key_ids(keys)
        # one sort shared by every aggregation in this groupBy
        self._order = np.argsort(self._ids, kind="stable")
        sorted_ids = self._ids[self._order]
        self._starts = (np.flatnonzero(
            np.r_[True, sorted_ids[1:] != sorted_ids[:-1]])
            if len(sorted_ids) else np.empty(0, dtype=np.int64))

    def _key_frame(self) -> dict[str, np.ndarray]:
        out = {}
        for k in self._keys:
            out[k] = self._df.col(k)[self._firsts]
        return out

    def _key_meta(self) -> dict[str, dict]:
        return {k: self._df._meta[k] for k in self._keys
                if k in self._df._meta}

    def _grouped(self, name: str):
        """(values sorted by group id, segment starts) for reduceat."""
        return self._df.col(name)[self._order], self._starts

    def rowGroupIds(self) -> np.ndarray:
        """Group id per ORIGINAL row (first-occurrence order, matching the
        row order of agg()/count() output) — lets callers broadcast
        aggregates back onto the ungrouped frame."""
        return self._ids.copy()

    def agg(self, spec: Optional[dict] = None, /, **named) -> DataFrame:
        """``agg({"col": "mean"})`` -> column ``mean(col)`` (Spark naming), or
        ``agg(out=("col", "mean"))`` for explicit output names."""
        items: list[tuple[str, str, str]] = []  # (out_name, col, fn)
        for col, fn in (spec or {}).items():
            items.append((f"{fn}({col})", col, fn))
        for out, (col, fn) in named.items():
            items.append((out, col, fn))
        if not items:
            raise ValueError("agg needs at least one aggregation")
        clash = [out for out, _, _ in items if out in self._keys]
        if clash:
            raise ValueError(
                f"aggregation output name(s) {clash} collide with group "
                f"key columns; pick different output names")
        cols = self._key_frame()
        n_groups = len(self._firsts)
        counts = np.bincount(self._ids, minlength=n_groups)
        stacked: dict = {}  # per-source-column cell matrix, reused across fns
        for out, col, fn in items:
            if fn == "count":
                cols[out] = counts.astype(np.int64)
                continue
            vals, starts = self._grouped(col)
            if fn == "first":
                cols[out] = self._df.col(col)[self._firsts]
            elif fn == "collect_list":
                from .utils import object_column
                cols[out] = object_column(
                    [list(vals[s:e]) for s, e in
                     zip(starts, np.r_[starts[1:], len(vals)])])
            elif fn in ("sum", "mean") and vals.dtype.kind == "O":
                # vector-valued cells (object column of equal-shape
                # arrays): stack once per source column, segment-reduce
                from .utils import object_column
                if len(vals) == 0:
                    cols[out] = object_column([])
                    continue
                if col not in stacked:
                    try:
                        stacked[col] = np.stack(
                            [np.asarray(v, dtype=np.float64) for v in vals])
                    except (ValueError, TypeError) as e:
                        raise TypeError(
                            f"{fn} on object column {col!r} needs numeric "
                            f"array cells of one common shape ({e})") from e
                mat = stacked[col]
                seg = np.add.reduceat(mat, starts, axis=0)
                if fn == "mean":
                    # divide along the GROUP axis only, whatever the cell rank
                    seg = seg / counts.reshape((-1,) + (1,) * (seg.ndim - 1))
                if mat.ndim < 2:  # numeric scalar cells -> plain column
                    cols[out] = seg
                else:
                    cols[out] = object_column(list(seg))
            elif fn in ("sum", "min", "max"):
                if vals.dtype.kind == "O":
                    raise TypeError(f"{fn} needs a numeric column, "
                                    f"{col!r} is object-typed")
                cols[out] = _AGG_REDUCERS[fn].reduceat(vals, starts)
            elif fn == "mean":
                cols[out] = (np.add.reduceat(vals.astype(np.float64), starts)
                             / counts)
            else:
                raise ValueError(f"unknown aggregation {fn!r}")
        return DataFrame(cols, metadata=self._key_meta(),
                         npartitions=self._df.npartitions)

    def count(self) -> DataFrame:
        if "count" in self._keys:
            raise ValueError("a group key is named 'count'; use "
                             "agg(<name>=(key, 'count')) instead")
        cols = self._key_frame()
        cols["count"] = np.bincount(
            self._ids, minlength=len(self._firsts)).astype(np.int64)
        return DataFrame(cols, metadata=self._key_meta(),
                         npartitions=self._df.npartitions)

    def _all_numeric(self, fn: str, names) -> DataFrame:
        names = list(names) or [c for c in self._df.columns
                                if c not in self._keys
                                and self._df.col(c).dtype.kind in "biuf"]
        if not names:  # no numeric columns: keys only (Spark behavior)
            return DataFrame(self._key_frame(), metadata=self._key_meta(),
                             npartitions=self._df.npartitions)
        return self.agg({c: fn for c in names})

    def sum(self, *names: str) -> DataFrame:
        return self._all_numeric("sum", names)

    def mean(self, *names: str) -> DataFrame:
        return self._all_numeric("mean", names)

    avg = mean

    def min(self, *names: str) -> DataFrame:
        return self._all_numeric("min", names)

    def max(self, *names: str) -> DataFrame:
        return self._all_numeric("max", names)
