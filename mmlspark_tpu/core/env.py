"""Environment / process / file utilities (reference: src/core/env —
EnvironmentUtils.scala:41-50 counts GPUs by shelling out to ``nvidia-smi -L``;
FileUtilities, StreamUtilities.using, ProcessUtils; NativeLoader lives in
mmlspark_tpu.native)."""

from __future__ import annotations

import contextlib
import os
import subprocess
from typing import Iterator, Optional, Sequence


def telemetry_enabled() -> bool:
    """The MMLSPARK_TPU_TELEMETRY=1 global switch: when truthy, the
    telemetry package enables its process-global metrics registry and span
    tracer at import (mmlspark_tpu.telemetry). Default off — a disabled
    registry costs one attribute lookup per call site."""
    return os.environ.get("MMLSPARK_TPU_TELEMETRY", "").strip().lower() \
        in ("1", "true", "yes", "on")


def telemetry_trace_path() -> Optional[str]:
    """MMLSPARK_TPU_TRACE=/path/file.jsonl: export the span buffer as
    Chrome-trace JSON-lines at interpreter exit (telemetry must also be
    enabled for spans to record). A literal ``{pid}`` in the path is
    replaced with the process id — spawned fleet workers inherit the env,
    and per-process files are what ``telemetry.merge_traces`` joins."""
    return os.environ.get("MMLSPARK_TPU_TRACE") or None


def flight_path() -> Optional[str]:
    """MMLSPARK_TPU_FLIGHT: arm the crash flight recorder
    (telemetry.flight) at import. ``=1`` (or any truthy switch) dumps
    bundles to the working directory; ``=/path/dir`` dumps there.
    Returns None (disarmed), "" (armed, default dir) or the directory."""
    v = os.environ.get("MMLSPARK_TPU_FLIGHT", "").strip()
    if not v or v.lower() in ("0", "false", "no", "off"):
        return None
    if v.lower() in ("1", "true", "yes", "on"):
        return ""
    return v


def timeseries_interval() -> Optional[float]:
    """MMLSPARK_TPU_TIMESERIES: arm the time-series sampler
    (telemetry.timeseries) at import. ``=1``/``true`` samples every
    second; a float value (``=0.25``) is the tick interval in seconds.
    Returns None (disarmed) or the interval. Arming also enables
    telemetry."""
    v = os.environ.get("MMLSPARK_TPU_TIMESERIES", "").strip()
    if not v or v.lower() in ("0", "false", "no", "off"):
        return None
    if v.lower() in ("1", "true", "yes", "on"):
        return 1.0
    try:
        iv = float(v)
    except ValueError:
        return 1.0
    return iv if iv > 0 else None


def fault_spec() -> Optional[str]:
    """MMLSPARK_TPU_FAULTS="site:kind:rate[:arg];...": arm the seeded
    fault-injection registry (mmlspark_tpu.resilience.faults) at import.
    Default unset — injection sites are a module-bool check, nothing
    more."""
    return os.environ.get("MMLSPARK_TPU_FAULTS") or None


def sanitize_mode() -> Optional[str]:
    """MMLSPARK_TPU_SANITIZE=donation: arm the donation sanitizer
    (mmlspark_tpu.analysis.sanitize) — donating dispatches poison their
    host-aliased donated inputs after dispatch and trap re-reads.
    MMLSPARK_TPU_SANITIZE=races: arm the race sanitizer
    (mmlspark_tpu.analysis.sanitize_races) — instrumented classes
    record (thread, held-lock set) per shared-field access and trap
    conflicting unlocked cross-thread pairs. Test/chaos-tier knob;
    unset (the default) costs nothing."""
    v = os.environ.get("MMLSPARK_TPU_SANITIZE", "").strip().lower()
    return v or None


def fault_seed() -> int:
    """MMLSPARK_TPU_FAULTS_SEED=<int>: the base seed every fault site's
    RNG derives from (seed ^ crc32(site)) — reruns replay identically."""
    try:
        return int(os.environ.get("MMLSPARK_TPU_FAULTS_SEED", "0"))
    except ValueError:
        return 0


def accelerator_count() -> int:
    """Attached accelerator chips (the GPUCount analog — no nvidia-smi
    subprocess: the JAX runtime already knows)."""
    import jax
    return sum(1 for d in jax.devices() if d.platform != "cpu")


def device_summary() -> dict:
    """Platform/topology snapshot for logs and config records."""
    import jax
    devs = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_count": len(devs),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "device_kinds": sorted({d.device_kind for d in devs}),
    }


@contextlib.contextmanager
def using(*resources) -> Iterator[tuple]:
    """Close every resource on exit, first-error wins (reference
    StreamUtilities.using): an exception from the with-body outranks any
    close()-time error; with a clean body the first close() error raises."""
    try:
        yield resources
    except BaseException:
        for r in resources:
            try:
                r.close()
            except Exception:  # body error is the first error; keep it
                pass
        raise
    else:
        err = None
        for r in resources:
            try:
                r.close()
            except Exception as e:  # noqa: BLE001 - collect, raise once
                err = err or e
        if err is not None:
            raise err


def run_process(cmd: Sequence[str], timeout: float = 600.0,
                check: bool = True) -> tuple[int, str, str]:
    """Run a subprocess, capture (returncode, stdout, stderr) (reference
    ProcessUtils; the reference shells out for ssh/scp/mpirun — here process
    launch is only for tooling, never the compute path)."""
    r = subprocess.run(list(cmd), capture_output=True, text=True,
                       timeout=timeout)
    if check and r.returncode != 0:
        raise RuntimeError(f"{cmd[0]} failed ({r.returncode}): "
                           f"{r.stderr[-500:]}")
    return r.returncode, r.stdout, r.stderr
