from .dataframe import DataFrame
from .params import (BooleanParam, ComplexParam, DictParam, FloatParam,
                     HasFeaturesCol, HasInputCol, HasInputCols, HasLabelCol,
                     HasOutputCol, IntParam, ListParam, Param,
                     ParamValidationError, Params, StringParam)
from .pipeline import (Estimator, Model, Pipeline, PipelineModel,
                       PipelineStage, Transformer, UnaryTransformer,
                       registered_stages)
from .schema import (CategoricalUtilities, SchemaConstants, SparkSchema,
                     findUnusedColumnName, image_to_array, is_image_column,
                     make_binary_row, make_image_row, tag_image_column)
from .serialize import load_stage, save_stage

__all__ = [n for n in dir() if not n.startswith("_")]
