"""Schemas + column metadata conventions.

Mirrors the reference's core/schema package:
  * ImageSchema — image rows as structs (reference:
    src/core/schema/src/main/scala/ImageSchema.scala:11-22);
  * BinaryFileSchema (reference: BinaryFileSchema.scala:11-18);
  * categorical levels carried in column metadata under an ``MMLTag``
    (reference: Categoricals.scala:16-60);
  * score-column tagging so downstream evaluators can find scores/labels by
    role rather than name (reference: SparkSchema.scala:13-80);
  * findUnusedColumnName (reference: DatasetExtensions.scala).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .dataframe import DataFrame

MML_TAG = "mml"

# ---------------------------------------------------------------- ImageSchema

IMAGE_FIELDS = ("path", "height", "width", "type", "bytes")


def make_image_row(path: str, height: int, width: int, channels: int,
                   data: bytes | np.ndarray) -> dict:
    """One image as a struct-row; `type` is the channel count, `bytes` is the
    HWC uint8 buffer (matching the reference's OpenCV byte layout)."""
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data, dtype=np.uint8).tobytes()
    return {"path": path, "height": int(height), "width": int(width),
            "type": int(channels), "bytes": data}


def image_to_array(row: dict) -> np.ndarray:
    """ImageSchema struct → HWC uint8 ndarray."""
    h, w, c = row["height"], row["width"], row["type"]
    return np.frombuffer(row["bytes"], dtype=np.uint8).reshape(h, w, c)


def is_image_column(df: DataFrame, name: str) -> bool:
    md = df.metadata(name).get(MML_TAG, {})
    if md.get("image"):
        return True
    col = df.col(name)
    if col.dtype.kind == "O" and len(col) and isinstance(col[0], dict):
        return set(IMAGE_FIELDS).issubset(col[0].keys())
    return False


def tag_image_column(df: DataFrame, name: str) -> DataFrame:
    md = df.metadata(name)
    md.setdefault(MML_TAG, {})["image"] = True
    return df.withMetadata(name, md)


# ----------------------------------------------------------- BinaryFileSchema

BINARY_FIELDS = ("path", "bytes")


def make_binary_row(path: str, data: bytes) -> dict:
    return {"path": path, "bytes": data}


# ----------------------------------------------------- categorical metadata

class CategoricalUtilities:
    """Store/retrieve categorical level arrays on column metadata.

    Levels leak into learner behavior (one-hot widths, label decode), exactly
    as in the reference (Categoricals.scala:16-60); keeping them on column
    metadata rather than in the model preserves that contract.
    """

    @staticmethod
    def setLevels(df: DataFrame, column: str, levels: Sequence,
                  ordinal: bool = False) -> DataFrame:
        md = df.metadata(column)
        md.setdefault(MML_TAG, {})["categorical"] = {
            "levels": list(levels), "ordinal": bool(ordinal)}
        return df.withMetadata(column, md)

    @staticmethod
    def getLevels(df: DataFrame, column: str) -> Optional[list]:
        cat = df.metadata(column).get(MML_TAG, {}).get("categorical")
        return None if cat is None else list(cat["levels"])

    @staticmethod
    def isCategorical(df: DataFrame, column: str) -> bool:
        return "categorical" in df.metadata(column).get(MML_TAG, {})


# ------------------------------------------------------------- score tagging

class SchemaConstants:
    ScoresColumnKind = "scores"
    ScoredLabelsColumnKind = "scored_labels"
    ScoredProbabilitiesColumnKind = "scored_probabilities"
    TrueLabelsColumnKind = "true_labels"
    ClassificationKind = "classification"
    RegressionKind = "regression"


class SparkSchema:
    """Role-tagging helpers (reference: SparkSchema.scala:13-80)."""

    @staticmethod
    def setColumnKind(df: DataFrame, column: str, kind: str,
                      model_kind: Optional[str] = None) -> DataFrame:
        md = df.metadata(column)
        tag = md.setdefault(MML_TAG, {})
        tag["kind"] = kind
        if model_kind is not None:
            tag["model_kind"] = model_kind
        return df.withMetadata(column, md)

    @staticmethod
    def clearColumnKind(df: DataFrame, column: str) -> DataFrame:
        md = df.metadata(column)
        md.get(MML_TAG, {}).pop("kind", None)
        return df.withMetadata(column, md)

    @staticmethod
    def getColumnKind(df: DataFrame, column: str) -> Optional[str]:
        return df.metadata(column).get(MML_TAG, {}).get("kind")

    @staticmethod
    def findColumnByKind(df: DataFrame, kind: str) -> Optional[str]:
        for c in df.columns:
            if SparkSchema.getColumnKind(df, c) == kind:
                return c
        return None

    @staticmethod
    def setLabelColumnName(df, column, model_kind=None):
        return SparkSchema.setColumnKind(
            df, column, SchemaConstants.TrueLabelsColumnKind, model_kind)

    @staticmethod
    def setScoresColumnName(df, column, model_kind=None):
        return SparkSchema.setColumnKind(
            df, column, SchemaConstants.ScoresColumnKind, model_kind)

    @staticmethod
    def setScoredLabelsColumnName(df, column, model_kind=None):
        return SparkSchema.setColumnKind(
            df, column, SchemaConstants.ScoredLabelsColumnKind, model_kind)

    @staticmethod
    def setScoredProbabilitiesColumnName(df, column, model_kind=None):
        return SparkSchema.setColumnKind(
            df, column, SchemaConstants.ScoredProbabilitiesColumnKind, model_kind)


# ----------------------------------------------------------------- utilities

def findUnusedColumnName(prefix: str, df: DataFrame) -> str:
    """reference: DatasetExtensions.findUnusedColumnName."""
    name, i = prefix, 0
    existing = set(df.columns)
    while name in existing:
        i += 1
        name = f"{prefix}_{i}"
    return name
