"""Whole-pipeline capture: cross-stage XLA fusion for PipelineModel.

A ``Pipeline`` of N stages used to run N separate jitted programs with a
host-numpy columnar round-trip between every pair (``PipelineModel.
transform`` chained per-stage ``transform`` calls), so a featurize→predict
chain paid N dispatches plus device→host→device transfers XLA could fuse
away. The Julia-to-TPU paper (PAPERS.md, arxiv 1810.09868) makes the case
for compiling the *whole program*, not fragments — this module is that
refactor's core:

* every ``Transformer``/``Model`` may expose a :class:`StageCapture` — a
  traced, shape-polymorphic description of its device computation
  (``capture(columns)``); host-only stages (``UDFTransformer``,
  ``Repartition``, ``Cacher``, ...) declare themselves uncapturable with
  the ``_uncapturable = True`` class marker (the explicit form graftlint's
  ``pipeline-capture-coverage`` rule checks for);
* :func:`run_fused_pipeline` composes consecutive capturable stages into
  **maximal fused segments**, each compiled as ONE program through
  :class:`~..telemetry.profiler.ProfiledFunction` (AOT lower/compile
  cache, FLOPs/bytes cost analysis, recompile-cause counters) — arrays
  stay on device across stage boundaries inside a segment, and the
  intermediate columns a later stage drops never return to host at all;
* the fused segment callable is also the serving composite: ``io/serving``
  builds a :class:`FusedServingStep` body from it
  (``FusedServingStep.from_pipeline``) and serializes the per-bucket
  executables into the manifest-committed bundle, so a worker loads a
  featurize→predict *pipeline* warm.

Capture contract (``StageCapture``): ``fn(params, inputs) -> outputs``
is a pure traceable function over device arrays — ``params`` an arbitrary
pytree of constants (weights, tables; ``{}`` when none), ``inputs`` a
tuple of column arrays aligned with ``capture.inputs``, returning a
tuple aligned with ``capture.outputs``. ``drops`` removes columns
(Select/Drop/Rename semantics); unmentioned columns pass through on
host, untouched. Compute runs in the device dtypes (f32/i32) — stages
whose host path computes in float64 document the fused path as f32.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from .. import telemetry
from .utils import get_logger

log = get_logger("pipeline")

_m_segments = telemetry.registry.gauge(
    "mmlspark_pipeline_segments",
    "fused segments in the last fused PipelineModel.transform plan")
_m_fused_dispatches = telemetry.registry.counter(
    "mmlspark_pipeline_fused_dispatches_total",
    "fused-segment device dispatches (one per segment execution — the "
    "staged path would have paid one per stage)")
_m_staged_stages = telemetry.registry.counter(
    "mmlspark_pipeline_staged_stage_transforms_total",
    "stages executed via their own transform inside a fused "
    "PipelineModel.transform (uncapturable, ineligible inputs, or a "
    "segment of one)")
_m_fallbacks = telemetry.registry.counter(
    "mmlspark_pipeline_fusion_fallbacks_total",
    "planned fused segments that fell back to staged execution at "
    "encode time (a column the cheap planner predicate accepted turned "
    "out not to be device-encodable, e.g. ragged rows)")
_m_transfer = telemetry.registry.counter(
    "mmlspark_pipeline_transfer_bytes_total",
    "host<->device bytes moved at fused-segment boundaries; within a "
    "segment stage-to-stage traffic is zero by construction. phase="
    "transform counts PipelineModel.transform segments, phase=fit the "
    "fused featurize->train fit path (raw wire-dtype rows in, learner "
    "state out)",
    labels=("direction", "phase"))
_m_fit_fused = telemetry.registry.counter(
    "mmlspark_fit_fused_dispatches_total",
    "fused featurize->train device dispatches on the fit side (one per "
    "train step / scan window / binning slab whose featurization ran "
    "inside the same XLA program as the consumer)")
_m_fit_fallbacks = telemetry.registry.counter(
    "mmlspark_fit_fusion_fallbacks_total",
    "Pipeline.fit calls that requested fusePipeline but fell back to "
    "the staged fit (uncapturable prefix stage, non-encodable raw "
    "column, or a learner that declined the fused plan)")


def count_fit_transfer(direction: str, nbytes) -> None:
    """Account fit-side host<->device traffic under phase="fit" (the
    trainer's raw-row uploads and the GBDT fused-binning slabs)."""
    _m_transfer.labels(direction=direction, phase="fit").inc(float(nbytes))


class StageCapture:
    """A stage's device computation as a traced callable.

    ``fn(params, inputs)``: pure traceable function; ``inputs`` aligned
    with :attr:`inputs`, returns value(s) aligned with :attr:`outputs`.
    ``drops`` names columns the stage removes. ``host_cast`` maps output
    columns to a numpy dtype applied at readback (e.g. prediction
    columns stay float64 like the staged path). ``finalize`` is an
    optional host-side ``df -> df`` hook applied after the segment's
    frame is rebuilt (column-metadata tagging — SparkSchema score
    kinds)."""

    __slots__ = ("fn", "inputs", "outputs", "params", "drops",
                 "host_cast", "finalize", "tag")

    def __init__(self, fn: Callable, inputs: Sequence[str] = (),
                 outputs: Sequence[str] = (), *, params: Any = None,
                 drops: Sequence[str] = (),
                 host_cast: Optional[dict] = None,
                 finalize: Optional[Callable] = None, tag: str = ""):
        self.fn = fn
        self.inputs = tuple(inputs)
        self.outputs = tuple(outputs)
        self.params = {} if params is None else params
        self.drops = tuple(drops)
        self.host_cast = dict(host_cast or {})
        self.finalize = finalize
        self.tag = tag


# ------------------------------------------------------------- host encoding

def encodable(col: np.ndarray) -> bool:
    """Cheap planning predicate: can this column feed the device?
    (Numeric arrays; object columns of numeric vectors/scalars. The
    authoritative check is :func:`encode_column` — ragged rows pass
    here and fall back there.)"""
    if col.dtype.kind in "biuf":
        return True
    if col.dtype.kind != "O":
        return False
    if len(col) == 0:
        return True
    v = col[0]
    if isinstance(v, np.ndarray):
        return v.dtype.kind in "biuf"
    if isinstance(v, (list, tuple)):
        return len(v) == 0 or isinstance(v[0], (int, float, np.number))
    return isinstance(v, (int, float, np.number)) \
        and not isinstance(v, bool)


def encode_column(col: np.ndarray) -> Optional[np.ndarray]:
    """Column -> device-feedable host array (None when it has no device
    encoding). Numeric columns ship as-is; object columns of fixed-shape
    numeric vectors become the (n, d) float32 matrix (the TpuModel wire
    convention, ``core.utils.to_float32_matrix``)."""
    if col.dtype.kind in "biuf":
        return col
    if col.dtype.kind != "O":
        return None
    from .utils import to_float32_matrix
    try:
        return to_float32_matrix(col)
    except (ValueError, TypeError):
        return None


def decode_column(arr: np.ndarray) -> np.ndarray:
    """Device output -> DataFrame column (2D+ becomes an object column
    of per-row vectors, the frame's canonical vector form)."""
    if arr.ndim <= 1:
        return arr
    from .utils import object_column
    return object_column(arr)


# ------------------------------------------------------------- fused runner

class _Segment:
    """One maximal run of capturable stages + its name-flow plan."""

    __slots__ = ("pairs", "in_names", "out_names", "names", "host_cast")

    def __init__(self, pairs, df_columns):
        self.pairs = list(pairs)          # [(stage, capture), ...]
        produced: set = set()
        in_names: list = []
        names = list(df_columns)          # running column order
        host_cast: dict = {}
        for _, cap in self.pairs:
            for i in cap.inputs:
                if i not in produced and i not in in_names:
                    in_names.append(i)
            for d in cap.drops:
                if d in names:
                    names.remove(d)
                produced.discard(d)
            for o in cap.outputs:
                if o not in names:
                    names.append(o)
                produced.add(o)
            host_cast.update(cap.host_cast)
        self.in_names = in_names
        self.names = names
        self.out_names = [n for n in names if n in produced]
        self.host_cast = host_cast


def _param_key(tree) -> tuple:
    """Cache-validity key for a segment's capture params: array leaves
    by identity (the framework-wide convention — updating weights means
    a NEW tree, TpuModel._device_params), scalar leaves by value (a
    fresh ``[0.5]`` fills list every transform must still hit)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (hash(treedef),
            tuple(x if isinstance(x, (int, float, str, bool, bytes,
                                      type(None)))
                  else id(x) for x in leaves))


def _segment_program(owner, seg: _Segment, seg_index: int):
    """The ONE jitted program for a segment, via ProfiledFunction's AOT
    lower/compile cache (compile counts + recompile causes observable),
    cached on the owning PipelineModel. Capture params are device-put
    once per (segment, params-identity) — re-shipping model weights per
    transform would dominate small-batch latency (the TpuModel
    ``_device_params`` convention: updating weights means new trees)."""
    import jax
    caps = [c for _, c in seg.pairs]
    # simple (jsonable) params pin the traced structure: a config change
    # that alters the capture's computation without renaming columns
    # (e.g. DataConversion.convertTo) must not reuse a stale program
    key = (tuple(s.uid for s, _ in seg.pairs),
           tuple(repr(sorted(s._jsonParams().items()))
                 for s, _ in seg.pairs),
           tuple(seg.in_names), tuple(seg.out_names))
    cache = getattr(owner, "_seg_cache", None)
    if cache is None:
        cache = owner._seg_cache = {}
    entry = cache.get(key)
    params = tuple(c.params for c in caps)
    if entry is None or entry["param_ids"] != _param_key(params):
        in_names, out_names = list(seg.in_names), list(seg.out_names)
        fns = [(c.fn, c.inputs, c.drops, c.outputs) for c in caps]

        def seg_fn(param_tuple, arrays):
            cols = dict(zip(in_names, arrays))
            for (fn, inputs, drops, outputs), p in zip(fns, param_tuple):
                vals = fn(p, tuple(cols[i] for i in inputs))
                if not isinstance(vals, (tuple, list)):
                    vals = (vals,)
                for d in drops:
                    cols.pop(d, None)
                cols.update(zip(outputs, vals))
            return tuple(cols[n] for n in out_names)

        tag = f"pipeline.seg{seg_index}.{getattr(owner, 'uid', 'anon')}"
        entry = {
            "pf": telemetry.profiler.wrap(jax.jit(seg_fn), tag, aot=True),
            "params_dev": jax.device_put(params),
            "param_ids": _param_key(params),
        }
        cache[key] = entry
    return entry["pf"], entry["params_dev"]


def _run_segment(owner, seg: _Segment, df, seg_index: int):
    """Execute one fused segment: encode inputs, ONE device dispatch,
    decode outputs, rebuild the frame (pass-through columns keep their
    values and metadata; produced columns land in staged order)."""
    from .dataframe import DataFrame
    arrays = []
    for n in seg.in_names:
        a = encode_column(df.col(n))
        if a is None:       # the cheap planner predicate over-promised
            _m_fallbacks.inc()
            log.warning("fused segment fell back to staged execution: "
                        "column %r is not device-encodable", n)
            cur = df
            for stage, _ in seg.pairs:
                _m_staged_stages.inc()
                cur = stage.transform(cur)
            return cur
        arrays.append(np.ascontiguousarray(a))
    pf, params_dev = _segment_program(owner, seg, seg_index)
    _m_transfer.labels(direction="in", phase="transform").inc(
        float(sum(a.nbytes for a in arrays)))
    with telemetry.trace.span("pipeline/segment", stages=len(seg.pairs),
                              rows=len(df)):
        outs = pf(params_dev, tuple(arrays))
    _m_fused_dispatches.inc()
    outs = [np.asarray(o) for o in outs]
    _m_transfer.labels(direction="out", phase="transform").inc(
        float(sum(o.nbytes for o in outs)))
    outmap = dict(zip(seg.out_names, outs))
    data, meta = {}, {}
    for n in seg.names:
        if n in outmap:
            arr = outmap[n]
            if n in seg.host_cast:
                arr = arr.astype(seg.host_cast[n])
            data[n] = decode_column(arr)
        else:
            data[n] = df.col(n)
            m = df.metadata(n)
            if m:
                meta[n] = m
    cur = DataFrame(data, metadata=meta, npartitions=df.npartitions)
    for _, cap in seg.pairs:
        # a later stage may have renamed/dropped this capture's outputs
        # (finalize hooks tag column metadata by name); tag only what
        # survived the whole segment
        if cap.finalize is not None and all(o in data
                                            for o in cap.outputs):
            cur = cap.finalize(cur)
    return cur


def stage_capture(stage, columns) -> Optional[StageCapture]:
    """A stage's capture for the given column-name schema, honoring the
    explicit ``_uncapturable`` marker; None when the stage cannot (or
    declines to) describe its computation."""
    if getattr(type(stage), "_uncapturable", False):
        return None
    cap_fn = getattr(stage, "capture", None)
    if cap_fn is None:
        return None
    return cap_fn(list(columns))


def run_fused_pipeline(owner, stages, df):
    """``PipelineModel.transform`` with cross-stage fusion: walk the
    stages left-to-right, accumulating consecutive capturable stages
    (whose capture inputs are device-encodable under the running schema)
    into maximal segments; each segment of >= 2 stages runs as ONE
    compiled program, everything else runs its own ``transform``.
    Uncapturable stages therefore split segments at prefix/middle/suffix
    positions and the plan degrades gracefully to the staged chain."""
    cur = df
    pending: list = []
    schema = {n: encodable(df.col(n)) for n in df.columns}
    segments = 0

    def flush():
        nonlocal cur, pending, segments
        if not pending:
            return
        if len(pending) >= 2:
            seg = _Segment(pending, list(cur.columns))
            cur = _run_segment(owner, seg, cur, segments)
            segments += 1
        else:
            for stage, _ in pending:
                _m_staged_stages.inc()
                cur = stage.transform(cur)
        pending = []

    for stage in stages:
        cap = stage_capture(stage, list(schema))
        if cap is not None and all(schema.get(i, False)
                                   for i in cap.inputs):
            pending.append((stage, cap))
            for d in cap.drops:
                schema.pop(d, None)
            for o in cap.outputs:
                schema[o] = True
        else:
            flush()
            _m_staged_stages.inc()
            cur = stage.transform(cur)
            schema = {n: encodable(cur.col(n)) for n in cur.columns}
    flush()
    _m_segments.set(segments)
    return cur


def whole_pipeline_capture(stages, input_cols: Sequence[str]):
    """One :class:`_Segment` covering EVERY stage, or raise — the serving
    composite's contract (``FusedServingStep.from_pipeline``): a bundle
    must not silently serve a half-fused pipeline. ``input_cols`` seed
    the schema (all assumed device-encodable wire inputs)."""
    schema = {n: True for n in input_cols}
    pairs = []
    for stage in stages:
        cap = stage_capture(stage, list(schema))
        if cap is None:
            raise ValueError(
                f"stage {type(stage).__name__} ({stage.uid}) is not "
                f"capturable; a pipeline serving composite needs every "
                f"stage to expose a capture")
        missing = [i for i in cap.inputs if not schema.get(i, False)]
        if missing:
            raise ValueError(
                f"stage {type(stage).__name__} reads column(s) {missing} "
                f"that no earlier stage produces and no input column "
                f"provides")
        pairs.append((stage, cap))
        for d in cap.drops:
            schema.pop(d, None)
        for o in cap.outputs:
            schema[o] = True
    return _Segment(pairs, list(input_cols))


def segment_body(seg: _Segment, out_name: str):
    """``(body(params, cols_tuple) -> out array, params)`` for a serving
    composite built over ``seg`` — the traced whole-pipeline callable the
    fused serving step compiles per bucket."""
    if out_name not in seg.out_names:
        raise ValueError(f"pipeline produces {seg.out_names}, not "
                         f"{out_name!r}")
    caps = [c for _, c in seg.pairs]
    fns = [(c.fn, c.inputs, c.drops, c.outputs) for c in caps]
    in_names = list(seg.in_names)
    params = tuple(c.params for c in caps)

    def body(param_tuple, arrays):
        cols = dict(zip(in_names, arrays))
        for (fn, inputs, drops, outputs), p in zip(fns, param_tuple):
            vals = fn(p, tuple(cols[i] for i in inputs))
            if not isinstance(vals, (tuple, list)):
                vals = (vals,)
            for d in drops:
                cols.pop(d, None)
            cols.update(zip(outputs, vals))
        return cols[out_name]

    return body, params


# ------------------------------------------------------------- fit-side plan

class FitCapturePlan:
    """The featurize prefix of a ``Pipeline.fit``, composed into ONE traced
    ``body(param_tuple, raw_arrays) -> (xb, yb)``.

    Built by :func:`compose_fit_capture` when EVERY stage ahead of the
    final estimator captures; the learner folds :meth:`body` into its
    per-step program (train step, scan body, or GBDT binning slab), so
    raw wire-dtype rows are the only fit-time H2D traffic and the
    intermediate featurized columns never exist on host.

    ``params`` are fit-constants (fill values, conversion targets —
    computed once, before training): checkpoints store learner state
    only and record :meth:`digest` in the manifest so a resume can
    verify it re-enters the *same* fused program bit-exact.

    ``fitted`` holds the prefix stages as they would appear in the
    resulting ``PipelineModel`` (transformers as-is, estimators as their
    fitted models) — also the staged-fallback executor
    (:meth:`apply_staged`). ``metadata`` carries column metadata a stage
    chose to surface without staging (``capture_metadata`` hook — the
    assembled categorical slot ranges GBDT reads)."""

    __slots__ = ("pairs", "fitted", "in_names", "features_col",
                 "label_col", "params", "metadata", "_fns", "_params_dev")

    def __init__(self, pairs, fitted, df_columns, features_col: str,
                 label_col: str, metadata: Optional[dict] = None):
        self.pairs = list(pairs)
        self.fitted = list(fitted)
        seg = _Segment(self.pairs, df_columns)
        in_names = list(seg.in_names)
        produced = set()
        for _, cap in self.pairs:
            produced.update(cap.outputs)
        for need in (features_col, label_col):
            # raw pass-through targets (an untouched label column) ride
            # along as extra wire inputs
            if need not in produced and need not in in_names:
                in_names.append(need)
        self.in_names = in_names
        self.features_col = features_col
        self.label_col = label_col
        self.params = tuple(cap.params for _, cap in self.pairs)
        self.metadata = dict(metadata or {})
        self._fns = [(cap.fn, cap.inputs, cap.drops, cap.outputs)
                     for _, cap in self.pairs]
        self._params_dev = None

    def body(self, param_tuple, arrays):
        """Pure traceable featurize composition: raw column arrays (one
        per :attr:`in_names` entry, batch-leading) -> ``(xb, yb)``.
        Computes in device dtypes — ``host_cast`` is a readback concern
        the fit side never pays."""
        cols = dict(zip(self.in_names, arrays))
        for (fn, inputs, drops, outputs), p in zip(self._fns, param_tuple):
            vals = fn(p, tuple(cols[i] for i in inputs))
            if not isinstance(vals, (tuple, list)):
                vals = (vals,)
            for d in drops:
                cols.pop(d, None)
            cols.update(zip(outputs, vals))
        return cols[self.features_col], cols[self.label_col]

    # ---- host-side helpers -------------------------------------------
    def encode(self, df) -> Optional[list]:
        """Raw wire arrays for :attr:`in_names` (contiguous, wire dtypes
        — ints/bools ship un-widened); None when a column turns out not
        to be device-encodable (caller falls back staged)."""
        arrays = []
        for n in self.in_names:
            a = encode_column(df.col(n))
            if a is None:
                return None
            arrays.append(np.ascontiguousarray(a))
        return arrays

    def device_params(self):
        """The capture params, device-put once per plan (fit-constants —
        re-shipping them per step would defeat the donated step)."""
        if self._params_dev is None:
            import jax
            self._params_dev = jax.device_put(self.params)
        return self._params_dev

    def apply_staged(self, df):
        """The staged equivalent (fallback path): run every fitted
        prefix stage's own transform."""
        cur = df
        for stage in self.fitted:
            _m_staged_stages.inc()
            cur = stage.transform(cur)
        return cur

    def key(self) -> tuple:
        """Trace-identity key for caching the fused program wrapper —
        same convention as :func:`_segment_program` (stage uids + json
        params pin the traced structure, ``_param_key`` pins the
        constant leaves)."""
        return (tuple(s.uid for s, _ in self.pairs),
                tuple(repr(sorted(s._jsonParams().items()))
                      for s, _ in self.pairs),
                tuple(self.in_names), self.features_col, self.label_col,
                _param_key(self.params))

    def digest(self) -> str:
        """Content hash over the plan's structure AND param bytes —
        recorded in checkpoint manifests so resume verifies the fused
        featurization is byte-identical to the one that produced the
        checkpoint (fill values recomputed over different data would
        silently change the model being trained)."""
        import hashlib
        import jax
        h = hashlib.sha256()
        for stage, _ in self.pairs:
            h.update(type(stage).__name__.encode())
            h.update(repr(sorted(stage._jsonParams().items())).encode())
        h.update(("|".join(self.in_names) + "->" + self.features_col
                  + "," + self.label_col).encode())
        for leaf in jax.tree_util.tree_leaves(self.params):
            arr = np.asarray(leaf)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()


def compose_fit_capture(stages, df, features_col: Optional[str],
                        label_col: Optional[str]):
    """Compose the featurize prefix of a fit into a
    :class:`FitCapturePlan`, or None when it must stay staged.

    Walks ``stages`` (everything ahead of the final learner) like
    :func:`run_fused_pipeline`, but the fused fit only engages when the
    prefix is *fully* capturable — a staged stage in the middle would
    re-materialize the frame and forfeit the raw-wire H2D win, so any
    uncapturable stage (or a capture input that is not device-encodable
    under the running schema) rejects the whole plan.

    Estimator prefix stages (CleanMissingData) use fit-then-capture:
    the staged frame is materialized lazily, only up to the stage being
    fitted, to compute its fit-constants — a one-time host pass, after
    which training runs fused. Transformer-only prefixes stage nothing.
    """
    from .pipeline import Estimator, Transformer
    if not stages or features_col is None or label_col is None:
        return None
    schema = {n: encodable(df.col(n)) for n in df.columns}
    pairs: list = []
    fitted: list = []
    metadata: dict = {}
    staged = {"df": df, "applied": 0}

    def staged_upto(k):
        # lazy staged materialization for fit-then-capture estimators
        while staged["applied"] < k:
            staged["df"] = fitted[staged["applied"]].transform(staged["df"])
            staged["applied"] += 1
        return staged["df"]

    for stage in stages:
        if isinstance(stage, Estimator) and not isinstance(stage,
                                                           Transformer):
            model = stage.fit(staged_upto(len(fitted)))
        else:
            model = stage
        cap = stage_capture(model, list(schema))
        if cap is None or not all(schema.get(i, False)
                                  for i in cap.inputs):
            log.info("fit-side fusion declined: stage %s does not "
                     "capture under the running schema",
                     type(stage).__name__)
            return None
        meta_fn = getattr(model, "capture_metadata", None)
        if meta_fn is not None and cap.outputs:
            m = meta_fn(df)
            if m:
                metadata[cap.outputs[0]] = m
        pairs.append((model, cap))
        fitted.append(model)
        for d in cap.drops:
            schema.pop(d, None)
        for o in cap.outputs:
            schema[o] = True
    if not schema.get(features_col, False) \
            or not schema.get(label_col, False):
        log.info("fit-side fusion declined: %r/%r not produced by the "
                 "prefix and not device-encodable in the raw frame",
                 features_col, label_col)
        return None
    return FitCapturePlan(pairs, fitted, list(df.columns), features_col,
                          label_col, metadata=metadata)
