"""Estimator / Transformer / Pipeline contract + stage registry.

Mirrors Spark ML's stage algebra that the whole reference is built on
(every reference component is an Estimator or Transformer — SURVEY.md §1),
plus the reference's reflective stage discovery used by its fuzzing coverage
gate (reference: src/core/utils/src/main/scala/JarLoadingUtils.scala:18-60):
here, every concrete PipelineStage subclass self-registers at class-creation
time, and tests/test_fuzzing.py iterates the registry the way the reference's
FuzzingTest.scala:25-130 iterates the built jars.
"""

from __future__ import annotations

import uuid as _uuid
from typing import Optional

from .dataframe import DataFrame
from .params import BooleanParam, ComplexParam, Params

# fully-qualified name -> class, for serialization lookup and fuzzing coverage
STAGE_REGISTRY: dict[str, type] = {}


def _qualname(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


def registered_stages() -> dict[str, type]:
    return dict(STAGE_REGISTRY)


def lookup_stage_class(name: str) -> type:
    """Resolve a stage class by fully-qualified name, or by bare class name
    when that is unambiguous across the registry."""
    if name in STAGE_REGISTRY:
        return STAGE_REGISTRY[name]
    matches = [c for q, c in STAGE_REGISTRY.items()
               if q.rsplit(".", 1)[-1] == name]
    if len(matches) == 1:
        return matches[0]
    if not matches:
        raise KeyError(f"stage class {name!r} not in registry")
    raise KeyError(f"stage class name {name!r} is ambiguous: "
                   f"{[_qualname(m) for m in matches]}")


class PipelineStage(Params):
    """Base of everything fit/transform-shaped. Subclasses auto-register."""

    _abstract = True  # subclasses default to concrete unless they re-declare

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if not cls.__dict__.get("_abstract", False):
            STAGE_REGISTRY[_qualname(cls)] = cls

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.uid = f"{type(self).__name__}_{_uuid.uuid4().hex[:12]}"

    # save/load (implemented in core.serialize; attached there to avoid cycle)
    def save(self, path: str, overwrite: bool = True):
        from . import serialize
        serialize.save_stage(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        from . import serialize
        return serialize.load_stage(path)

    def __repr__(self):
        shown = {k: v for k, v in self._paramMap.items()
                 if self._params[k].jsonable}
        return f"{type(self).__name__}({shown})"


class Transformer(PipelineStage):
    _abstract = True

    #: explicit "host-only stage" marker: a Transformer whose transform
    #: dispatches device computation must either expose a capture() or
    #: set this True (enforced by graftlint's pipeline-capture-coverage)
    _uncapturable = False

    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def capture(self, columns):
        """This stage's device computation as a traced callable
        (:class:`~.capture.StageCapture`), given the incoming column
        names — or None when the stage cannot describe one (the default:
        stages opt IN to cross-stage fusion). Host-only stages set
        ``_uncapturable = True`` instead of overriding this."""
        return None

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""
    _abstract = True


class Estimator(PipelineStage):
    _abstract = True

    def fit(self, df: DataFrame) -> Model:
        raise NotImplementedError


class UnaryTransformer(Transformer):
    """Convenience: inputCol -> outputCol via _transform_column."""
    _abstract = True

    def _transform_column(self, values, df: DataFrame):
        raise NotImplementedError

    def transform(self, df: DataFrame) -> DataFrame:
        inp = self.getOrDefault("inputCol")
        out = self.getOrDefault("outputCol")
        return df.withColumn(out, self._transform_column(df.col(inp), df))


class Pipeline(Estimator):
    """Chain of stages; fit() fits estimators in order, threading transforms
    (same contract as Spark ML Pipeline, which reference notebooks rely on)."""

    stages = ComplexParam("ordered list of PipelineStages", default=())
    fusePipeline = BooleanParam(
        "fuse the FIT side: compose the maximal prefix of capturable "
        "featurize stages into ONE traced featurize body folded into the "
        "final estimator's per-step training program (core/capture.py "
        "fit-side capture) — raw wire-dtype rows are the only fit-time "
        "host->device traffic and intermediate featurized columns never "
        "touch host. Engages only when EVERY stage ahead of the final "
        "estimator captures AND the estimator accepts a fused plan "
        "(TpuLearner, LightGBM*); anything else falls back to the staged "
        "fit (mmlspark_fit_fusion_fallbacks_total counts these). The "
        "returned PipelineModel has fusePipeline set so transform fuses "
        "too. Fused featurization computes in device dtypes "
        "(docs/performance.md, Fit-side fusion)", default=False)

    def fit(self, df: DataFrame) -> "PipelineModel":
        stages = list(self.getOrDefault("stages"))
        if self.getOrDefault("fusePipeline") and len(stages) >= 2:
            fused = self._fit_fused(df, stages)
            if fused is not None:
                return fused
        fitted = []
        cur = df
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage!r} is neither Estimator nor Transformer")
        return PipelineModel().setStages(tuple(fitted))

    def _fit_fused(self, df: DataFrame, stages) -> Optional["PipelineModel"]:
        """The fused featurize->train fit, or None to fall back staged.

        The final stage must be an Estimator exposing ``_fit_captured``
        (the fused-fit hook: takes the raw frame plus a
        :class:`~.capture.FitCapturePlan`, may itself return None to
        decline — e.g. a GBDT configured for a path the fused binner
        does not cover). Every stage ahead of it must capture; a partial
        prefix would still stage the remainder and forfeit the raw-wire
        H2D win, so it is not worth the second code path."""
        from .capture import _m_fit_fallbacks, compose_fit_capture
        last = stages[-1]
        hook = getattr(last, "_fit_captured", None)
        if not isinstance(last, Estimator) or hook is None:
            _m_fit_fallbacks.inc()
            return None
        get_f = getattr(last, "getFeaturesCol", None)
        get_l = getattr(last, "getLabelCol", None)
        plan = compose_fit_capture(
            stages[:-1], df,
            get_f() if get_f else None, get_l() if get_l else None)
        if plan is None:
            _m_fit_fallbacks.inc()
            return None
        model = hook(df, plan)
        if model is None:
            _m_fit_fallbacks.inc()
            return None
        pm = PipelineModel().setStages(tuple(plan.fitted + [model]))
        return pm.setFusePipeline(True)

    def transform(self, df: DataFrame) -> DataFrame:
        """Only valid for all-transformer pipelines; refitting estimators on
        the transform input would be silent train/test leakage."""
        bad = [type(s).__name__ for s in self.getOrDefault("stages")
               if isinstance(s, Estimator) and not isinstance(s, (Transformer, Pipeline))]
        if bad:
            raise TypeError(
                "Pipeline.transform called on a pipeline containing unfitted "
                f"Estimators {bad}; call fit() first")
        return self.fit(df).transform(df)


class PipelineModel(Model):
    #: as a STAGE of an outer pipeline a nested PipelineModel runs its
    #: own transform (which may itself fuse internally) — it does not
    #: flatten into the outer segment
    _uncapturable = True
    stages = ComplexParam("ordered list of fitted Transformers", default=())
    fusePipeline = BooleanParam(
        "compose consecutive capturable stages into maximal fused "
        "segments, each compiled as ONE XLA program (core/capture.py): "
        "arrays stay on device across stage boundaries inside a segment, "
        "so an N-stage chain pays number-of-segments dispatches instead "
        "of N, and zero host round-trips between fused stages. "
        "Uncapturable stages split segments and run their own transform. "
        "Fused compute runs in device dtypes (f32/i32); stages whose "
        "host path computes in float64 differ at f32 precision "
        "(docs/performance.md, Cross-stage fusion)", default=False)

    def transform(self, df: DataFrame) -> DataFrame:
        stages = self.getOrDefault("stages")
        if self.getOrDefault("fusePipeline") and len(stages) >= 2:
            from .capture import run_fused_pipeline
            return run_fused_pipeline(self, stages, df)
        cur = df
        for stage in stages:
            cur = stage.transform(cur)
        return cur
