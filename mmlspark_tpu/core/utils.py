"""Small shared utilities (reference: core/env FileUtilities/StreamUtilities/
Logging, core/utils CastUtilities)."""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import time
from typing import Iterator

import numpy as np


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(f"mmlspark_tpu.{name}")
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(h)
        logger.setLevel(os.environ.get("MMLSPARK_TPU_LOGLEVEL", "WARNING"))
    return logger


@contextlib.contextmanager
def timed(label: str, logger: logging.Logger | None = None) -> Iterator[dict]:
    out = {"label": label}
    t0 = time.perf_counter()
    yield out
    out["seconds"] = time.perf_counter() - t0
    if logger:
        logger.info("%s took %.3fs", label, out["seconds"])


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def object_column(values) -> np.ndarray:
    """Build a 1-D object ndarray holding one (possibly vector) value per
    row — the canonical representation of vector-valued columns."""
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def to_float32_matrix(col: np.ndarray) -> np.ndarray:
    """Coerce a column of scalars / vectors / lists into an (n, d) float32
    matrix — the device-feed analog of the reference's input coercion UDF
    (CNTKModel.scala:232-241), done once per column instead of per element."""
    if col.dtype.kind in "bifu":
        if col.ndim == 1:
            return col.astype(np.float32).reshape(-1, 1)
        return col.astype(np.float32).reshape(len(col), -1)
    if len(col) == 0:
        # width is unknowable from an empty object column; multi-host
        # callers recover it from the fleet (TpuModel._transform_multihost)
        return np.zeros((0, 0), np.float32)
    return np.stack([np.asarray(v, dtype=np.float32).ravel() for v in col])


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0):
    """Pad axis to a multiple (XLA static shapes want bucketed batches).
    Returns (padded, original_length)."""
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, rem)
    return np.pad(arr, widths), n
