"""Param DSL: typed parameters with defaults + domain validation.

TPU-native re-design of the reference's ``MMLParams`` / ``Wrappable`` contract
system (reference: src/core/contracts/src/main/scala/Params.scala:22-145).
The reference builds typed param factories (BooleanParam/IntParam/...) with
defaults and validation domains on top of Spark ML's Params, and uses that
single source of truth to drive codegen of Python/R bindings and docs.

Here the framework is Python-first, so the DSL *is* the user API: params are
class-level descriptors collected by a metaclass, which also auto-generates
``setFoo``/``getFoo`` accessors (the role played by the reference's codegen,
src/codegen/src/main/scala/PySparkWrapper.scala:33-160).
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Iterable, Optional

_NO_DEFAULT = object()


class ParamValidationError(ValueError):
    pass


class Param:
    """A declared parameter: name, doc, optional default, optional domain.

    ``jsonable=False`` marks a *complex* param (reference: ComplexParam,
    src/core/serialize/src/main/scala/ComplexParam.scala:10) whose value is not
    JSON-serializable (models, functions, arrays); the serializer stores these
    out-of-band (see mmlspark_tpu.core.serialize).
    """

    __slots__ = ("name", "doc", "default", "validator", "ptype", "jsonable", "owner")

    def __init__(self, doc: str = "", default: Any = _NO_DEFAULT,
                 validator: Optional[Callable[[Any], bool]] = None,
                 ptype: Optional[type] = None, jsonable: bool = True):
        self.name: str = ""  # filled by __set_name__
        self.doc = doc
        self.default = default
        self.validator = validator
        self.ptype = ptype
        self.jsonable = jsonable
        self.owner: Optional[type] = None

    def __set_name__(self, owner, name):
        self.name = name
        self.owner = owner

    @property
    def has_default(self) -> bool:
        return self.default is not _NO_DEFAULT

    def validate(self, value: Any) -> Any:
        if self.ptype is not None and value is not None:
            if self.ptype in (int, float) and isinstance(value, bool):
                raise ParamValidationError(
                    f"Param {self.name}: expected {self.ptype.__name__}, got bool")
            if self.ptype is float and isinstance(value, int):
                value = float(value)
            elif not isinstance(value, self.ptype):
                raise ParamValidationError(
                    f"Param {self.name}: expected {self.ptype.__name__}, "
                    f"got {type(value).__name__} ({value!r})")
        if self.validator is not None and value is not None:
            if not self.validator(value):
                raise ParamValidationError(
                    f"Param {self.name}: value {value!r} outside allowed domain")
        return value

    # descriptor protocol: stage.foo reads the current/default value
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.getOrDefault(self.name)

    def __set__(self, obj, value):
        obj.set(**{self.name: value})

    def __repr__(self):
        return f"Param({self.name!r}, default={self.default!r})"


# ---- typed factories (reference Params.scala:22-108) -----------------------

def BooleanParam(doc="", default=_NO_DEFAULT):
    return Param(doc, default, ptype=bool)


def IntParam(doc="", default=_NO_DEFAULT, min=None, max=None):
    v = _range_validator(min, max)
    return Param(doc, default, validator=v, ptype=int)


def FloatParam(doc="", default=_NO_DEFAULT, min=None, max=None):
    v = _range_validator(min, max)
    return Param(doc, default, validator=v, ptype=float)


def StringParam(doc="", default=_NO_DEFAULT, choices: Optional[Iterable[str]] = None):
    v = None
    if choices is not None:
        allowed = frozenset(choices)
        v = lambda x: x in allowed
    return Param(doc, default, validator=v, ptype=str)


def ListParam(doc="", default=_NO_DEFAULT):
    return Param(doc, default, ptype=(list, tuple))


def DictParam(doc="", default=_NO_DEFAULT):
    return Param(doc, default, ptype=dict)


def ComplexParam(doc="", default=_NO_DEFAULT):
    """Non-JSON param (model/function/array/stage); serialized out-of-band."""
    return Param(doc, default, jsonable=False)


def _range_validator(lo, hi):
    if lo is None and hi is None:
        return None

    def check(x):
        if lo is not None and x < lo:
            return False
        if hi is not None and x > hi:
            return False
        return True
    return check


# ---- metaclass + base ------------------------------------------------------

def _make_setter(pname):
    def setter(self, value):
        self.set(**{pname: value})
        return self
    setter.__name__ = "set" + pname[0].upper() + pname[1:]
    return setter


def _make_getter(pname):
    def getter(self):
        return self.getOrDefault(pname)
    getter.__name__ = "get" + pname[0].upper() + pname[1:]
    return getter


class ParamsMeta(type):
    """Collects Param descriptors across the MRO; generates set/get accessors."""

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        declared: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    declared[k] = v
        cls._params = declared
        for pname in declared:
            cap = pname[0].upper() + pname[1:]
            if "set" + cap not in ns and not hasattr(cls, "set" + cap):
                setattr(cls, "set" + cap, _make_setter(pname))
            if "get" + cap not in ns and not hasattr(cls, "get" + cap):
                setattr(cls, "get" + cap, _make_getter(pname))
        return cls


class Params(metaclass=ParamsMeta):
    """Base for anything with declared params (stages, models)."""

    def __init__(self, **kwargs):
        self._paramMap: dict[str, Any] = {}
        if kwargs:
            self.set(**kwargs)

    # -- core accessors --
    @classmethod
    def params(cls) -> dict[str, Param]:
        return dict(cls._params)

    def hasParam(self, name: str) -> bool:
        return name in self._params

    def isSet(self, name: str) -> bool:
        return name in self._paramMap

    def isDefined(self, name: str) -> bool:
        return name in self._paramMap or self._params[name].has_default

    def getOrDefault(self, name: str):
        if name in self._paramMap:
            return self._paramMap[name]
        p = self._params[name]
        if p.has_default:
            return p.default
        raise KeyError(f"Param {name!r} is not set and has no default "
                       f"(on {type(self).__name__})")

    def get(self, name: str, default=None):
        try:
            return self.getOrDefault(name)
        except KeyError:
            return default

    def set(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            if k not in self._params:
                raise KeyError(f"{type(self).__name__} has no param {k!r}; "
                               f"available: {sorted(self._params)}")
            self._paramMap[k] = self._params[k].validate(v)
        return self

    def clear(self, name: str) -> "Params":
        self._paramMap.pop(name, None)
        return self

    def explainParams(self) -> str:
        lines = []
        for name, p in sorted(self._params.items()):
            cur = self._paramMap.get(name, p.default if p.has_default else "(undefined)")
            lines.append(f"{name}: {p.doc} (current: {cur!r})")
        return "\n".join(lines)

    def copy(self, extra: Optional[dict] = None) -> "Params":
        new = _copy.copy(self)
        new._paramMap = dict(self._paramMap)
        if extra:
            new.set(**extra)
        return new

    # -- serialization of the *simple* portion of the param map --
    def _jsonParams(self) -> dict:
        return {k: v for k, v in self._paramMap.items()
                if self._params[k].jsonable}

    def _complexParams(self) -> dict:
        return {k: v for k, v in self._paramMap.items()
                if not self._params[k].jsonable}


# ---- shared column mixins (reference Params.scala:112-145) -----------------

class HasInputCol(Params):
    inputCol = StringParam("The name of the input column", default="input")


class HasOutputCol(Params):
    outputCol = StringParam("The name of the output column", default="output")


class HasInputCols(Params):
    inputCols = ListParam("The names of the input columns", default=())


class HasLabelCol(Params):
    labelCol = StringParam("The name of the label column", default="label")


class HasFeaturesCol(Params):
    featuresCol = StringParam("The name of the features column", default="features")
