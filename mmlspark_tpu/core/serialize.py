"""Stage save/load, including non-JSON ("complex") params.

Plays the role of the reference's core/serialize package: ComplexParam +
ComplexParamsWritable/Readable (reference: src/core/serialize/src/main/scala/
ComplexParamsSerializer.scala:16-33,137) which persist Spark stages whose
params aren't JSON-able (inner models, UDFs, byte arrays).

Layout on disk:
    <path>/metadata.json            class name, uid, simple params, complex index
    <path>/complex/<param>...       one entry per complex param, kind-tagged:
        stage/        a nested PipelineStage (recursive save)
        stage_list/0..N  list/tuple of stages
        ndarray .npy  numpy array
        pytree .msgpack  JAX/flax pytree (dict/list of arrays) via flax msgpack
        pickle .pkl   anything else picklable

Pytrees use flax.serialization msgpack — the TPU-native answer to the
reference's save-model-to-bytes trick (SerializableFunction.scala:58-82).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any

import numpy as np

from .pipeline import PipelineStage, _qualname, lookup_stage_class

_FORMAT_VERSION = 1


def _ensure_registry_populated():
    # importing the root package registers every stage subclass
    import mmlspark_tpu  # noqa: F401


def _save_complex(value: Any, path: str) -> dict:
    if isinstance(value, PipelineStage):
        save_stage(value, path)
        return {"kind": "stage"}
    if isinstance(value, (list, tuple)) and value and all(
            isinstance(v, PipelineStage) for v in value):
        os.makedirs(path, exist_ok=True)
        for i, v in enumerate(value):
            save_stage(v, os.path.join(path, str(i)))
        return {"kind": "stage_list", "n": len(value)}
    if isinstance(value, np.ndarray):
        np.save(path + ".npy", value)
        return {"kind": "ndarray"}
    # try a flax-msgpack pytree (covers jax arrays / nested dicts of arrays);
    # msgpack restore rejects non-string map keys, so only use it for
    # string-keyed trees
    def _str_keyed(v):
        if isinstance(v, dict):
            return all(isinstance(k, str) and _str_keyed(x)
                       for k, x in v.items())
        if isinstance(v, (list, tuple)):
            return all(_str_keyed(x) for x in v)
        return True

    try:
        if not _str_keyed(value):
            raise TypeError("non-string map keys")
        from flax import serialization
        blob = serialization.msgpack_serialize(value)
        with open(path + ".msgpack", "wb") as f:
            f.write(blob)
        return {"kind": "pytree"}
    except Exception:
        pass
    with open(path + ".pkl", "wb") as f:
        pickle.dump(value, f)
    return {"kind": "pickle"}


def _load_complex(tag: dict, path: str) -> Any:
    kind = tag["kind"]
    if kind == "stage":
        return load_stage(path)
    if kind == "stage_list":
        return tuple(load_stage(os.path.join(path, str(i)))
                     for i in range(tag["n"]))
    if kind == "ndarray":
        return np.load(path + ".npy", allow_pickle=False)
    if kind == "pytree":
        from flax import serialization
        import jax.numpy as jnp

        def _to_jax(x):
            if isinstance(x, dict):
                return {k: _to_jax(v) for k, v in x.items()}
            if isinstance(x, (list, tuple)):
                return type(x)(_to_jax(v) for v in x)
            if isinstance(x, np.ndarray):
                return jnp.asarray(x)
            return x
        with open(path + ".msgpack", "rb") as f:
            return _to_jax(serialization.msgpack_restore(f.read()))
    if kind == "pickle":
        with open(path + ".pkl", "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown complex-param kind {kind!r}")


def _jsonable(v):
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def save_stage(stage: PipelineStage, path: str, overwrite: bool = True):
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path)

    simple, complex_idx = {}, {}
    complex_dir = os.path.join(path, "complex")
    for name, value in stage._paramMap.items():
        p = stage._params[name]
        if p.jsonable and _jsonable(value):
            simple[name] = value
        else:
            os.makedirs(complex_dir, exist_ok=True)
            complex_idx[name] = _save_complex(
                value, os.path.join(complex_dir, name))

    meta = {"format": _FORMAT_VERSION, "class": _qualname(type(stage)),
            "uid": stage.uid, "params": simple, "complex": complex_idx}
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1, default=str)


def load_stage(path: str) -> PipelineStage:
    _ensure_registry_populated()
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = lookup_stage_class(meta["class"])
    # stages must be no-arg constructible (same contract as Spark ML stages);
    # going through __init__ restores any non-param instance state
    stage = cls()
    stage.uid = meta["uid"]
    # restore simple params through validation; tuples arrive as JSON lists
    for k, v in meta["params"].items():
        if isinstance(v, list) and isinstance(stage._params[k].default, tuple):
            v = tuple(v)
        stage.set(**{k: v})
    for k, tag in meta["complex"].items():
        stage._paramMap[k] = _load_complex(
            tag, os.path.join(path, "complex", k))
    return stage
