"""The ``mmlspark-tpu-perf`` command line (also
``python -m mmlspark_tpu.perf``).

Exit codes mirror graftlint: 0 — no regression, 1 — at least one metric
regressed past its noise band (the failure names each metric and its
delta), 2 — usage error. ``--format json`` emits the full
:class:`~.gate.GateReport` document for CI annotations.

    # gate a fresh bench capture against the committed trajectory
    python bench.py --all > run.json && mmlspark-tpu-perf --check run.json

    # re-validate a committed round against the rounds before it
    mmlspark-tpu-perf --check BENCH_r05.json
"""

from __future__ import annotations

import argparse
import json
import sys

from .gate import DEFAULT_K_MAD, DEFAULT_MIN_REL, check_run
from .history import (find_history_dir, load_history, load_record,
                      metric_series)


def _fmt_value(v: float) -> str:
    return f"{v:.4g}" if abs(v) < 1000 else f"{v:,.1f}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mmlspark-tpu-perf",
        description="statistical bench-regression gate: a run's metrics "
                    "vs the BENCH_r*.json history (median-of-N with a "
                    "MAD noise band)")
    ap.add_argument("--check", metavar="FILE",
                    help="run to gate: bench.py [--all] JSON output or a "
                         "BENCH_rNN.json round record (a round checks "
                         "against the rounds before it)")
    ap.add_argument("--history", metavar="DIR", default=None,
                    help="directory holding BENCH_r*.json (default: "
                         "search cwd, its parents, then the checkout)")
    ap.add_argument("--min-rel", type=float, default=DEFAULT_MIN_REL,
                    help="noise-band floor as a fraction of the median "
                         f"(default {DEFAULT_MIN_REL})")
    ap.add_argument("--k-mad", type=float, default=DEFAULT_K_MAD,
                    help="noise-band width in robust sigmas "
                         f"(1.4826*MAD; default {DEFAULT_K_MAD})")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list", action="store_true",
                    help="print the discovered history per metric and "
                         "exit")
    args = ap.parse_args(argv)

    history_dir = args.history or find_history_dir()
    history = load_history(history_dir) if history_dir else []

    if args.list:
        if not history:
            print("no BENCH_r*.json history found")
            return 0
        names = sorted({m for r in history for m in r["metrics"]})
        print(f"history: {history_dir} ({len(history)} round(s))")
        for name in names:
            vals = metric_series(history, name)
            print(f"  {name}: " + " -> ".join(_fmt_value(v)
                                              for v in vals))
        return 0

    if not args.check:
        ap.error("--check FILE is required (or --list)")
    try:
        run = load_record(args.check)
    except ValueError as e:
        print(f"mmlspark-tpu-perf: {e}", file=sys.stderr)
        return 2
    if not run["metrics"]:
        print(f"mmlspark-tpu-perf: {args.check}: no metrics found",
              file=sys.stderr)
        return 2
    # a round record inside the history gates against the rounds BEFORE
    # it — never against itself, and not against later rounds either
    if history and run["round"] is not None:
        history = [r for r in history
                   if r["source"] != run["source"]
                   and (r["round"] is None or r["round"] < run["round"])]

    report = check_run(run, history, min_rel=args.min_rel,
                       k_mad=args.k_mad, history_dir=history_dir)

    if args.format == "json":
        print(json.dumps(report.to_json(), indent=2))
    else:
        for e in report.entries:
            if e["status"] == "no-history":
                print(f"  new      {e['metric']}: "
                      f"{_fmt_value(e['value'])} (no history — recorded, "
                      f"not gated)")
                continue
            arrow = {"regression": "REGRESSION", "improvement": "faster ",
                     "ok": "ok      "}[e["status"]]
            print(f"  {arrow} {e['metric']}: {_fmt_value(e['value'])} vs "
                  f"median {_fmt_value(e['median'])} over "
                  f"{e['history_n']} round(s) "
                  f"({e['rel_delta']:+.1%}, band "
                  f"±{e['band'] / abs(e['median']):.1%}, "
                  f"{e['direction']})")
        n_reg = len(report.regressions)
        if n_reg:
            print(f"mmlspark-tpu-perf: {n_reg} regression(s) — FAIL")
        else:
            print("mmlspark-tpu-perf: no regressions")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
