"""Perf gate: statistical regression checking over the BENCH_r* history.

The repo measures speed (``bench.py`` and friends) and records it
(``BENCH_r*.json`` rounds at the repo root), but until this package
nothing *defended* it — a PR that halved throughput still merged green.
``python -m mmlspark_tpu.perf --check <run.json>`` is the
``graftlint``-style gate that closes the loop:

  * :mod:`.history` discovers and parses the bench trajectory — both the
    harness round records (``{"n": .., "parsed": {...}}``) and the
    multi-scenario ``mmlspark-bench/v1`` schema ``bench.py --all``
    emits — searching the explicit ``--history`` dir, then the current
    directory and its parents, then the checkout the package lives in
    (the fix for the long-standing ``vs_baseline: null``: the harness
    cwd is not the repo root);
  * :mod:`.gate` compares each metric in a run against the
    **median-of-N** of its history with a noise band of
    ``max(min_rel · median, k · 1.4826 · MAD)`` — a 2% wobble on a noisy
    series passes, a 20% cliff on a stable one fails — with the
    regression direction derived from the unit (``s``/``ms`` regress
    upward, throughput regresses downward);
  * :mod:`.cli` exits nonzero naming the metric and the delta, so CI
    fails the run that lands the slowdown, not a retrospective.

Console script ``mmlspark-tpu-perf``; wrapper ``tools/bin/perfgate``.
"""

from .gate import GateReport, check_run, lower_is_better, mad, median
from .history import (SCHEMA, find_history_dir, latest_value, load_history,
                      load_record, metric_series)

__all__ = ["check_run", "GateReport", "lower_is_better", "median", "mad",
           "find_history_dir", "load_history", "load_record",
           "metric_series", "latest_value", "SCHEMA"]
