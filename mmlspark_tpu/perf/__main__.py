"""``python -m mmlspark_tpu.perf`` — the bench-regression gate CLI."""

import sys

from .cli import main

sys.exit(main())
