"""The statistical regression check: median-of-N history + MAD band.

A naive ``value < last_round`` gate is wrong twice over: bench numbers
wobble run to run (so it false-alarms on noise), and a slow drift can
hide behind a lucky last round (so it misses real regressions). The
gate here compares a run against the **median** of the metric's history
and only fails when the delta clears a noise band sized from the
history's own spread:

    band = max(min_rel * |median|,  k_mad * 1.4826 * MAD)

``1.4826 * MAD`` is the robust stand-in for one standard deviation
(exact under normality, outlier-immune otherwise); ``min_rel`` floors
the band so a perfectly-flat history doesn't fail on a 0.1% wobble.

Direction is derived from the metric itself: units measured in
seconds/milliseconds (and ``*_seconds`` / ``*_ms`` metric names)
regress when they go UP, everything else (throughput, rates) regresses
when it goes DOWN.
"""

from __future__ import annotations

from typing import Optional

#: defaults: 5% floor, 3 robust sigmas
DEFAULT_MIN_REL = 0.05
DEFAULT_K_MAD = 3.0

_MAD_TO_SIGMA = 1.4826


def median(values) -> float:
    s = sorted(values)
    n = len(s)
    if not n:
        raise ValueError("median of empty series")
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def mad(values) -> float:
    """Median absolute deviation around the median."""
    m = median(values)
    return median([abs(v - m) for v in values])


def lower_is_better(metric: str, unit: str = "") -> bool:
    """Regression direction from the metric's identity: time-like
    metrics regress upward, throughput-like metrics regress downward."""
    u = (unit or "").strip().lower()
    if u == "s" or u.startswith(("s ", "s(", "s/", "sec", "ms")):
        return True
    m = metric.lower()
    return m.endswith(("_seconds", "_ms", "_s", "_latency")) \
        or "latency" in m


class GateReport:
    """Per-metric verdicts for one checked run."""

    def __init__(self, entries: list, history_dir: Optional[str]):
        self.entries = entries
        self.history_dir = history_dir

    @property
    def regressions(self) -> list:
        return [e for e in self.entries if e["status"] == "regression"]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def to_json(self) -> dict:
        return {"ok": self.ok, "history_dir": self.history_dir,
                "checked": len(self.entries),
                "regressions": len(self.regressions),
                "metrics": self.entries}


def check_metric(metric: str, value: float, unit: str, series: list,
                 min_rel: float = DEFAULT_MIN_REL,
                 k_mad: float = DEFAULT_K_MAD) -> dict:
    """One metric against its history series (oldest first)."""
    if not series:
        return {"metric": metric, "value": value, "status": "no-history",
                "history_n": 0}
    med = median(series)
    band = max(min_rel * abs(med), k_mad * _MAD_TO_SIGMA * mad(series))
    delta = value - med
    rel = delta / med if med else (0.0 if not delta else float("inf"))
    lower = lower_is_better(metric, unit)
    if lower:
        regressed = delta > band
        improved = delta < -band
    else:
        regressed = delta < -band
        improved = delta > band
    return {"metric": metric, "value": value, "unit": unit,
            "median": med, "band": band,
            "delta": delta, "rel_delta": rel,
            "history_n": len(series),
            "direction": "lower-is-better" if lower
            else "higher-is-better",
            "status": ("regression" if regressed
                       else "improvement" if improved else "ok")}


def check_run(run: dict, history: list,
              min_rel: float = DEFAULT_MIN_REL,
              k_mad: float = DEFAULT_K_MAD,
              history_dir: Optional[str] = None) -> GateReport:
    """Every metric of a loaded run record (:func:`.history.load_record`)
    against a loaded history (:func:`.history.load_history`)."""
    from .history import metric_series
    entries = []
    for name in sorted(run["metrics"]):
        m = run["metrics"][name]
        entries.append(check_metric(
            name, m["value"], m.get("unit", ""),
            metric_series(history, name), min_rel=min_rel, k_mad=k_mad))
    return GateReport(entries, history_dir)
