"""Discovery and parsing of the benchmark history (``BENCH_r*.json``).

Every bench round the driver records lands as a ``BENCH_rNN.json`` at
the repo root. Two shapes exist in the wild and both are first-class:

* the **harness round record** — ``{"n": 5, "cmd": ..., "tail": ...,
  "parsed": {"metric": ..., "value": ..., "unit": ...}}`` where
  ``parsed`` is the last JSON line of the bench run (historically one
  metric; with ``bench.py --all`` it is the schema document below);
* the **bench schema document** (``mmlspark-bench/v1``) — what
  ``bench.py --all`` prints: ``{"schema": "mmlspark-bench/v1",
  "backend": ..., "metrics": [{"metric", "value", "unit", ...}, ...]}``.

A bare one-metric line (``{"metric": ..., "value": ...}``) also parses,
so ``--check`` accepts a raw ``python bench.py`` capture.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Optional

SCHEMA = "mmlspark-bench/v1"

#: the round-record filename pattern at the repo root
BENCH_GLOB = "BENCH_r*.json"
_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def find_history_dir(start: Optional[str] = None) -> Optional[str]:
    """The directory holding the ``BENCH_r*.json`` trajectory.

    Searches ``start`` (default: cwd) and each parent up to the
    filesystem root, then the checkout this package lives in. Returns
    None when no round file exists anywhere — the caller treats that as
    "no history", never an error (a fresh clone has no trajectory yet).

    This is the fix for the long-standing ``vs_baseline: null``: the
    bench harness runs from its own cwd, where a look-next-to-the-script
    search finds nothing.
    """
    seen = set()
    d = os.path.abspath(start or os.getcwd())
    while d not in seen:
        seen.add(d)
        if glob.glob(os.path.join(d, BENCH_GLOB)):
            return d
        d = os.path.dirname(d)
    # the checkout the installed package lives in (repo root is two
    # levels above this file: mmlspark_tpu/perf/history.py)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if pkg_root not in seen and glob.glob(os.path.join(pkg_root,
                                                       BENCH_GLOB)):
        return pkg_root
    return None


def _metric_entries(doc: dict):
    """Yield ``{"metric", "value", "unit", ...}`` dicts from any
    recognized document shape (round record, schema doc, bare line)."""
    if not isinstance(doc, dict):
        return
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        yield from _metric_entries(parsed)
        return
    if isinstance(doc.get("metrics"), list):    # mmlspark-bench/v1
        for m in doc["metrics"]:
            if isinstance(m, dict):
                yield m
        return
    if "metric" in doc:
        yield doc


def load_record(path: str) -> dict:
    """One history/run file -> ``{"source", "round", "metrics"}`` where
    ``metrics`` maps metric name to ``{"value": float, "unit": str}``.
    Entries without a numeric value (skipped scenarios, nulls) are
    dropped. Raises ``ValueError`` on unreadable/unparseable files —
    a gate must not silently pass on garbage input."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise ValueError(f"cannot read {path}: {e}") from e
    try:
        doc = json.loads(text)
    except ValueError:
        # tolerate a multi-line capture: the last parseable JSON line
        doc = None
        for line in reversed(text.strip().splitlines()):
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
        if doc is None:
            raise ValueError(f"{path}: no parseable JSON document")
    metrics: dict[str, dict] = {}
    for m in _metric_entries(doc):
        name, value = m.get("metric"), m.get("value")
        if not name or not isinstance(value, (int, float)):
            continue
        metrics[str(name)] = {"value": float(value),
                              "unit": str(m.get("unit", ""))}
    rnd = None
    if isinstance(doc, dict) and isinstance(doc.get("n"), int):
        rnd = doc["n"]
    else:
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            rnd = int(m.group(1))
    return {"source": os.path.abspath(path), "round": rnd,
            "metrics": metrics}


def load_history(directory: str,
                 exclude: Optional[str] = None) -> list:
    """Every parseable round record in ``directory``, oldest first
    (by round number, then filename). ``exclude`` drops one file by
    path — checking ``BENCH_r05.json`` must not compare it against
    itself."""
    out = []
    skip = os.path.abspath(exclude) if exclude else None
    for path in sorted(glob.glob(os.path.join(directory, BENCH_GLOB))):
        if skip and os.path.abspath(path) == skip:
            continue
        try:
            out.append(load_record(path))
        except ValueError:
            continue    # one corrupt round must not hide the others
    out.sort(key=lambda r: (r["round"] is None, r["round"] or 0,
                            r["source"]))
    return out


def metric_series(history: list, metric: str) -> list:
    """The metric's values across the history, oldest first."""
    return [r["metrics"][metric]["value"] for r in history
            if metric in r["metrics"]]


def latest_value(history: list, metric: str) -> Optional[float]:
    """Most recent recorded value of ``metric`` (None when never
    recorded) — what ``bench.py`` prints its ``vs_baseline`` ratio
    against."""
    series = metric_series(history, metric)
    return series[-1] if series else None
