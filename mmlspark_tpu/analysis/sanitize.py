"""Donation sanitizer — the dynamic oracle behind the donation rules.

``MMLSPARK_TPU_SANITIZE=donation`` arms it (tests and chaos runs; OFF
by default with zero overhead — the wrapper is only installed when the
env knob is set at step-build time). Every donating dispatch the
trainer builds goes through :func:`wrap_donated`, which does two things
the static taint walk (:mod:`mmlspark_tpu.analysis.donation`) cannot:

* **poison after dispatch** — any argument at a donated position whose
  leaves are HOST numpy buffers (the zero-copy-alias hazard: on the CPU
  backend ``device_put`` may alias them, and XLA now treats that memory
  as scratch) is filled with a sentinel (NaN for floats, ``0xDD`` for
  ints) immediately after the call returns.  The PR 7 / PR 9 bug class
  corrupted *nondeterministically* — whenever the host allocator
  happened to reuse the pages; poisoning makes the reuse deterministic,
  so a donation bug fails the FIRST run, loudly, with sentinel values
  instead of a flaky 1e35 loss three epochs later.
* **trap re-reads** — a poisoned buffer showing up as an argument to a
  later sanitized dispatch raises :class:`DonatedBufferReuse`
  immediately (counted on ``mmlspark_sanitizer_poisoned_reads_total``)
  — the dynamic twin of the ``donation-use-after-donate`` rule.

The sanitizer never changes program semantics for correct code: donated
buffers are consumed by contract, so poisoning memory the program must
never read again is a no-op for every correct caller.
"""

from __future__ import annotations

import weakref
from typing import Iterable

from .. import telemetry

_m_poisoned = telemetry.registry.counter(
    "mmlspark_sanitizer_poisoned_buffers",
    "host-aliased buffers found at donated argument positions and "
    "filled with the sentinel after dispatch (each one is a donation "
    "hazard the static rules should also have flagged)")
_m_poisoned_reads = telemetry.registry.counter(
    "mmlspark_sanitizer_poisoned_reads",
    "re-reads of poisoned (donated) buffers trapped at a later "
    "sanitized dispatch — use-after-donate caught dynamically")

#: finite int sentinel byte; floats get NaN (anything arithmetic with
#: it stays NaN, so the corruption cannot silently average away)
_INT_SENTINEL = 0xDD


class DonatedBufferReuse(RuntimeError):
    """A buffer previously passed at a donated position (and poisoned)
    reached a later sanitized dispatch — the dynamic use-after-donate."""


def enabled() -> bool:
    from ..core.env import sanitize_mode
    return sanitize_mode() == "donation"


#: id(buffer) -> weakref; weakrefs keep id() collisions from false-
#: positiving after the poisoned array is garbage collected
_poisoned: dict = {}


def _np():
    import numpy as np
    return np


def _leaves(tree) -> Iterable:
    import jax
    return jax.tree_util.tree_leaves(tree)


def _host_buffers(tree) -> list:
    """The numpy-owned leaves of ``tree`` — buffers the host allocator
    still controls after a donating dispatch placed (or aliased) them."""
    np = _np()
    return [leaf for leaf in _leaves(tree)
            if isinstance(leaf, np.ndarray) and leaf.size > 0]


def _poison(arr) -> None:
    np = _np()
    try:
        if np.issubdtype(arr.dtype, np.floating) \
                or np.issubdtype(arr.dtype, np.complexfloating):
            arr.fill(np.nan)
        elif np.issubdtype(arr.dtype, np.integer):
            arr.fill(_INT_SENTINEL)
        else:
            return       # bool/str leaves: nothing sensible to poison
    except ValueError:
        return           # read-only buffer: cannot alias-corrupt either
    _poisoned[id(arr)] = weakref.ref(arr)
    _m_poisoned.inc()


def _check_not_poisoned(tree, label: str) -> None:
    for leaf in _leaves(tree):
        ref = _poisoned.get(id(leaf))
        if ref is not None and ref() is leaf:
            _m_poisoned_reads.inc()
            telemetry.trace.instant("sanitizer/poisoned_read",
                                    dispatch=label)
            raise DonatedBufferReuse(
                f"buffer id={id(leaf)} shape={getattr(leaf, 'shape', ())} "
                f"was donated to an earlier dispatch and poisoned; it "
                f"reached dispatch {label!r} again — donated buffers are "
                f"consumed, rebind from the call's outputs")


def _dealias_outputs(out, hazards):
    """Copy any output leaf whose device buffer IS one of the host
    buffers about to be poisoned. The CPU backend may zero-copy a
    (suitably aligned) numpy input and then donate that very memory as
    the output buffer — poisoning it would corrupt a live result the
    caller legitimately owns."""
    import jax
    np = _np()
    spans = [(a.__array_interface__["data"][0],
              a.__array_interface__["data"][0] + a.nbytes)
             for a in hazards]

    def dealias(leaf):
        try:
            p = leaf.unsafe_buffer_pointer()
        except Exception:
            return leaf          # sharded/host leaf: no single buffer
        if any(lo <= p < hi for lo, hi in spans):
            return jax.device_put(np.array(leaf, copy=True))
        return leaf

    return jax.tree_util.tree_map(dealias, out)


def clear() -> None:
    """Forget poisoned-buffer identities (test isolation)."""
    _poisoned.clear()


def wrap_donated(fn, donate_argnums, label: str = "step"):
    """Wrap a donating dispatch. When the sanitizer is DISARMED (the
    default) returns ``fn`` unchanged — zero overhead, zero behavior
    change. Armed: traps poisoned re-reads across dispatches, then
    poisons the host-aliased donated inputs of this one."""
    if not enabled() or not donate_argnums:
        return fn
    donate = tuple(sorted(set(int(i) for i in donate_argnums)))

    def sanitized(*args, **kwargs):
        _check_not_poisoned((args, kwargs), label)
        hazards = []
        for i in donate:
            if i < len(args):
                hazards.extend(_host_buffers(args[i]))
        out = fn(*args, **kwargs)
        if hazards:
            # the dispatch is async: the program may still be READING the
            # host-aliased buffers — they are only dead once it completes
            import jax
            out = jax.block_until_ready(out)
            out = _dealias_outputs(out, hazards)
        for arr in hazards:
            _poison(arr)
        if hazards:
            telemetry.trace.instant("sanitizer/poisoned", dispatch=label,
                                    buffers=len(hazards))
        return out

    sanitized.__name__ = getattr(fn, "__name__", "sanitized")
    sanitized.__wrapped__ = fn
    if hasattr(fn, "lower"):
        # the profiler's AOT path (ProfiledFunction._compile) lowers the
        # step fn directly; forward it so profile=True composes (AOT
        # dispatches skip the poison pass — the sanitizer is a test-tier
        # oracle, not a semantics guarantee under every wrapper stack)
        sanitized.lower = fn.lower
    return sanitized
