"""The ``graftlint`` command line (also ``python -m mmlspark_tpu.analysis``).

Exit codes: 0 — clean (every finding baselined or none), 1 — new
findings, 2 — usage error. ``--format json`` emits a machine-readable
document (what CI annotations and the flight recorder embed);
``--sarif OUT`` additionally writes a SARIF 2.1.0 log (what CI code-
scanning UIs ingest); ``--changed-only`` reuses cached results for
files whose content hash is unchanged (``.graftlint/cache.json``);
``--write-baseline`` grandfathers the current findings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import Baseline, all_rules, run_analysis


def _default_paths_and_baseline() -> tuple[list[str], str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg)
    baseline = os.path.join(root, "tools", "graftlint_baseline.json")
    return [pkg], baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="framework-aware static analysis for mmlspark_tpu "
                    "(jit-safety, concurrency, API consistency)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "installed mmlspark_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of grandfathered findings "
                         "(default: tools/graftlint_baseline.json next "
                         "to the package)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report and fail on "
                         "everything")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule or family names to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-codegen", action="store_true",
                    help="skip the import-based codegen-sync check")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths and docs lookup")
    ap.add_argument("--sarif", default=None, metavar="OUT",
                    help="also write a SARIF 2.1.0 log to OUT (CI "
                         "code-scanning ingestion)")
    ap.add_argument("--changed-only", action="store_true",
                    help="incremental mode: reuse cached findings for "
                         "files whose content hash is unchanged "
                         "(implies --no-codegen; cache under "
                         ".graftlint/)")
    ap.add_argument("--cache", default=None,
                    help="cache file for --changed-only (default: "
                         "<root>/.graftlint/cache.json)")
    ap.add_argument("--jobs", type=int, metavar="N",
                    default=int(os.environ.get("GRAFTLINT_JOBS", "1")),
                    help="run file-scoped rules over N worker processes "
                         "(default: $GRAFTLINT_JOBS or 1; the graftlint "
                         "wrapper exports min(8, cpus))")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in sorted(all_rules(), key=lambda r: (r.family, r.name)):
            print(f"{r.name:28s} [{r.family}] {r.doc}")
        return 0

    paths, default_baseline = _default_paths_and_baseline()
    if args.paths:
        paths = args.paths
        root = args.root
    else:
        root = args.root or os.path.dirname(paths[0])
    baseline = args.baseline or default_baseline
    if args.no_baseline:
        baseline = None
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    options = {"codegen": (not args.no_codegen and not args.paths
                           and not args.changed_only)}

    stats = None
    if args.changed_only:
        from .incremental import run_changed_only
        findings, stats = run_changed_only(
            paths, root=root, baseline=baseline, rules=rules,
            options=options, cache_path=args.cache)
    else:
        findings = run_analysis(paths, root=root, baseline=baseline,
                                rules=rules, options=options,
                                jobs=args.jobs)
    new = [f for f in findings if not f.baselined]
    _observe_findings(findings)

    if args.write_baseline:
        path = args.baseline or default_baseline
        Baseline.write(path, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to {path}")
        return 0

    if args.sarif:
        from .sarif import to_sarif
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(to_sarif(findings), f, indent=2)

    if args.format == "json":
        doc = {
            "findings": [f.to_json() for f in findings],
            "total": len(findings),
            "new": len(new),
            "baselined": len(findings) - len(new),
        }
        if stats is not None:
            doc["incremental"] = stats
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            print(f.render())
        n_base = len(findings) - len(new)
        inc = (f" [incremental: {stats['analyzed_files']} analyzed, "
               f"{stats['reused_files']} cached]" if stats else "")
        print(f"graftlint: {len(new)} finding(s)"
              + (f" ({n_base} baselined)" if n_base else "")
              + inc
              + (" — FAIL" if new else " — ok"))
    return 1 if new else 0


def _observe_findings(findings) -> None:
    """Per-family finding counts onto the telemetry registry, so CI
    wrappers that scrape /metrics (or embed a snapshot in the flight
    bundle) can chart graftlint findings by family over time."""
    try:
        from .. import telemetry
        from .core import all_rules as _rules
        fam = {r.name: r.family for r in _rules()}
        counts: dict = {}
        for f in findings:
            counts[fam.get(f.rule, "unknown")] = \
                counts.get(fam.get(f.rule, "unknown"), 0) + 1
        g = telemetry.registry.gauge(
            "mmlspark_graftlint_findings",
            "graftlint findings by rule family at the last analyzer "
            "run (baselined findings included)", labels=("family",))
        for family, n in counts.items():
            g.labels(family=family).set(n)
    except Exception:    # telemetry must never fail the analyzer
        pass


if __name__ == "__main__":
    sys.exit(main())
