"""Incremental analysis: ``--changed-only``, keyed on file content hashes.

The full repo pass parses every module and walks every rule — fine in
CI, wasteful in an edit loop where one file changed. The cache
(``<root>/.graftlint/cache.json``) stores, per analyzed file, the
sha256 of its text plus the file-scoped findings it produced, and one
project-level entry (digest over EVERY file hash + the observability
doc + the tests/ index + the thread-root index + the selected rule
set) holding the project-scoped findings (lock-order graph, catalogue/chaos coverage,
codegen sync — anything whose result can change when OTHER files do).

On a run:

* a file whose hash matches the cache contributes its cached findings
  with zero re-analysis;
* changed/new files are re-run through the file-scoped rules only;
* the project-scoped rules re-run only when the project digest moved.

A fully unchanged tree is therefore a pure cache hit — no rule runs at
all (``stats["analyzed_files"] == 0 and not stats["project_rules_run"]``,
the property the tier-1 test pins). Baseline matching is re-applied
after assembly, so editing the baseline never requires a cache flush.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Iterable, Optional

from .core import Baseline, Finding, Project, all_rules, load_project


def default_cache_path(root: str) -> str:
    return os.path.join(root, ".graftlint", "cache.json")


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _finding_to_dict(f: Finding) -> dict:
    return {"rule": f.rule, "file": f.path, "line": f.line,
            "context": f.context, "message": f.message, "hint": f.hint,
            "code": f.code}


def _finding_from_dict(d: dict) -> Finding:
    return Finding(rule=d["rule"], path=d["file"], line=int(d["line"]),
                   message=d["message"], hint=d.get("hint", ""),
                   context=d.get("context", "<module>"),
                   code=d.get("code", ""))


def _env_digest(project: Project, rule_names: list) -> str:
    """Cross-file inputs the project-scoped rules read: the docs, the
    tests/ index, the selected rules themselves."""
    h = hashlib.sha256()
    h.update(",".join(sorted(rule_names)).encode())
    from .consistency import _doc_path, _tests_dir, _tests_index
    doc = _doc_path(project)
    if doc and os.path.isfile(doc):
        with open(doc, encoding="utf-8") as f:
            h.update(_sha(f.read()).encode())
    tests = _tests_dir(project)
    if tests:
        h.update(_sha(_tests_index(tests)).encode())
    if any(n.startswith("race-") for n in rule_names):
        # the race family's whole-program view pivots on the thread-root
        # index (which entry points exist, spawned where); folding its
        # digest in makes the cache key concurrency-aware — a new spawn
        # site anywhere re-runs the family even if the individually
        # hashed files somehow collide
        from .races import thread_root_digest
        h.update(thread_root_digest(project).encode())
    return h.hexdigest()


def run_changed_only(paths: list, root: Optional[str] = None,
                     baseline: Optional[str] = None,
                     rules: Optional[Iterable[str]] = None,
                     options: Optional[dict] = None,
                     cache_path: Optional[str] = None):
    """Returns ``(findings, stats)``; findings match what
    :func:`mmlspark_tpu.analysis.run_analysis` would produce for the
    same inputs, stats report what actually ran:
    ``{"analyzed_files", "reused_files", "project_rules_run",
    "cache_hit"}``."""
    project = load_project(paths, root=root, options=options)
    cache_path = cache_path or default_cache_path(project.root)
    try:
        with open(cache_path, encoding="utf-8") as f:
            cache = json.load(f)
    except (OSError, ValueError):
        cache = {}
    cached_files = cache.get("files", {})

    selected = all_rules()
    if rules is not None:
        wanted = set(rules)
        selected = [r for r in selected
                    if r.name in wanted or r.family in wanted]
    file_rules = [r for r in selected if r.scope == "file"]
    project_rules = [r for r in selected if r.scope == "project"]

    hashes = {sf.rel: _sha(sf.text) for sf in project.files}
    changed = [sf for sf in project.files
               if cached_files.get(sf.rel, {}).get("sha256")
               != hashes[sf.rel]]
    changed_rels = {sf.rel for sf in changed}
    unchanged = [sf for sf in project.files
                 if sf.rel not in changed_rels]

    findings: list[Finding] = []
    new_files_entry: dict = {}
    for sf in unchanged:
        cached = cached_files[sf.rel]
        findings.extend(_finding_from_dict(d)
                        for d in cached.get("findings", []))
        new_files_entry[sf.rel] = cached
    if changed:
        sub = Project(changed, project.root, project.options)
        per_file: dict[str, list] = {sf.rel: [] for sf in changed}
        for r in file_rules:
            for f in r.run(sub):
                if f is not None:
                    findings.append(f)
                    per_file.setdefault(f.path, []).append(f)
        for sf in changed:
            new_files_entry[sf.rel] = {
                "sha256": hashes[sf.rel],
                "findings": [_finding_to_dict(f)
                             for f in per_file.get(sf.rel, [])]}

    # project-scoped rules: digest over everything they can read
    digest = hashlib.sha256()
    for rel in sorted(hashes):
        digest.update(f"{rel}:{hashes[rel]};".encode())
    digest.update(_env_digest(project,
                              [r.name for r in project_rules]).encode())
    digest = digest.hexdigest()
    cached_project = cache.get("project", {})
    project_rules_run = False
    if project_rules:
        if cached_project.get("digest") == digest:
            findings.extend(_finding_from_dict(d)
                            for d in cached_project.get("findings", []))
            project_findings = cached_project.get("findings", [])
        else:
            project_rules_run = True
            fresh = []
            for r in project_rules:
                fresh.extend(f for f in r.run(project) if f is not None)
            findings.extend(fresh)
            project_findings = [_finding_to_dict(f) for f in fresh]
    else:
        project_findings = []

    try:
        os.makedirs(os.path.dirname(cache_path), exist_ok=True)
        with open(cache_path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "files": new_files_entry,
                       "project": {"digest": digest,
                                   "findings": project_findings}}, f)
    except OSError:
        pass     # a read-only checkout still gets correct results

    base = Baseline.load(baseline) if baseline else Baseline([])
    for f in findings:
        f.baselined = base.matches(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = {"analyzed_files": len(changed),
             "reused_files": len(unchanged),
             "project_rules_run": project_rules_run,
             "cache_hit": not changed and not project_rules_run}
    return findings, stats
