"""``python -m mmlspark_tpu.analysis`` — the graftlint CLI."""

import sys

from .cli import main

sys.exit(main())
