"""Race sanitizer — the dynamic oracle behind the race rules.

``MMLSPARK_TPU_SANITIZE=races`` arms it (tests and chaos runs; OFF by
default with zero overhead — :func:`instrument` is a no-op and no class
is ever patched unless the env knob is set when the object is built).
Armed, it does two things the static pass
(:mod:`mmlspark_tpu.analysis.races`) cannot:

* **record the actual interleaving** — every access to an instrumented
  field is tagged with the accessing thread and the set of
  :class:`TrackedLock` s that thread holds *right now*, counted on
  ``mmlspark_sanitizer_race_accesses_total``;
* **trap the racy pair at the moment it happens** — an **unlocked
  write** paired with any access to the same field from another thread
  raises :class:`RaceConflict` immediately (counted on
  ``mmlspark_sanitizer_race_conflicts_total``), with both sides' thread
  names and held-lock sets in the message. A *locked* write observed by
  an unlocked read is recorded but NOT trapped: single-machine-word
  reads of a locked field (e.g. ``ProcessHTTPSource._offset``'s fast
  path) are a deliberate, benign pattern in this codebase.

The held-lock bookkeeping doubles as the data source for the
``/debug/threads`` endpoint: :func:`thread_dump` joins
``sys._current_frames`` with the per-thread held-lock map, so a wedged
fleet shows WHICH thread holds WHICH lock under WHICH frame — the
deadlock-diagnosis twin of ``/debug/flight``.

Production classes opt in cheaply::

    sanitize_races.instrument(self, fields=("_n_pending", "_inflight"),
                              locks=("_lock",))

Disarmed this returns immediately; armed it wraps the named lock
attributes in :class:`TrackedLock` and patches the class's
``__setattr__``/``__getattribute__`` once to observe the named fields.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Iterable, Optional

from .. import telemetry

_m_accesses = telemetry.registry.counter(
    "mmlspark_sanitizer_race_accesses",
    "instrumented shared-field accesses observed by the race sanitizer "
    "(each tagged with the accessing thread and its held-lock set)")
_m_conflicts = telemetry.registry.counter(
    "mmlspark_sanitizer_race_conflicts",
    "conflicting unlocked write/access pairs trapped by the race "
    "sanitizer — each one is a data race the static race rules should "
    "also have flagged")


class RaceConflict(RuntimeError):
    """An instrumented field was written without a lock while another
    thread was accessing it — the dynamic ``race-unguarded-write``."""


def enabled() -> bool:
    from ..core.env import sanitize_mode
    return sanitize_mode() == "races"


#: fast-path gate: flipped on by the first armed instrument() call, so
#: patched-class hooks cost one global read when a test later disarms
_armed = False

_state_lock = threading.Lock()
_held = threading.local()               # .labels: list of lock labels
_held_by_thread: dict = {}              # ident -> list of labels
_class_fields: dict = {}                # class -> set of field names
_patched: set = set()
#: (id(obj), field) -> (ident, thread name, frozenset(locks), kind)
_last: dict = {}


class TrackedLock:
    """Proxy around a real lock that records which thread holds it.

    Transparent for correct code: ``acquire``/``release``/``with`` all
    forward to the wrapped lock; everything else (``locked``,
    ``notify``, ...) proxies via ``__getattr__``. Reentrant acquires
    push the label once per level so release bookkeeping stays exact.
    """

    def __init__(self, inner, label: str):
        self._inner = inner
        self._label = label

    def _push(self):
        labels = getattr(_held, "labels", None)
        if labels is None:
            labels = _held.labels = []
        labels.append(self._label)
        with _state_lock:
            _held_by_thread[threading.get_ident()] = list(labels)

    def _pop(self):
        labels = getattr(_held, "labels", None)
        if labels and self._label in labels:
            labels.reverse()
            labels.remove(self._label)
            labels.reverse()
        with _state_lock:
            ident = threading.get_ident()
            if labels:
                _held_by_thread[ident] = list(labels)
            else:
                _held_by_thread.pop(ident, None)

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._push()
        return got

    def release(self):
        self._inner.release()
        self._pop()

    def __enter__(self):
        self._inner.__enter__()
        self._push()
        return self

    def __exit__(self, *exc):
        self._pop()
        return self._inner.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def held_locks() -> tuple:
    """Lock labels the CALLING thread holds right now."""
    return tuple(getattr(_held, "labels", ()))


def all_held() -> dict:
    """``{thread_ident: [lock labels]}`` across every live thread."""
    with _state_lock:
        return {k: list(v) for k, v in _held_by_thread.items()}


def _on_access(obj, field: str, kind: str):
    if not _armed:
        return
    ident = threading.get_ident()
    locks = frozenset(getattr(_held, "labels", ()))
    key = (id(obj), field)
    _m_accesses.inc()
    with _state_lock:
        prev = _last.get(key)
        _last[key] = (ident, threading.current_thread().name, locks, kind)
    if prev is None or prev[0] == ident:
        return
    # trap only when the WRITE side is unlocked: a locked write observed
    # by a lock-free read is the benign atomic-read pattern
    cur_racy = kind == "write" and not locks
    prev_racy = prev[3] == "write" and not prev[2]
    if not (cur_racy or prev_racy):
        return
    _m_conflicts.inc()
    label = f"{type(obj).__name__}.{field}"
    telemetry.trace.instant("sanitizer/race_conflict", field=label)
    telemetry.flight.note("sanitizer/race_conflict", field=label,
                          thread=threading.current_thread().name,
                          other=prev[1])
    raise RaceConflict(
        f"unlocked {kind} of {label} by thread "
        f"{threading.current_thread().name!r} (holding "
        f"{sorted(locks) or 'no locks'}) races a {prev[3]} by thread "
        f"{prev[1]!r} (holding {sorted(prev[2]) or 'no locks'}) — take "
        f"the field's lock on BOTH sides or confine it to one thread")


def _patch(cls) -> None:
    if cls in _patched:
        return
    _patched.add(cls)
    orig_set = cls.__setattr__
    orig_get = cls.__getattribute__

    def __setattr__(self, name, value):
        fields = _class_fields.get(type(self))
        if fields is not None and name in fields:
            _on_access(self, name, "write")
        orig_set(self, name, value)

    def __getattribute__(self, name):
        value = orig_get(self, name)
        fields = _class_fields.get(type(self))
        if fields is not None and name in fields:
            _on_access(self, name, "read")
        return value

    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__


def instrument(obj, fields: Iterable[str], locks: Iterable[str] = (),
               label: Optional[str] = None):
    """Opt ``obj`` into race sanitizing (no-op unless armed): track the
    named ``fields`` and wrap the named ``locks`` attributes so held-
    lock sets are observable. Returns ``obj``."""
    global _armed
    if not enabled():
        return obj
    _armed = True
    cls = type(obj)
    want = set(fields)
    with _state_lock:
        _class_fields.setdefault(cls, set()).update(want)
    prefix = label or cls.__name__
    for lname in locks:
        raw = getattr(obj, lname, None)
        if raw is not None and not isinstance(raw, TrackedLock):
            object.__setattr__(obj, lname,
                               TrackedLock(raw, f"{prefix}.{lname}"))
    _patch(cls)
    return obj


def clear() -> None:
    """Forget access history and re-read the env knob (test isolation).
    Patched classes stay patched — their hooks gate on the armed flag."""
    global _armed
    with _state_lock:
        _last.clear()
        _held_by_thread.clear()
    _armed = enabled() and bool(_class_fields)


# ------------------------------------------------------------- thread dumps

def thread_dump(max_frames: int = 32, note: bool = True) -> dict:
    """Every live thread's stack joined with the sanitizer's held-lock
    map — the payload behind ``GET /debug/threads``. Mirrors a compact
    summary into the flight recorder (``note=False`` to skip, e.g. when
    the caller notes a richer record itself)."""
    frames = sys._current_frames()
    held = all_held()
    threads = []
    for t in sorted(threading.enumerate(), key=lambda t: t.ident or 0):
        fr = frames.get(t.ident)
        stack = traceback.format_stack(fr) if fr is not None else []
        if len(stack) > max_frames:
            stack = stack[-max_frames:]
        top = ""
        if fr is not None:
            top = (f"{fr.f_code.co_filename.rsplit('/', 1)[-1]}:"
                   f"{fr.f_lineno}:{fr.f_code.co_name}")
        threads.append({
            "name": t.name, "ident": t.ident, "daemon": t.daemon,
            "top": top,
            "held_locks": held.get(t.ident, []),
            "stack": [ln.rstrip("\n") for ln in stack]})
    doc = {"armed": _armed, "n_threads": len(threads),
           "locks_held": sum(len(v) for v in held.values()),
           "race_accesses": _m_accesses.value,
           "race_conflicts": _m_conflicts.value,
           "threads": threads}
    if note:
        telemetry.flight.note(
            "debug/threads", n_threads=len(threads),
            holders={str(i): v for i, v in held.items()},
            tops=[f"{t['name']}@{t['top']}" for t in threads])
    return doc
