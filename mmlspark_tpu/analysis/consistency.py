"""API-consistency rules: code and its catalogues must not drift.

* ``metric-catalogue`` — every metric registered through
  ``telemetry.registry.counter/gauge/histogram`` must appear (by its
  EXPOSITION name — counters gain ``_total``) in the metric catalogue
  table of ``docs/observability.md``, and vice versa. A dashboard built
  from the docs must never scrape a name that does not exist.
* ``span-catalogue`` — every literal span/instant name recorded through
  ``telemetry.trace.span/instant/complete`` (or handed to a prefetcher
  via a ``span=`` keyword) must appear in the span catalogue table, and
  vice versa.
* ``metric-aggregation`` — every metric catalogue row must declare its
  fleet-federation merge rule in the **Aggregation** column (counters
  ``sum``, histograms ``histogram``, gauges ``sum``/``max``/``last``),
  and the gauge cells must match the ``GAUGE_POLICIES`` table in
  ``telemetry/federation.py`` in BOTH directions — the merge the
  federated sampler performs and the merge the docs promise must be the
  same merge.
* ``exemplar-coverage`` — a histogram the catalogue marks
  exemplar-bearing (Type cell ``histogram (exemplars)``) must pass an
  ``exemplar=`` at every ``observe`` site: a latency histogram that
  promises trace links but observes without one has buckets that can
  never name the request that filled them.
* ``fault-site`` — every ``faults.inject("<site>")`` call site must name
  a site registered in ``resilience/faults.py``'s ``SITES`` tuple, and
  every registered site must have at least one injection call — a chaos
  spec naming a site nobody calls silently injects nothing.
* ``codegen-sync`` — committed codegen artifacts (``stubs/``,
  ``R/generated_wrappers.R``, ``docs/api/``) must match regeneration
  from the live Param registry (Python signatures are the single source
  of truth). Import-based; disable with ``options={"codegen": False}``
  (fixture projects) or ``--no-codegen``.
* ``pipeline-capture-coverage`` — every concrete ``Transformer`` whose
  ``transform`` (transitively) dispatches a jitted/pjit computation must
  either expose a ``capture()`` (the cross-stage fusion entry point,
  core/capture.py) or carry the explicit ``_uncapturable = True``
  marker, so the fused pipeline path can distinguish "host-only by
  design" from "capture forgotten". Since the fit side fuses too
  (``Pipeline.fusePipeline``), the same obligation covers estimator FIT
  bodies: a concrete ``Estimator`` whose ``fit`` dispatches a jitted
  computation must expose ``_fit_captured(df, plan)`` (the fused-fit
  hook) or carry ``_uncapturable = True``. Dispatch is an
  interprocedural fixed point over jit-bound names
  (``x = jax.jit(...)``, jit-decorated defs, ``profiler.wrap``),
  excluding delegation through the stage algebra's own
  ``transform``/``fit`` edges (composition stages like Timer defer the
  obligation to their inner stages).

Chaos-coverage rules (a fault-injection framework only pays for itself
when every recovery path it guards is actually rehearsed):

* ``chaos-test-coverage`` — every site registered in ``faults.SITES``
  must appear in at least one file under ``tests/`` (a grep-backed
  index): a site no test ever arms is a recovery path that has never
  run.
* ``chaos-retry-path`` — every ``RetryPolicy(...)`` / breaker
  construction in library code must live in a module with a
  ``faults.inject`` site on its IO path: a retry loop whose failure
  mode can't be injected is untestable by construction.
* ``chaos-io-site`` — IO call sites without a reachable fault site:
  outbound network calls (urlopen / requests / socket connects) whose
  enclosing class (or module, for top-level functions) never calls
  ``faults.inject``; HTTP handler classes (``do_GET``/``do_POST``)
  with no injection point; artifact writes under ``codegen/`` without
  a site. New IO paths must register a site as they land.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .core import Finding, Project, SourceFile, dotted, qualname_of, rule

_REG_METHODS = {"counter", "gauge", "histogram"}
_REG_RECEIVERS = {"registry", "REGISTRY"}
_TRACE_METHODS = {"span", "instant", "complete"}
_TRACE_RECEIVERS = {"trace", "TRACER"}


def _expo_name(name: str, kind: str) -> str:
    if kind == "counter" and not name.endswith("_total"):
        return name + "_total"
    return name


def _repo_root(project: Project) -> str:
    d = project.root
    for _ in range(5):
        if os.path.isfile(os.path.join(d, "pyproject.toml")):
            return d
        d = os.path.dirname(d)
    return project.root


def _doc_path(project: Project) -> Optional[str]:
    p = project.options.get("observability_doc")
    if p:
        return p
    p = os.path.join(_repo_root(project), "docs", "observability.md")
    return p if os.path.isfile(p) else None


def _doc_table_names(doc_text: str, heading: str) -> set:
    """Backticked names from the first cell of every row of the table
    under ``heading``. Suffix tokens (`_foo`) expand against the
    previous full name by replacing its trailing underscore segments."""
    out: set[str] = set()
    in_section = False
    prev_full: Optional[str] = None
    for line in doc_text.splitlines():
        if line.startswith("## "):
            in_section = line[3:].strip().lower().startswith(
                heading.lower())
            continue
        if not in_section or not line.strip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells or set(cells[0]) <= {"-", " ", ":"}:
            continue
        for tok in re.findall(r"`([^`]+)`", cells[0]):
            tok = re.sub(r"\{[^}]*\}", "", tok).strip()
            if not re.fullmatch(r"[A-Za-z_][\w/.\- ]*", tok):
                continue
            if tok.startswith("_") and prev_full:
                sfx = tok.lstrip("_").split("_")
                base = prev_full.split("_")
                merged = base[:max(1, len(base) - len(sfx))] + sfx
                out.add("_".join(merged))
            else:
                out.add(tok)
                prev_full = tok
    return out


def _doc_span_names(doc_text: str) -> set:
    return _doc_table_names(doc_text, "Span catalogue")


# ------------------------------------------------------------- registrations

def _registered_metrics(project: Project):
    """Yield (SourceFile, node, exposition_name, kind)."""
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _REG_METHODS:
                continue
            recv = dotted(node.func.value)
            if recv is None or recv.rsplit(".", 1)[-1] \
                    not in _REG_RECEIVERS:
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            yield (sf, node,
                   _expo_name(node.args[0].value, node.func.attr),
                   node.func.attr)


def _recorded_spans(project: Project):
    """Yield (SourceFile, node, span_name)."""
    for sf in project.files:
        if "/analysis/" in "/" + sf.rel:
            continue     # the analyzer's own string tables aren't spans
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name: Optional[str] = None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _TRACE_METHODS:
                recv = dotted(node.func.value)
                if recv is not None and recv.rsplit(".", 1)[-1] \
                        in _TRACE_RECEIVERS:
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        name = node.args[0].value
            if name is None:
                for kw in node.keywords:
                    if kw.arg == "span" and isinstance(kw.value,
                                                       ast.Constant) \
                            and isinstance(kw.value.value, str):
                        name = kw.value.value
            if name is not None:
                yield sf, node, name


@rule("metric-catalogue", "consistency",
      "registered metric names vs the docs/observability.md catalogue",
      scope="project")
def check_metric_catalogue(project: Project) -> Iterable[Finding]:
    regs = list(_registered_metrics(project))
    if not regs:
        return
    doc = _doc_path(project)
    if doc is None:
        sf = regs[0][0]
        f = sf.finding(
            "metric-catalogue", regs[0][1],
            "metrics are registered but no docs/observability.md metric "
            "catalogue exists to document them")
        if f:
            yield f
        return
    with open(doc, "r", encoding="utf-8") as fh:
        doc_text = fh.read()
    documented = {n for n in _doc_table_names(doc_text, "Metric catalogue")
                  if "_" in n and "/" not in n and " " not in n}
    seen: set[str] = set()
    for sf, node, name, kind in regs:
        seen.add(name)
        if name not in documented:
            f = sf.finding(
                "metric-catalogue", node,
                f"metric `{name}` ({kind}) is registered but missing from "
                f"the docs/observability.md metric catalogue",
                hint="add a catalogue row (exposition name, type, where, "
                     "meaning)",
                context=qualname_of([]))
            if f:
                yield f
    rel_doc = os.path.relpath(doc, _repo_root(project)).replace(os.sep, "/")
    for name in sorted(documented - seen):
        yield Finding(
            rule="metric-catalogue", path=rel_doc, line=1,
            message=f"documented metric `{name}` is not registered "
                    f"anywhere in the analyzed sources (stale catalogue "
                    f"row or renamed metric)",
            hint="fix or drop the catalogue row", context="<doc>",
            code=name)


_AGG_VALUES = {"sum", "max", "last", "histogram"}


def _doc_metric_rows(doc_text: str):
    """(names, type_cell, agg_cell, line_no) per metric-catalogue row.
    ``agg_cell`` is None when the table has no Aggregation column."""
    rows = []
    in_section = False
    type_i = agg_i = None
    for ln, line in enumerate(doc_text.splitlines(), start=1):
        if line.startswith("## "):
            in_section = line[3:].strip().lower().startswith(
                "metric catalogue")
            type_i = agg_i = None
            continue
        if not in_section or not line.strip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells or set(cells[0]) <= {"-", " ", ":"}:
            continue
        if type_i is None:
            headers = [c.lower() for c in cells]
            type_i = headers.index("type") if "type" in headers else 1
            agg_i = (headers.index("aggregation")
                     if "aggregation" in headers else None)
            continue
        names = {re.sub(r"\{[^}]*\}", "", tok).strip()
                 for tok in re.findall(r"`([^`]+)`", cells[0])}
        names = {n for n in names
                 if re.fullmatch(r"[A-Za-z_]\w*", n) and "_" in n}
        if not names:
            continue
        typ = cells[type_i] if type_i < len(cells) else ""
        agg = (cells[agg_i] if agg_i is not None and agg_i < len(cells)
               else None)
        rows.append((names, typ, agg, ln))
    return rows


def _gauge_policies(project: Project):
    """(SourceFile, node, {name: policy}) from the GAUGE_POLICIES dict
    literal in telemetry/federation.py."""
    for sf in project.files:
        if not sf.rel.endswith("federation.py"):
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "GAUGE_POLICIES"
                    for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                pol = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(v, ast.Constant):
                        pol[k.value] = v.value
                return sf, node, pol
    return None, None, {}


@rule("metric-aggregation", "consistency",
      "the metric catalogue's Aggregation column vs the federation "
      "merge policy (GAUGE_POLICIES) — both directions",
      scope="project")
def check_metric_aggregation(project: Project) -> Iterable[Finding]:
    pol_sf, pol_node, policies = _gauge_policies(project)
    if pol_sf is None:
        return          # no federation layer in this project
    doc = _doc_path(project)
    if doc is None:
        return
    with open(doc, "r", encoding="utf-8") as fh:
        doc_text = fh.read()
    rows = _doc_metric_rows(doc_text)
    if not rows:
        return
    rel_doc = os.path.relpath(doc, _repo_root(project)).replace(os.sep, "/")
    documented_gauges: dict[str, str] = {}
    for names, typ, agg, ln in rows:
        first = sorted(names)[0]
        if agg is None or agg not in _AGG_VALUES:
            yield Finding(
                rule="metric-aggregation", path=rel_doc, line=ln,
                message=f"catalogue row for `{first}` declares no valid "
                        f"Aggregation cell (got {agg!r}) — the fleet "
                        f"federation merge rule for this metric is "
                        f"undocumented",
                hint="add the Aggregation column cell: counters `sum`, "
                     "histograms `histogram`, gauges `sum`/`max`/`last`",
                context="<doc>", code=first)
            continue
        # the Type cell may carry qualifiers after the kind — e.g.
        # `histogram (exemplars)` for exemplar-bearing latency histograms
        # (the exemplar-coverage rule keys off that marker); only the
        # leading word is the metric kind
        kind = typ.split()[0] if typ.split() else ""
        expected = {"counter": "sum", "histogram": "histogram"}.get(kind)
        if expected is not None and agg != expected:
            yield Finding(
                rule="metric-aggregation", path=rel_doc, line=ln,
                message=f"catalogue row for `{first}` ({typ}) declares "
                        f"Aggregation `{agg}` but every {kind} merges as "
                        f"`{expected}` across the fleet",
                hint=f"set the cell to `{expected}`",
                context="<doc>", code=first)
        if kind == "gauge":
            for n in names:
                base = n[:-6] if n.endswith("_total") else n
                documented_gauges[base] = agg
                declared = policies.get(n, policies.get(base, "sum"))
                if agg != declared:
                    yield Finding(
                        rule="metric-aggregation", path=rel_doc, line=ln,
                        message=f"catalogue row declares gauge `{n}` "
                                f"merges by `{agg}` but "
                                f"telemetry/federation.py GAUGE_POLICIES "
                                f"resolves it to `{declared}` — the docs "
                                f"and the federated sampler disagree",
                        hint="fix the Aggregation cell or the "
                             "GAUGE_POLICIES entry",
                        context="<doc>", code=n)
    for name, declared in sorted(policies.items()):
        if name not in documented_gauges:
            f = pol_sf.finding(
                "metric-aggregation", pol_node,
                f"GAUGE_POLICIES declares `{name}` merges by "
                f"`{declared}` but no gauge row in the metric catalogue "
                f"documents it — stale policy entry or renamed metric",
                hint="drop the entry or fix the catalogue row",
                context="GAUGE_POLICIES")
            if f:
                yield f


@rule("exemplar-coverage", "consistency",
      "histograms the catalogue marks exemplar-bearing (`histogram "
      "(exemplars)` Type cell) must pass an exemplar at every observe "
      "site", scope="project")
def check_exemplar_coverage(project: Project) -> Iterable[Finding]:
    doc = _doc_path(project)
    if doc is None:
        return
    with open(doc, "r", encoding="utf-8") as fh:
        doc_text = fh.read()
    marked: set[str] = set()
    for names, typ, _agg, _ln in _doc_metric_rows(doc_text):
        if "exemplar" in typ.lower():
            marked |= names
    if not marked:
        return
    # handle name -> metric name, project-wide: the registration handle
    # (`_m_req_latency = telemetry.registry.histogram("...")`) is how
    # observe sites name the metric, including across module imports
    handles: dict[str, str] = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if not (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "histogram"):
                continue
            recv = dotted(call.func.value)
            if recv is None or recv.rsplit(".", 1)[-1] \
                    not in _REG_RECEIVERS:
                continue
            if not (call.args and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                    and call.args[0].value in marked):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    handles[t.id] = call.args[0].value
                elif isinstance(t, ast.Attribute):
                    handles[t.attr] = call.args[0].value
    if not handles:
        return
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "observe"):
                continue
            recv = dotted(node.func.value)
            term = recv.rsplit(".", 1)[-1] if recv else None
            if term not in handles:
                continue
            has_exemplar = len(node.args) >= 2 or any(
                kw.arg == "exemplar" for kw in node.keywords)
            if has_exemplar:
                continue
            f = sf.finding(
                "exemplar-coverage", node,
                f"histogram `{handles[term]}` is catalogued as "
                f"exemplar-bearing but this observe() passes no exemplar "
                f"— observations through this site can never link their "
                f"bucket to a trace",
                hint="pass exemplar=<trace_id or None> at every observe "
                     "site of an exemplar-marked histogram (None when "
                     "tail sampling retained nothing)",
                context=term)
            if f:
                yield f


@rule("span-catalogue", "consistency",
      "recorded span/instant names vs the docs span catalogue",
      scope="project")
def check_span_catalogue(project: Project) -> Iterable[Finding]:
    spans = list(_recorded_spans(project))
    if not spans:
        return
    doc = _doc_path(project)
    if doc is None:
        return
    with open(doc, "r", encoding="utf-8") as fh:
        doc_text = fh.read()
    documented = _doc_span_names(doc_text)
    if not documented:
        sf, node, name = spans[0]
        f = sf.finding(
            "span-catalogue", node,
            "spans are recorded but docs/observability.md has no "
            "`## Span catalogue` table",
            hint="add the table; every literal span/instant name belongs "
                 "in it")
        if f:
            yield f
        return
    seen: set[str] = set()
    for sf, node, name in spans:
        if name in seen:
            continue
        seen.add(name)
        if name not in documented:
            f = sf.finding(
                "span-catalogue", node,
                f"span/instant `{name}` is recorded but missing from the "
                f"docs/observability.md span catalogue",
                hint="add a span-catalogue row")
            if f:
                yield f
    rel_doc = os.path.relpath(doc, _repo_root(project)).replace(os.sep, "/")
    for name in sorted(d for d in documented if d not in seen):
        yield Finding(
            rule="span-catalogue", path=rel_doc, line=1,
            message=f"documented span `{name}` is never recorded in the "
                    f"analyzed sources",
            hint="fix or drop the span-catalogue row", context="<doc>",
            code=name)


@rule("fault-site", "consistency",
      "faults.inject sites vs the SITES registry in resilience/faults.py",
      scope="project")
def check_fault_sites(project: Project) -> Iterable[Finding]:
    # registered sites: the SITES tuple in a module named faults.py
    registered: set[str] = set()
    faults_sf: Optional[SourceFile] = None
    sites_node = None
    for sf in project.files:
        if not sf.rel.endswith("faults.py"):
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "SITES"
                    for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        registered.add(sub.value)
                faults_sf, sites_node = sf, node
    injected: dict[str, tuple[SourceFile, ast.AST]] = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted(node.func)
            if dn is None or dn.rsplit(".", 1)[-1] != "inject":
                continue
            if not (dn == "inject" or dn.endswith("faults.inject")):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                injected.setdefault(node.args[0].value, (sf, node))
    if not injected and not registered:
        return
    if not registered and injected:
        sf, node = next(iter(injected.values()))
        f = sf.finding(
            "fault-site", node,
            "faults.inject sites exist but resilience/faults.py declares "
            "no SITES registry tuple",
            hint="declare SITES = (\"site\", ...) next to the docstring "
                 "site list")
        if f:
            yield f
        return
    for site, (sf, node) in sorted(injected.items()):
        if site not in registered:
            f = sf.finding(
                "fault-site", node,
                f"fault site `{site}` is injected but not registered in "
                f"resilience/faults.py SITES — chaos specs can't "
                f"discover it and typos go unnoticed",
                hint="add it to SITES (and the docstring site list)")
            if f:
                yield f
    for site in sorted(registered - set(injected)):
        if faults_sf is not None:
            f = faults_sf.finding(
                "fault-site", sites_node,
                f"registered fault site `{site}` has no faults.inject "
                f"call anywhere — a chaos spec naming it injects nothing",
                hint="remove it from SITES or add the injection site")
            if f:
                yield f


@rule("codegen-sync", "consistency",
      "committed stubs/R/docs-api artifacts vs regeneration",
      scope="project")
def check_codegen(project: Project) -> Iterable[Finding]:
    if not project.options.get("codegen", False):
        return
    root = _repo_root(project)
    try:
        import tempfile

        import mmlspark_tpu  # noqa: F401  (populates the stage registry)
        from mmlspark_tpu import codegen as cg
    except Exception as e:  # pragma: no cover - import environment issues
        yield Finding(
            rule="codegen-sync", path="mmlspark_tpu/codegen/__init__.py",
            line=1, context="<import>", code="import mmlspark_tpu.codegen",
            message=f"codegen could not be imported for the sync check: "
                    f"{e}")
        return

    def _read_tree(d: str) -> dict:
        out = {}
        for base, _dirs, names in os.walk(d):
            for n in sorted(names):
                p = os.path.join(base, n)
                rel = os.path.relpath(p, d)
                with open(p, "r", encoding="utf-8") as fh:
                    out[rel] = fh.read()
        return out

    with tempfile.TemporaryDirectory() as tmp:
        checks = []
        try:
            cg.generate_docs(os.path.join(tmp, "api"))
            checks.append(("docs/api", os.path.join(tmp, "api"),
                           os.path.join(root, "docs", "api")))
            cg.generate_stubs(os.path.join(tmp, "stubs"))
            checks.append(("stubs", os.path.join(tmp, "stubs"),
                           os.path.join(root, "stubs")))
            cg.generate_r_wrappers(os.path.join(tmp, "wrappers.R"))
            checks.append(("R/generated_wrappers.R",
                           os.path.join(tmp, "wrappers.R"),
                           os.path.join(root, "R",
                                        "generated_wrappers.R")))
        except Exception as e:  # pragma: no cover
            yield Finding(
                rule="codegen-sync",
                path="mmlspark_tpu/codegen/__init__.py", line=1,
                context="<generate>", code="generate_all",
                message=f"codegen regeneration failed: {e}")
            return
        for label, fresh_path, committed_path in checks:
            if not os.path.exists(committed_path):
                continue    # artifact never generated in this checkout
            if os.path.isdir(fresh_path):
                fresh = _read_tree(fresh_path)
                committed = _read_tree(committed_path)
            else:
                with open(fresh_path, "r", encoding="utf-8") as fh:
                    fresh = {"": fh.read()}
                with open(committed_path, "r", encoding="utf-8") as fh:
                    committed = {"": fh.read()}
            if fresh != committed:
                stale = sorted(
                    set(fresh) ^ set(committed)
                    | {k for k in set(fresh) & set(committed)
                       if fresh[k] != committed[k]})
                yield Finding(
                    rule="codegen-sync", path=label, line=1,
                    context="<artifact>", code=label,
                    message=f"committed {label} out of sync with the "
                            f"Param registry ({len(stale)} file(s) "
                            f"differ: {', '.join(stale[:5])}"
                            f"{'...' if len(stale) > 5 else ''})",
                    hint="run `python -m mmlspark_tpu.codegen` and commit "
                         "the result")


# ---------------------------------------------------------- chaos coverage

def _tests_dir(project: Project) -> Optional[str]:
    p = project.options.get("tests_dir")
    if p:
        return p if os.path.isdir(p) else None
    p = os.path.join(_repo_root(project), "tests")
    return p if os.path.isdir(p) else None


def _tests_index(tests_dir: str) -> str:
    """The concatenated text of every test file — the grep-backed index
    the coverage rule matches site names against."""
    chunks = []
    for base, dirs, names in os.walk(tests_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for n in sorted(names):
            if n.endswith(".py"):
                try:
                    with open(os.path.join(base, n), encoding="utf-8") as f:
                        chunks.append(f.read())
                except OSError:
                    continue
    return "\n".join(chunks)


def _sites_registry(project: Project):
    """(SourceFile, SITES assign node, {site names}) from faults.py."""
    for sf in project.files:
        if not sf.rel.endswith("faults.py"):
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "SITES"
                    for t in node.targets):
                sites = {sub.value for sub in ast.walk(node.value)
                         if isinstance(sub, ast.Constant)
                         and isinstance(sub.value, str)}
                return sf, node, sites
    return None, None, set()


@rule("chaos-test-coverage", "consistency",
      "every faults.SITES entry must be exercised by at least one test",
      scope="project")
def check_chaos_test_coverage(project: Project) -> Iterable[Finding]:
    sf, node, sites = _sites_registry(project)
    if sf is None or not sites:
        return
    tests = _tests_dir(project)
    if tests is None:
        return          # fixture projects without a tests tree
    index = _tests_index(tests)
    for site in sorted(sites):
        if site in index:
            continue
        f = sf.finding(
            "chaos-test-coverage", node,
            f"fault site `{site}` is registered but no file under "
            f"tests/ ever names it — the recovery path it guards has "
            f"never been rehearsed",
            hint="add a chaos test that arms the site "
                 "(faults.configure(f'{site}:error:1.0')) and asserts "
                 "the recovery behavior",
            context="SITES")
        if f:
            yield f


# ------------------------------------------------- pipeline capture coverage

#: constructing/holding one of these means device computation is being
#: compiled — a transform reaching one dispatches a jitted program
_CC_JIT_WRAPPERS = {
    "jax.jit", "jit", "jax.pjit", "pjit", "jax.shard_map", "shard_map",
    "jax.experimental.shard_map.shard_map", "profiler.wrap",
    "telemetry.profiler.wrap", "ProfiledFunction"}
#: stage-algebra method names excluded from call-graph propagation: a
#: stage delegating to an INNER stage's transform (Timer, adapters,
#: PipelineModel) is a composition point — the inner stage carries its
#: own capture obligation
_CC_NO_PROPAGATE = {"transform", "fit", "__call__", "capture"}
_CC_STAGE_BASES = {"Transformer", "Model", "UnaryTransformer"}
_CC_ESTIMATOR_BASES = {"Estimator"}
#: the core contract classes whose default capture()/_uncapturable must
#: NOT satisfy the rule for subclasses
_CC_CORE_BASES = _CC_STAGE_BASES | _CC_ESTIMATOR_BASES | {"PipelineStage"}


class _CCFunc:
    __slots__ = ("sf", "node", "name", "direct", "calls", "nested")

    def __init__(self, sf, node, name, direct, calls, nested):
        self.sf = sf
        self.node = node
        self.name = name
        self.direct = direct
        self.calls = calls
        #: defined inside another function — invoked only locally, so it
        #: never participates in cross-function by-name propagation
        #: (generic names like `fn` / a jitted nested `run` would
        #: otherwise taint every caller of ANY `fn`/`run`)
        self.nested = nested


def _cc_scan_file(sf: SourceFile):
    """(functions, classes, jit-bound names) of one module.

    jit-bound names: assignment targets whose value is a jit/pjit/
    shard_map/profiler.wrap construction (incl. ``self._x = jax.jit(f)``)
    plus defs decorated with one — calling such a name dispatches."""
    jit_names: set[str] = set()
    funcs: list[_CCFunc] = []
    classes: dict[str, dict] = {}

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            dn = dotted(node.value.func)
            if dn in _CC_JIT_WRAPPERS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jit_names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        jit_names.add(t.attr)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dd = dotted(dec if not isinstance(dec, ast.Call)
                            else dec.func)
                if dd in _CC_JIT_WRAPPERS \
                        or (isinstance(dec, ast.Call) and dec.args
                            and dotted(dec.args[0]) in _CC_JIT_WRAPPERS):
                    jit_names.add(node.name)

    def scan_fn(node):
        direct = False
        calls: set[str] = set()
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            dn = dotted(sub.func)
            term = dn.rsplit(".", 1)[-1] if dn else ""
            if dn in _CC_JIT_WRAPPERS:
                direct = True       # constructs/holds a jitted callable
            elif term and term in jit_names:
                direct = True       # invokes a jit-bound name
            elif term and term not in _CC_NO_PROPAGATE:
                calls.add(term)
        return direct, calls

    def walk(node, cls, in_func=False):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                info = classes.setdefault(child.name, {
                    "sf": sf, "node": child, "bases": [], "methods": {},
                    "abstract": False, "uncapturable": False})
                for b in child.bases:
                    bn = dotted(b)
                    if bn:
                        info["bases"].append(bn.rsplit(".", 1)[-1])
                for stmt in child.body:
                    if isinstance(stmt, ast.Assign):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name) \
                                    and t.id == "_abstract" \
                                    and isinstance(stmt.value, ast.Constant) \
                                    and stmt.value.value:
                                info["abstract"] = True
                            if isinstance(t, ast.Name) \
                                    and t.id == "_uncapturable" \
                                    and isinstance(stmt.value, ast.Constant) \
                                    and stmt.value.value:
                                info["uncapturable"] = True
                    elif isinstance(stmt, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        info["methods"][stmt.name] = stmt
                walk(child, child, in_func)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                direct, calls = scan_fn(child)
                funcs.append(_CCFunc(sf, child, child.name, direct, calls,
                                     nested=in_func))
                walk(child, cls, True)
            else:
                walk(child, cls, in_func)

    walk(sf.tree, None)
    return funcs, classes


@rule("pipeline-capture-coverage", "consistency",
      "every Transformer whose transform (and Estimator whose fit) "
      "dispatches a jitted computation must expose a capture() (resp. "
      "_fit_captured()) or carry an explicit _uncapturable marker",
      scope="project")
def check_pipeline_capture_coverage(project: Project) -> Iterable[Finding]:
    all_funcs: list[_CCFunc] = []
    all_classes: dict[str, dict] = {}
    for sf in project.files:
        if _is_test_rel(sf.rel) or "/analysis/" in "/" + sf.rel:
            continue
        funcs, classes = _cc_scan_file(sf)
        all_funcs.extend(funcs)
        for name, info in classes.items():
            all_classes.setdefault(name, info)
    if not all_classes:
        return
    # fixed point: a function dispatches if it calls (by terminal name)
    # any project function that dispatches — an over-approximation that
    # crosses modules (transform -> engine.predict_raw -> jitted run)
    by_name: dict[str, list[_CCFunc]] = {}
    for f in all_funcs:
        if not f.nested:
            by_name.setdefault(f.name, []).append(f)
    dispatching = {id(f) for f in all_funcs if f.direct}
    changed = True
    while changed:
        changed = False
        for f in all_funcs:
            if id(f) in dispatching:
                continue
            for callee in f.calls:
                if any(id(g) in dispatching
                       for g in by_name.get(callee, ())):
                    dispatching.add(id(f))
                    changed = True
                    break

    def reaches_base(name: str, bases: set, seen: set) -> bool:
        if name in seen:
            return False
        seen.add(name)
        info = all_classes.get(name)
        if info is None:
            return False
        for b in info["bases"]:
            if b in bases or reaches_base(b, bases, seen):
                return True
        return False

    def is_stage_class(name: str, seen: set) -> bool:
        return reaches_base(name, _CC_STAGE_BASES, seen)

    def chain(name: str):
        """The class + its project-defined ancestors, nearest first,
        stopping at (and excluding) the core contract bases."""
        out, queue, seen = [], [name], set()
        while queue:
            n = queue.pop(0)
            if n in seen or n in _CC_CORE_BASES:
                continue
            seen.add(n)
            info = all_classes.get(n)
            if info is None:
                continue
            out.append(info)
            queue.extend(info["bases"])
        return out

    for name, info in sorted(all_classes.items()):
        if info["abstract"] or not is_stage_class(name, set()):
            continue
        lineage = chain(name)
        transform_def = next((c["methods"]["transform"] for c in lineage
                              if "transform" in c["methods"]), None)
        if transform_def is None:
            continue
        tf = next((f for f in all_funcs if f.node is transform_def), None)
        if tf is None or id(tf) not in dispatching:
            continue
        covered = any("capture" in c["methods"] or c["uncapturable"]
                      for c in lineage)
        if covered:
            continue
        f = info["sf"].finding(
            "pipeline-capture-coverage", info["node"],
            f"Transformer `{name}` dispatches a jitted computation in its "
            f"transform but neither exposes a capture() nor carries the "
            f"explicit `_uncapturable = True` marker — the fused pipeline "
            f"path (core/capture.py) cannot tell \"host-only by design\" "
            f"from \"capture forgotten\"",
            hint="implement capture(columns) returning a StageCapture "
                 "(preferred for device stages), or declare "
                 "`_uncapturable = True` with a one-line justification",
            context=name)
        if f:
            yield f

    # fit-side twin: a trainer whose fit dispatches jitted computation
    # must either accept a fused featurize plan (_fit_captured — the
    # Pipeline.fusePipeline fit hook) or declare itself out of the fused
    # fit path explicitly
    for name, info in sorted(all_classes.items()):
        if info["abstract"] \
                or not reaches_base(name, _CC_ESTIMATOR_BASES, set()) \
                or is_stage_class(name, set()):
            continue
        lineage = chain(name)
        fit_def = next((c["methods"]["fit"] for c in lineage
                        if "fit" in c["methods"]), None)
        if fit_def is None:
            continue
        ff = next((f for f in all_funcs if f.node is fit_def), None)
        if ff is None or id(ff) not in dispatching:
            continue
        covered = any("_fit_captured" in c["methods"] or c["uncapturable"]
                      for c in lineage)
        if covered:
            continue
        f = info["sf"].finding(
            "pipeline-capture-coverage", info["node"],
            f"Estimator `{name}` dispatches a jitted computation in its "
            f"fit but neither exposes a _fit_captured() fused-fit hook "
            f"nor carries the explicit `_uncapturable = True` marker — "
            f"the fit-side fusion path (Pipeline.fusePipeline) cannot "
            f"tell \"staged fit by design\" from \"hook forgotten\"",
            hint="implement _fit_captured(df, plan) accepting a "
                 "FitCapturePlan (preferred for trainers), or declare "
                 "`_uncapturable = True` with a one-line justification",
            context=name)
        if f:
            yield f


_POLICY_CTORS = {"RetryPolicy", "CircuitBreaker"}


def _module_has_inject(sf: SourceFile) -> bool:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            dn = dotted(node.func)
            if dn is not None and dn.rsplit(".", 1)[-1] == "inject":
                return True
    return False


def _is_test_rel(rel: str) -> bool:
    parts = rel.split("/")
    return (any(p in ("tests", "testing", "fixtures") for p in parts)
            or parts[-1].startswith("test_"))


@rule("chaos-retry-path", "consistency",
      "RetryPolicy/breaker constructions in modules with no fault site "
      "on their IO path")
def check_chaos_retry_path(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_rel(sf.rel) or sf.rel.endswith("resilience/policy.py"):
            continue    # the defining module ships no IO of its own
        has_inject = _module_has_inject(sf)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted(node.func)
            if dn is None or dn.rsplit(".", 1)[-1] not in _POLICY_CTORS:
                continue
            if has_inject:
                continue
            f = sf.finding(
                "chaos-retry-path", node,
                f"`{dn.rsplit('.', 1)[-1]}` constructed in a module with "
                f"no faults.inject site — the failure mode this policy "
                f"guards cannot be injected, so its recovery path is "
                f"untestable",
                hint="add a faults.inject(\"<site>\") on the IO path the "
                     "policy retries (and register the site in "
                     "resilience/faults.py SITES)",
                context=sf.rel)
            if f:
                yield f


_NET_CALLS = {"urllib.request.urlopen", "urlopen", "requests.get",
              "requests.post", "requests.put", "requests.delete",
              "requests.head", "requests.request",
              "socket.create_connection"}
_HANDLER_METHODS = {"do_GET", "do_POST"}


def _enclosing_scopes(sf: SourceFile):
    """Yield (node, enclosing ClassDef or None) for every Call/def."""
    out = []

    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            ncls = child if isinstance(child, ast.ClassDef) else cls
            out.append((child, cls))
            walk(child, ncls)

    walk(sf.tree, None)
    return out


def _scope_has_inject(scope_node) -> bool:
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Call):
            dn = dotted(node.func)
            if dn is not None and dn.rsplit(".", 1)[-1] == "inject":
                return True
    return False


@rule("chaos-io-site", "consistency",
      "IO call sites (network / HTTP handlers / codegen writes) with no "
      "fault-injection site in scope")
def check_chaos_io_site(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_rel(sf.rel) or "/analysis/" in "/" + sf.rel:
            continue
        module_inject = _module_has_inject(sf)
        for node, cls in _enclosing_scopes(sf):
            # 1) HTTP handler methods: the handler class must carry a site
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in _HANDLER_METHODS and cls is not None:
                if not _scope_has_inject(cls):
                    f = sf.finding(
                        "chaos-io-site", node,
                        f"HTTP handler `{cls.name}.{node.name}` serves "
                        f"responses with no faults.inject site in its "
                        f"class — the handler's failure behavior can't "
                        f"be chaos-tested",
                        hint="inject a registered site at the top of the "
                             "handler (e.g. `http.debug`) and answer "
                             "injected faults with a 5xx",
                        context=f"{cls.name}.{node.name}")
                    if f:
                        yield f
                continue
            if not isinstance(node, ast.Call):
                continue
            dn = dotted(node.func)
            term = dn.rsplit(".", 1)[-1] if dn else ""
            # 2) outbound network calls: class (or module) must inject
            if (dn in _NET_CALLS or term == "urlopen"):
                covered = (module_inject if cls is None
                           else _scope_has_inject(cls))
                if not covered:
                    f = sf.finding(
                        "chaos-io-site", node,
                        f"outbound network call `{dn}` with no "
                        f"faults.inject site in its enclosing "
                        f"{'class' if cls is not None else 'module'} — "
                        f"a new IO path landed without a registered "
                        f"fault site",
                        hint="register a site in resilience/faults.py "
                             "SITES and inject it next to the call",
                        context=cls.name if cls is not None else sf.rel)
                    if f:
                        yield f
            # 3) artifact writes in codegen modules
            elif term == "open" and "/codegen/" in "/" + sf.rel:
                mode = None
                if len(node.args) >= 2 and isinstance(node.args[1],
                                                      ast.Constant):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value,
                                                       ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and "w" in mode \
                        and not module_inject:
                    f = sf.finding(
                        "chaos-io-site", node,
                        "codegen artifact write with no faults.inject "
                        "site in the module — generated-file IO "
                        "failures (full disk, readonly checkout) have "
                        "no rehearsed recovery",
                        hint="route writes through a helper that "
                             "injects `codegen.write`",
                        context=sf.rel)
                    if f:
                        yield f
