"""graftlint: framework-aware static analysis for mmlspark_tpu.

Three rule families encode the invariants the test suite cannot see
(they only bite at TPU scale or under production concurrency):

* **jit-safety** — host syncs / Python control flow on traced values,
  set-order iteration and jit-in-loop recompile hazards, missing
  ``donate_argnums`` on documented-donated buffers, unseeded RNGs in
  library code;
* **concurrency** — a lock-order graph over every ``with <lock>:`` scope
  (cycles, same-lock reacquire), blocking calls made while holding a
  lock, and ``# guarded-by:`` field annotations checked at every
  mutation site;
* **consistency** — metric/span names vs the ``docs/observability.md``
  catalogues, ``faults.inject`` sites vs the ``SITES`` registry, and
  committed codegen artifacts (stubs / R wrappers / API docs) vs
  regeneration.

Run it as ``python -m mmlspark_tpu.analysis`` (console script:
``graftlint``); CI runs it via ``tests/test_analysis.py`` and fails on
any finding not grandfathered in ``tools/graftlint_baseline.json``.
Suppress a single site with ``# graftlint: disable=<rule>``. See
``docs/static-analysis.md``.
"""

from .core import (Baseline, Finding, Project, SourceFile, all_rules,
                   load_project, run_analysis)
from .cli import main

__all__ = ["Baseline", "Finding", "Project", "SourceFile", "all_rules",
           "load_project", "run_analysis", "main"]
