"""graftlint: framework-aware static analysis for mmlspark_tpu.

Six rule families encode the invariants the test suite cannot see
(they only bite at TPU scale, under production concurrency, or when the
power goes out mid-commit):

* **jit-safety** — host syncs / Python control flow on traced values,
  set-order iteration and jit-in-loop recompile hazards, missing
  ``donate_argnums`` on documented-donated buffers, unseeded RNGs in
  library code;
* **donation** — an interprocedural taint walk from host-buffer origins
  (``np.*``, arrow/zero-copy decoders, checkpoint restores) to donated
  argument positions of jitted dispatches (the PR 7 arrow-fitstream /
  PR 9 post-resume corruption class), plus use-after-donate; the
  runtime twin is :mod:`mmlspark_tpu.analysis.sanitize`
  (``MMLSPARK_TPU_SANITIZE=donation``);
* **protocol** — collectives whose axis is absent from the enclosing
  shard_map spec, collectives under per-rank-divergent conditions,
  blocking calls on attempt/watcher threads, and commit-ordering
  violations (rename before fsync, manifest before payload);
* **concurrency** — a lock-order graph over every ``with <lock>:`` scope
  (cycles, same-lock reacquire), blocking calls made while holding a
  lock, and ``# guarded-by:`` field annotations checked at every
  mutation site;
* **races** — whole-program cross-thread race detection: thread-root
  discovery (Thread/Timer targets, executor submits, HTTP handler
  classes, signal/atexit hooks), escape analysis of which fields and
  globals are reachable from ≥2 roots, and access classification
  (unguarded writes, compound read-modify-write, started-before-init,
  majority-lock inference suggesting the ``# guarded-by:`` annotation
  to add); the runtime twin is
  :mod:`mmlspark_tpu.analysis.sanitize_races`
  (``MMLSPARK_TPU_SANITIZE=races``);
* **consistency** — metric/span names vs the ``docs/observability.md``
  catalogues, ``faults.inject`` sites vs the ``SITES`` registry,
  chaos coverage (every site exercised by a test, every retry policy
  injectable, no IO path without a site), and committed codegen
  artifacts (stubs / R wrappers / API docs) vs regeneration.

Run it as ``python -m mmlspark_tpu.analysis`` (console script:
``graftlint``); CI runs it via ``tests/test_analysis.py`` and fails on
any finding not grandfathered in ``tools/graftlint_baseline.json``.
``--sarif OUT`` emits SARIF 2.1.0 for code-scanning UIs;
``--changed-only`` reuses content-hash-keyed cached results.
Suppress a single site with ``# graftlint: disable=<rule>``. See
``docs/static-analysis.md``.
"""

from .core import (Baseline, Finding, Project, SourceFile, all_rules,
                   load_project, run_analysis)
from .cli import main

__all__ = ["Baseline", "Finding", "Project", "SourceFile", "all_rules",
           "load_project", "run_analysis", "main"]
