"""Cross-thread race rules: thread roots, escaped state, unlocked access.

The concurrency family checks *annotated* locks (``# guarded-by:`` /
``# requires-lock:``); an unguarded shared field added by a new PR is
invisible to it until the field corrupts a fleet run. This family closes
that gap with a whole-program pass in three stages:

1. **Thread-root discovery** — every concurrent entry point:
   ``threading.Thread(target=...)`` / ``threading.Timer`` spawns
   (including lambda and nested-def closure targets),
   ``ThreadPoolExecutor.submit``/``.map``, ``BaseHTTPRequestHandler``
   subclasses (each request runs on its own thread under the threading
   server, so every handler method is a MANY-instance root), and
   ``signal.signal`` / ``atexit.register`` hooks. A spawn inside a
   ``for``/``while`` body is many-instance too.
2. **Escape analysis** — which ``self._field`` attributes (and module
   globals) are reachable from two or more roots. ``self`` captured in
   a target/closure counts (``source = self`` + a nested handler class
   touching ``source._field`` attributes the accesses to the outer
   class), and a method transitively called from a root (BFS over
   ``self.m()`` edges) inherits that root. Methods not reachable from
   any thread root belong to the ``<caller>`` root — the constructing /
   driver thread that invokes the public API.
3. **Access classification** — the same lexically-held-lock walk the
   concurrency family uses labels every shared access read / write /
   compound (``+=``, ``self.x = self.x + ...``, check-then-act ``if k
   not in d: d[k] = ...``) and records the lock set held there.

Rules (all skip fields that carry a ``# guarded-by:`` annotation — the
concurrency family owns those — and all exempt ``__init__``, whose
writes happen before any thread this class spawns exists; the spawn
ordering itself is checked by ``race-thread-started-before-init``):

* ``race-unguarded-write`` — a shared field written from ≥2 roots with
  no lock held at any of its access sites.
* ``race-compound-rmw`` — a read-modify-write on a shared field outside
  any lock. GIL-atomic-looking ones count: ``d[k] += 1`` is a read, an
  add and a store, and another thread's store lands between them.
* ``race-guarded-by-missing`` — a shared field where a *majority lock*
  exists (most accesses hold the same lock) but some write site doesn't
  hold it. The finding suggests the inferred ``# guarded-by:``
  annotation, so the fix is either locking the stray site or declaring
  the discipline and letting the guarded-by rule enforce it forever.
* ``race-thread-started-before-init`` — ``__init__`` starts a thread
  (or registers a handler server) before assigning a field the thread's
  target (transitively) reads: the new thread can observe the
  half-constructed object.

Fields whose declared value is an internally-synchronized type
(``queue.Queue``, ``threading.Event``/locks, ``collections.deque``,
executors) are exempt from method-call mutation events — calling
``.put()`` on a shared Queue is the point of a Queue — but *rebinding*
such a field is still a write.

The runtime twin is :mod:`mmlspark_tpu.analysis.sanitize_races`
(``MMLSPARK_TPU_SANITIZE=races``): instrumented classes record
(thread-id, held-lock set) per field access and trap a conflicting
unlocked write at the moment it happens.
"""

from __future__ import annotations

import ast
import hashlib
import re
import weakref
from typing import Iterable, Optional

from .core import Finding, Project, SourceFile, dotted, qualname_of, rule
from .concurrency import (_GUARDED_RE, _MUTATORS, _collect_classes,
                          _module_locks, _terminal, _ClassInfo)

#: the implicit root: public API invoked by whoever constructed the
#: object (the driver / test / caller thread)
CALLER = "<caller>"

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}
_HANDLER_BASES = {"BaseHTTPRequestHandler", "StreamRequestHandler",
                  "BaseRequestHandler", "SimpleHTTPRequestHandler"}
_POOLISH = re.compile(r"(^|_)(pool|executor|workers)$")

#: value constructors whose instances are internally synchronized —
#: method calls on such fields are not racy accesses (rebinding is)
_SYNC_CTORS = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "Queue",
    "threading.Event", "threading.Lock", "threading.RLock",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.local", "Event",
    "collections.deque", "deque",
    "concurrent.futures.ThreadPoolExecutor", "ThreadPoolExecutor",
    "futures.ThreadPoolExecutor",
}


class _Access:
    """One read/write/compound touch of a class field or module global."""

    __slots__ = ("field", "kind", "roots", "locks", "node", "sf", "qual")

    def __init__(self, field: str, kind: str, roots: frozenset,
                 locks: tuple, node: ast.AST, sf: SourceFile, qual: str):
        self.field = field
        self.kind = kind          # "read" | "write" | "compound"
        self.roots = roots        # root ids; handler/pool roots end in "*"
        self.locks = locks
        self.node = node
        self.sf = sf
        self.qual = qual


class _Spawn:
    """One thread-spawn site (for the start-before-init rule and the
    thread-root index)."""

    __slots__ = ("kind", "target", "multi", "line", "sf", "cls", "qual")

    def __init__(self, kind: str, target: str, multi: bool, line: int,
                 sf: SourceFile, cls: str, qual: str):
        self.kind = kind          # thread|timer|executor|handler|signal|atexit
        self.target = target      # root id, or dotted external target
        self.multi = multi
        self.line = line
        self.sf = sf
        self.cls = cls
        self.qual = qual


class _ClassModel:
    """Everything the race rules need to know about one class."""

    def __init__(self, name: str, info: _ClassInfo, sf: SourceFile):
        self.name = name
        self.info = info
        self.sf = sf
        self.roots: dict[str, bool] = {}       # root id -> many-instance
        self.spawns: list[_Spawn] = []
        self.accesses: list[_Access] = []
        self.call_edges: dict[str, set] = {}   # method -> self-methods called
        self.fields: set[str] = set()          # attrs assigned via self.*
        self.sync_fields: set[str] = set()     # internally-synchronized values
        self.reads_by_method: dict[str, set] = {}  # method -> fields read
        self.init_thread_targets: list = []    # (start_line, root, node)
        self.init_assign_lines: dict[str, int] = {}  # field -> first line


def _base_of(node: ast.AST) -> Optional[str]:
    """Root name of a dotted chain (``a`` for ``a.b.c``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _Walker:
    """Walk one function/handler body: held locks, accesses, spawns.

    ``owners`` maps base names (``self``, ``cls``, closure aliases like
    ``source``) to the :class:`_ClassModel` whose fields they denote.
    """

    def __init__(self, model: _ClassModel, owners: dict, qual: str,
                 roots: frozenset, method: Optional[str],
                 module: str, mod_locks: set, mod_accesses: list,
                 loop_depth: int = 0):
        self.model = model
        self.owners = owners
        self.qual = qual
        self.roots = roots
        self.method = method          # edge-collection key, None for nested
        self.module = module
        self.mod_locks = mod_locks
        self.mod_accesses = mod_accesses
        self.loop_depth = loop_depth
        self.spawned_fns: list = []   # nested defs used as thread targets
        self._module_globals: set = set()

    # ------------------------------------------------------------ lock keys
    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        name = dotted(expr)
        if name is None:
            return None
        term = _terminal(name)
        lockish = ("lock" in term or "mutex" in term or term == "guard"
                   or term.endswith("_cv") or term == "cond")
        base = name.split(".", 1)[0]
        owner = self.owners.get(base)
        if owner is not None and "." in name:
            attr = name.split(".", 1)[1]
            if attr in owner.info.locks or lockish:
                return f"{owner.name}.{attr}"
            return None
        if "." not in name and name in self.mod_locks:
            return f"{self.module}.{name}"
        if lockish:
            return f"*.{term}" if "." in name else f"{self.module}.{name}"
        return None

    # ------------------------------------------------------------- accesses
    def _field_of(self, node: ast.AST):
        """(model, field) when ``node`` is ``<owner>.<field>``, else None."""
        if not isinstance(node, ast.Attribute):
            return None
        base = node.value
        if not isinstance(base, ast.Name):
            return None
        owner = self.owners.get(base.id)
        if owner is None:
            return None
        attr = node.attr
        if attr in owner.info.methods or attr in owner.info.locks:
            return None
        return owner, attr

    def _record(self, owner: _ClassModel, field: str, kind: str,
                node: ast.AST, held: tuple):
        owner.accesses.append(_Access(
            f"{owner.name}.{field}", kind, self.roots, held, node,
            self.sf_for(owner), self.qual))
        if self.method is not None and owner is self.model \
                and kind == "read":
            self.model.reads_by_method.setdefault(self.method,
                                                  set()).add(field)

    def sf_for(self, owner: _ClassModel) -> SourceFile:
        return owner.sf

    def _record_global(self, name: str, kind: str, node: ast.AST,
                       held: tuple, sf: SourceFile):
        self.mod_accesses.append(_Access(
            f"{self.module}.{name}", kind, self.roots, held, node, sf,
            self.qual))

    # --------------------------------------------------------------- spawns
    def _root_of_target(self, target_node: ast.AST, held: tuple):
        """Resolve a spawn target expression to (root_id or None, multi,
        display). Lambdas/nested defs are walked in place as root
        contexts."""
        multi = self.loop_depth > 0
        if isinstance(target_node, ast.Lambda):
            rid = f"{self.qual}.<lambda>"
            sub = _Walker(self.model, self.owners, rid,
                          frozenset([rid + ("*" if multi else "")]),
                          None, self.module, self.mod_locks,
                          self.mod_accesses)
            sub._module_globals = self._module_globals
            sub.expr(target_node.body, ())
            # self.m() calls inside the lambda make m a root too
            for call in ast.walk(target_node.body):
                if isinstance(call, ast.Call):
                    dn = dotted(call.func)
                    if dn:
                        base = dn.split(".", 1)[0]
                        owner = self.owners.get(base)
                        if owner is not None and dn.count(".") == 1:
                            m = dn.split(".", 1)[1]
                            if m in owner.info.methods:
                                owner.roots[m + ("*" if multi else "")] = \
                                    multi
            return rid, multi, rid
        name = dotted(target_node)
        if name is None:
            return None, multi, "<expr>"
        base = name.split(".", 1)[0]
        owner = self.owners.get(base)
        if owner is not None and name.count(".") == 1:
            m = name.split(".", 1)[1]
            if m in owner.info.methods:
                rid = m + ("*" if multi else "")
                owner.roots[rid] = multi
                return rid, multi, f"{owner.name}.{m}"
        if "." not in name:
            # nested def in the enclosing scope, or module-level function
            self.spawned_fns.append((name, multi))
            return name, multi, f"{self.module}.{name}"
        return None, multi, name       # foreign object (self.server.x)

    def _check_spawn(self, call: ast.Call, held: tuple):
        dn = dotted(call.func)
        target_node = None
        kind = None
        multi_force = False
        if dn in _THREAD_CTORS or dn in _TIMER_CTORS:
            kind = "timer" if dn in _TIMER_CTORS else "thread"
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    target_node = kw.value
            if target_node is None and dn in _TIMER_CTORS \
                    and len(call.args) >= 2:
                target_node = call.args[1]
        elif dn == "signal.signal" and len(call.args) >= 2:
            kind, target_node = "signal", call.args[1]
        elif dn == "atexit.register" and call.args:
            kind, target_node = "atexit", call.args[0]
        elif isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("submit", "map"):
            recv = _terminal(dotted(call.func.value) or "")
            fo = self._field_of(call.func.value)
            poolish = bool(_POOLISH.search(recv)) or (
                fo is not None and fo[1] in fo[0].sync_fields)
            if poolish and call.args:
                kind, target_node, multi_force = ("executor",
                                                  call.args[0], True)
        if kind is None or target_node is None:
            return
        rid, multi, display = self._root_of_target(target_node, held)
        multi = multi or multi_force
        if rid is not None and multi and not rid.endswith("*") \
                and kind == "executor":
            # pool-submitted self-methods run many at once
            for owner in set(self.owners.values()):
                if rid in owner.roots:
                    owner.roots.pop(rid)
                    owner.roots[rid + "*"] = True
        self.model.spawns.append(_Spawn(
            kind, display, multi or multi_force, call.lineno,
            self.model.sf, self.model.name, self.qual))
        if self.method == "__init__" and rid is not None:
            self.model.init_thread_targets.append((call.lineno, rid, call))

    # ------------------------------------------------------------- the walk
    def walk(self, stmts, held: tuple, cta: frozenset = frozenset()):
        for st in stmts:
            self.stmt(st, held, cta)

    def stmt(self, st, held: tuple, cta: frozenset = frozenset()):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return          # nested defs are walked when spawned
        if isinstance(st, ast.ClassDef):
            return          # nested handler classes handled by the model
        if isinstance(st, ast.With):
            new_held = held
            for item in st.items:
                key = self._lock_key(item.context_expr)
                if key is not None:
                    new_held = new_held + (key,)
                else:
                    self.expr(item.context_expr, held)
            # entering a lock resets check-then-act suspicion: the test
            # outside the lock no longer pairs with the write inside it
            self.walk(st.body, new_held, frozenset() if new_held != held
                      else cta)
            return
        if isinstance(st, ast.If):
            self.expr(st.test, held)
            tested = self._fields_in(st.test)
            self.walk(st.body, held, cta | tested if not held else cta)
            self.walk(st.orelse or [], held, cta)
            return
        if isinstance(st, (ast.For, ast.While)):
            if isinstance(st, ast.While):
                self.expr(st.test, held)
            else:
                self.expr(st.iter, held)
            self.loop_depth += 1
            self.walk(st.body, held, cta)
            self.loop_depth -= 1
            self.walk(st.orelse or [], held, cta)
            return
        if isinstance(st, ast.Try):
            self.walk(st.body, held, cta)
            for h in st.handlers:
                self.walk(h.body, held, cta)
            self.walk(st.orelse or [], held, cta)
            self.walk(st.finalbody or [], held, cta)
            return
        if isinstance(st, ast.AugAssign):
            self._write(st.target, st, held, compound=True)
            self.expr(st.value, held)
            return
        if isinstance(st, (ast.Assign, ast.AnnAssign)):
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            value_fields = self._fields_in(getattr(st, "value", None)) \
                if getattr(st, "value", None) is not None else frozenset()
            for t in targets:
                self._write(t, st, held,
                            compound_if=(value_fields | cta))
            if getattr(st, "value", None) is not None:
                self.expr(st.value, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._write(t, st, held)
            return
        if isinstance(st, ast.Return) and st.value is not None:
            self.expr(st.value, held)
            return
        if isinstance(st, ast.Expr):
            self.expr(st.value, held, cta)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self.expr(child, held)

    def _fields_in(self, node) -> frozenset:
        """Field keys (``Class.attr`` / ``module.global``) read in an
        expression — the check-then-act / RMW pairing set."""
        out = set()
        if node is None:
            return frozenset()
        for sub in ast.walk(node):
            fo = self._field_of(sub)
            if fo is not None:
                out.add(f"{fo[0].name}.{fo[1]}")
            elif isinstance(sub, ast.Name) \
                    and sub.id in self._module_globals:
                out.add(f"{self.module}.{sub.id}")
        return frozenset(out)

    def _write(self, target, st, held: tuple,
               compound: bool = False, compound_if: frozenset = frozenset()):
        node = target
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._write(e, st, held, compound, compound_if)
            return
        subscripted = False
        if isinstance(node, ast.Subscript):
            node = node.value
            subscripted = True
        fo = self._field_of(node)
        if fo is not None:
            owner, field = fo
            key = f"{owner.name}.{field}"
            kind = "compound" if (compound or key in compound_if) \
                else "write"
            if self.method is not None and owner is self.model:
                owner.fields.add(field)
            self._record(owner, field, kind, st, held)
            return
        if isinstance(node, ast.Name) and node.id in self._module_globals:
            kind = "compound" if (compound
                                  or f"{self.module}.{node.id}"
                                  in compound_if) else "write"
            self._record_global(node.id, kind, st, held, self.model.sf)

    def expr(self, node, held: tuple, cta: frozenset = frozenset()):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_spawn(sub, held)
                # edge collection for reachability
                dn = dotted(sub.func)
                if dn and self.method is not None:
                    base = dn.split(".", 1)[0]
                    owner = self.owners.get(base)
                    if owner is self.model and dn.count(".") == 1 \
                            and dn.split(".", 1)[1] in owner.info.methods:
                        self.model.call_edges.setdefault(
                            self.method, set()).add(dn.split(".", 1)[1])
                # mutator calls on fields
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _MUTATORS:
                    fo = self._field_of(sub.func.value)
                    if fo is not None:
                        owner, field = fo
                        if field not in owner.sync_fields:
                            kind = ("compound"
                                    if f"{owner.name}.{field}" in cta
                                    else "write")
                            self._record(owner, field, kind, sub, held)
                        continue
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.ctx, ast.Load):
                fo = self._field_of(sub)
                if fo is not None:
                    owner, field = fo
                    if field not in owner.sync_fields:
                        self._record(owner, field, "read", sub, held)


def _collect_sync_fields(cls_node: ast.ClassDef, ci: _ClassInfo) -> set:
    """Fields whose __init__ value is an internally-synchronized ctor."""
    out = set()
    for sub in ast.walk(cls_node):
        if not isinstance(sub, ast.Assign) \
                or not isinstance(sub.value, ast.Call):
            continue
        ctor = dotted(sub.value.func)
        if ctor not in _SYNC_CTORS and ctor not in _THREAD_CTORS:
            continue
        for t in sub.targets:
            tn = dotted(t)
            if tn and tn.startswith(("self.", "cls.")) \
                    and tn.count(".") == 1:
                out.add(tn.split(".", 1)[1])
    return out


def _handler_classes(cls_node: ast.ClassDef):
    """Nested (or top-level) BaseHTTPRequestHandler-ish subclasses and
    the enclosing-scope alias map (``source = self``) in effect."""
    for sub in ast.walk(cls_node):
        if isinstance(sub, ast.ClassDef) and sub is not cls_node:
            bases = {_terminal(dotted(b) or "") for b in sub.bases}
            if bases & _HANDLER_BASES:
                yield sub


def _self_aliases(fn_node) -> set:
    """Names bound to ``self`` in a method body (``source = self``)."""
    out = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self":
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class _FileModel:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.classes: dict[str, _ClassModel] = {}
        self.mod_accesses: list[_Access] = []
        self.mod_locks: set = set()
        self.mod_globals: set = set()


_MODEL_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _module_mutable_globals(sf: SourceFile) -> set:
    """Module-level names bound to mutable literals/ctors (the registry-
    singleton shape) — candidates for cross-root global access."""
    out = set()
    for node in sf.tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if isinstance(value, (ast.Dict, ast.List, ast.Set)):
            pass
        elif isinstance(value, ast.Call) and dotted(value.func) in (
                "dict", "list", "set", "defaultdict",
                "collections.defaultdict", "OrderedDict",
                "collections.OrderedDict"):
            pass
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and not t.id.isupper():
                out.add(t.id)
    return out


def _analyze_file(sf: SourceFile) -> _FileModel:
    fm = _FileModel(sf)
    module = sf.rel.rsplit("/", 1)[-1].removesuffix(".py")
    infos = _collect_classes(sf)
    fm.mod_locks = _module_locks(sf)
    fm.mod_globals = _module_mutable_globals(sf)

    class_nodes = {n.name: n for n in ast.walk(sf.tree)
                   if isinstance(n, ast.ClassDef)}
    handler_nodes = set()
    for node in class_nodes.values():
        for h in _handler_classes(node):
            handler_nodes.add(h.name)

    for name, info in infos.items():
        if name in handler_nodes:
            continue       # handler methods walk under their outer class
        node = class_nodes[name]
        cm = _ClassModel(name, info, sf)
        cm.sync_fields = _collect_sync_fields(node, info)
        # every self.<attr> assignment anywhere in the class declares a
        # field (reads of undeclared attrs are someone else's state)
        for sub in ast.walk(node):
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                targets = [sub.target]
            for t in targets:
                tn = dotted(t.value if isinstance(t, ast.Subscript) else t)
                if tn and tn.startswith("self.") and tn.count(".") == 1:
                    cm.fields.add(tn.split(".", 1)[1])
        fm.classes[name] = cm

    # ---- pass 1: walk every method of every (non-handler) class
    for name, cm in fm.classes.items():
        node = class_nodes[name]
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = f"{name}.{item.name}"
            owners = {"self": cm, "cls": cm}
            w = _Walker(cm, owners, qual, frozenset(), item.name,
                        module, fm.mod_locks, fm.mod_accesses)
            w._module_globals = fm.mod_globals
            held: tuple = ()
            req = cm.info.method_requires.get(item.name, set())
            if req:
                held = tuple(f"{name}.{r}" for r in req)
            w.walk(item.body, held)
            if item.name == "__init__":
                # record top-level assignment order for the
                # started-before-init rule
                for st in item.body:
                    if isinstance(st, ast.Assign):
                        for t in st.targets:
                            tn = dotted(t)
                            if tn and tn.startswith("self.") \
                                    and tn.count(".") == 1:
                                cm.init_assign_lines.setdefault(
                                    tn.split(".", 1)[1], st.lineno)
            # nested defs spawned as threads: walk them as root contexts
            nested = {d.name: d for d in ast.walk(item)
                      if isinstance(d, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and d is not item}
            for fn_name, multi in w.spawned_fns:
                d = nested.get(fn_name)
                if d is None:
                    continue
                rid = f"{qual}.{fn_name}" + ("*" if multi else "")
                cm.roots[rid] = multi
                aliases = {"self": cm, "cls": cm}
                sub = _Walker(cm, aliases, f"{qual}.{fn_name}",
                              frozenset([rid]), None, module,
                              fm.mod_locks, fm.mod_accesses)
                sub._module_globals = fm.mod_globals
                sub.walk(d.body, ())
            # nested handler classes: each method is a many-instance root
            aliases = _self_aliases(item)
            for h in _handler_classes(item):
                for hm in h.body:
                    if not isinstance(hm, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        continue
                    rid = f"{h.name}.{hm.name}*"
                    cm.roots[rid] = True
                    owners_h = {a: cm for a in aliases}
                    sub = _Walker(cm, owners_h, f"{qual}.{h.name}.{hm.name}",
                                  frozenset([rid]), None, module,
                                  fm.mod_locks, fm.mod_accesses)
                    sub._module_globals = fm.mod_globals
                    sub.walk(hm.body, ())
                cm.spawns.append(_Spawn(
                    "handler", f"{name}.{h.name}", True, h.lineno, sf,
                    name, qual))

    # ---- module-level functions as thread roots
    mod_fn = {n.name: n for n in sf.tree.body
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    # find spawns at module level / in module functions referencing them
    dummy = _ClassModel("<module>", _ClassInfo("<module>"), sf)
    for fn in mod_fn.values():
        w = _Walker(dummy, {}, fn.name, frozenset(), None, module,
                    fm.mod_locks, fm.mod_accesses)
        w._module_globals = fm.mod_globals
        w.walk(fn.body, ())
        for fn_name, multi in w.spawned_fns:
            target = mod_fn.get(fn_name)
            if target is None:
                continue
            rid = f"{module}.{fn_name}" + ("*" if multi else "")
            sub = _Walker(dummy, {}, fn_name, frozenset([rid]), None,
                          module, fm.mod_locks, fm.mod_accesses)
            sub._module_globals = fm.mod_globals
            sub.walk(target.body, ())
    fm.classes.pop("<module>", None)

    # ---- pass 2: root reachability — accesses recorded with method
    # names get their final root sets (BFS over self-call edges)
    for cm in fm.classes.values():
        reach: dict[str, set] = {}      # method -> roots reaching it
        for rid in cm.roots:
            entry = rid.rstrip("*")
            if entry not in cm.info.methods:
                continue
            seen = {entry}
            stack = [entry]
            while stack:
                m = stack.pop()
                reach.setdefault(m, set()).add(rid)
                for nxt in cm.call_edges.get(m, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
        for acc in cm.accesses:
            if acc.roots:
                continue               # nested/handler context: already set
            meth = acc.qual.split(".", 1)[1] if "." in acc.qual else acc.qual
            roots = set(reach.get(meth, ()))
            # a method that is not itself a thread entry point is also
            # callable by whoever holds the object — the caller root
            if meth + "*" not in cm.roots and meth not in cm.roots:
                roots.add(CALLER)
            acc.roots = frozenset(roots)
    return fm


def _file_model(sf: SourceFile) -> _FileModel:
    try:
        return _MODEL_CACHE[sf]
    except (KeyError, TypeError):
        fm = _analyze_file(sf)
        try:
            _MODEL_CACHE[sf] = fm
        except TypeError:
            pass
        return fm


# ------------------------------------------------------------ field verdicts

def _distinct_roots(accesses: list) -> int:
    """Count concurrency: a many-instance root alone is two threads."""
    n = 0
    seen = set()
    for a in accesses:
        for r in a.roots:
            if r in seen:
                continue
            seen.add(r)
            n += 2 if r.endswith("*") else 1
    return n


def _majority_lock(accesses: list) -> Optional[str]:
    counts: dict[str, int] = {}
    for a in accesses:
        for lk in a.locks:
            counts[lk] = counts.get(lk, 0) + 1
    if not counts:
        return None
    best = max(counts.items(), key=lambda kv: kv[1])
    # "majority": the lock guards at least half of all accesses
    return best[0] if best[1] * 2 >= len(accesses) else None


def _field_verdicts(cm: _ClassModel) -> Iterable[Finding]:
    by_field: dict[str, list] = {}
    for a in cm.accesses:
        if a.qual.endswith(".__init__") or ".__init__." in a.qual:
            continue       # pre-thread construction (ordering rule below)
        by_field.setdefault(a.field, []).append(a)
    for field, accesses in sorted(by_field.items()):
        attr = field.split(".", 1)[1]
        if attr in cm.info.guards:
            continue       # annotated: the guarded-by rule owns it
        writes = [a for a in accesses if a.kind in ("write", "compound")]
        if not writes:
            continue
        if _distinct_roots(accesses) < 2:
            continue
        write_roots = _distinct_roots(writes)
        lstar = _majority_lock(accesses)
        unlocked_writes = [a for a in writes if not a.locks]
        if lstar is None and write_roots >= 2 and unlocked_writes:
            a = min(unlocked_writes, key=lambda a: a.node.lineno)
            others = sorted({r for w in writes for r in w.roots}
                            - set(a.roots))
            f = a.sf.finding(
                "race-unguarded-write", a.node,
                f"`{field}` is written from {write_roots} concurrent "
                f"roots ({', '.join(sorted({r for w in writes for r in w.roots}))}) "
                f"with no lock held at any access — concurrent writes "
                f"interleave and one update is lost",
                hint=f"guard every access with one lock and declare it "
                     f"(`# guarded-by: <lock>` on the field), or confine "
                     f"the field to a single thread",
                context=a.qual)
            if f:
                yield f
            continue
        if lstar is not None:
            stray = [a for a in writes if lstar not in a.locks]
            if stray:
                a = min(stray, key=lambda a: a.node.lineno)
                guard = (lstar.split(".", 1)[1]
                         if lstar.startswith(cm.name + ".") else lstar)
                f = a.sf.finding(
                    "race-guarded-by-missing", a.node,
                    f"`{field}` is mostly accessed under {lstar} but "
                    f"this write in `{a.qual}` (and "
                    f"{len(stray) - 1} more site(s)) does not hold it — "
                    f"the lock discipline exists but is not enforced",
                    hint=f"annotate the field `# guarded-by: {guard}` "
                         f"and take the lock at the stray sites (the "
                         f"guarded-by rule then enforces it forever)",
                    context=a.qual)
                if f:
                    yield f
                continue
        for a in writes:
            if a.kind == "compound" and not a.locks:
                f = a.sf.finding(
                    "race-compound-rmw", a.node,
                    f"read-modify-write of shared `{field}` outside any "
                    f"lock in `{a.qual}` — the read and the store are "
                    f"separate bytecodes and another thread's write "
                    f"lands between them",
                    hint="wrap the check/read and the write in one "
                         "`with <lock>:` block (GIL atomicity does not "
                         "cover read-modify-write)",
                    context=a.qual)
                if f:
                    yield f


def _global_verdicts(fm: _FileModel) -> Iterable[Finding]:
    by_name: dict[str, list] = {}
    for a in fm.mod_accesses:
        by_name.setdefault(a.field, []).append(a)
    for name, accesses in sorted(by_name.items()):
        writes = [a for a in accesses if a.kind in ("write", "compound")]
        if not writes:
            continue
        roots = {r for a in accesses for r in a.roots}
        if not any(r != CALLER for r in roots):
            continue       # never touched from a spawned root
        if _distinct_roots(accesses) < 2:
            continue
        lstar = _majority_lock(accesses)
        unlocked = [a for a in writes if not a.locks]
        if unlocked and lstar is None and _distinct_roots(writes) >= 2:
            a = min(unlocked, key=lambda a: a.node.lineno)
            f = a.sf.finding(
                "race-unguarded-write", a.node,
                f"module global `{name}` is written from multiple "
                f"concurrent roots with no lock — a registry singleton "
                f"mutated by racing threads",
                hint="guard it with a module-level lock, or make the "
                     "mutation single-threaded",
                context=a.qual)
            if f:
                yield f
        elif lstar is not None:
            stray = [a for a in writes if lstar not in a.locks]
            if stray:
                a = min(stray, key=lambda a: a.node.lineno)
                f = a.sf.finding(
                    "race-guarded-by-missing", a.node,
                    f"module global `{name}` is mostly accessed under "
                    f"{lstar} but this write does not hold it",
                    hint=f"take {lstar} at this site too",
                    context=a.qual)
                if f:
                    yield f
        else:
            for a in writes:
                if a.kind == "compound" and not a.locks:
                    f = a.sf.finding(
                        "race-compound-rmw", a.node,
                        f"read-modify-write of shared module global "
                        f"`{name}` outside any lock in `{a.qual}`",
                        hint="wrap the read and the write in one "
                             "`with <lock>:` block",
                        context=a.qual)
                    if f:
                        yield f


# -------------------------------------------------------------------- rules

@rule("race-unguarded-write", "races",
      "shared field written from >=2 thread roots with no common lock",
      scope="project")
def check_unguarded_write(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        fm = _file_model(sf)
        for cm in fm.classes.values():
            for f in _field_verdicts(cm):
                if f.rule == "race-unguarded-write":
                    yield f
        for f in _global_verdicts(fm):
            if f.rule == "race-unguarded-write":
                yield f


@rule("race-compound-rmw", "races",
      "read-modify-write on a shared field outside any lock",
      scope="project")
def check_compound_rmw(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        fm = _file_model(sf)
        for cm in fm.classes.values():
            for f in _field_verdicts(cm):
                if f.rule == "race-compound-rmw":
                    yield f
        for f in _global_verdicts(fm):
            if f.rule == "race-compound-rmw":
                yield f


@rule("race-guarded-by-missing", "races",
      "shared field with a majority lock not held at some write "
      "(suggests the inferred guarded-by annotation)",
      scope="project")
def check_guarded_by_missing(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        fm = _file_model(sf)
        for cm in fm.classes.values():
            for f in _field_verdicts(cm):
                if f.rule == "race-guarded-by-missing":
                    yield f
        for f in _global_verdicts(fm):
            if f.rule == "race-guarded-by-missing":
                yield f


@rule("race-thread-started-before-init", "races",
      "thread spawned in __init__ before fields its target reads are "
      "assigned", scope="project")
def check_started_before_init(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        fm = _file_model(sf)
        for cm in fm.classes.values():
            if not cm.init_thread_targets:
                continue
            # fields each root (transitively) reads
            for start_line, rid, node in cm.init_thread_targets:
                entry = rid.rstrip("*")
                reads: set = set()
                seen = {entry}
                stack = [entry]
                while stack:
                    m = stack.pop()
                    reads |= cm.reads_by_method.get(m, set())
                    for nxt in cm.call_edges.get(m, ()):
                        if nxt not in seen:
                            seen.add(nxt)
                            stack.append(nxt)
                late = sorted(
                    f for f in reads
                    if cm.init_assign_lines.get(f, 0) > start_line
                    and f not in cm.info.guards)
                if not late:
                    continue
                f = sf.finding(
                    "race-thread-started-before-init", node,
                    f"`{cm.name}.__init__` spawns `{rid.rstrip('*')}` "
                    f"here, but the thread reads "
                    f"{', '.join('self.' + x for x in late)} which are "
                    f"only assigned later in __init__ — the thread can "
                    f"observe the half-constructed object",
                    hint="assign every field the target reads before "
                         "the spawn (start threads last)",
                    context=f"{cm.name}.__init__")
                if f:
                    yield f


# --------------------------------------------------------- thread-root index

def thread_root_index(project: Project) -> list[dict]:
    """Every concurrent entry point the analyzer discovered, sorted —
    the docs' threading-model inventory and the incremental project
    digest both consume this."""
    out = []
    for sf in project.files:
        fm = _file_model(sf)
        for cm in fm.classes.values():
            for sp in cm.spawns:
                out.append({"file": sf.rel, "class": sp.cls,
                            "kind": sp.kind, "target": sp.target,
                            "multi": sp.multi, "line": sp.line})
    out.sort(key=lambda d: (d["file"], d["line"], d["target"]))
    return out


def thread_root_digest(project: Project) -> str:
    h = hashlib.sha256()
    for entry in thread_root_index(project):
        h.update(f"{entry['file']}|{entry['class']}|{entry['kind']}|"
                 f"{entry['target']}|{entry['multi']};".encode())
    return h.hexdigest()
