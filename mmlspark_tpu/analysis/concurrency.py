"""Concurrency rules: lock ordering, blocking under lock, guarded fields.

The framework's threaded subsystems (serving loops, the fleet driver,
prefetch producers, the supervisor, telemetry) share state behind
``threading.Lock`` attributes. Three rule groups keep that discipline
checkable instead of folkloric:

* ``lock-blocking-call`` — a blocking operation (``time.sleep``, HTTP
  round-trips, ``queue.get``, thread/process joins, socket IO, logging —
  handlers do file/stream IO) executed while holding a lock: every other
  thread needing that lock stalls for the duration, and a blocking call
  that itself needs the lock deadlocks.
* ``lock-order-cycle`` / ``lock-reacquire`` — a lock-order graph built
  from lexically nested ``with <lock>:`` scopes plus one-hop
  ``self.method()`` calls. A cycle (A held while taking B somewhere, B
  held while taking A elsewhere) is a potential deadlock; re-acquiring
  the SAME non-reentrant lock is a guaranteed one.
* ``guarded-by`` — fields declared with a trailing
  ``# guarded-by: <lock>`` comment must only be mutated inside a
  ``with self.<lock>:`` block (or in the declaring method / ``__init__``,
  or in a method annotated ``# requires-lock: <lock>`` — a helper whose
  contract is "caller holds the lock"). A guard of the form
  ``!<method>`` declares thread confinement instead: the field must
  never be touched from the named (worker-thread) method.

Lock identity is ``Class.attr`` for ``self``/``cls`` attributes,
``<module>.name`` for module globals, and ``*.attr`` for locks reached
through other objects — close enough for a single-package analysis, and
the annotations close the gap where inference can't.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .core import Finding, Project, SourceFile, dotted, qualname_of, rule

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(!?[\w.]+)")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([\w.]+)")

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}
_RLOCK_CTORS = {"threading.RLock", "RLock"}

#: container methods that mutate the receiver in place
_MUTATORS = {"append", "extend", "insert", "pop", "popitem", "remove",
             "clear", "update", "setdefault", "add", "discard",
             "appendleft", "popleft", "sort", "reverse"}

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_LOGGERISH = {"log", "logger", "logging"}

_BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "HTTP round-trip",
    "urlopen": "HTTP round-trip",
    "requests.get": "HTTP round-trip", "requests.post": "HTTP round-trip",
    "requests.put": "HTTP round-trip", "requests.delete": "HTTP round-trip",
    "requests.head": "HTTP round-trip",
    "requests.request": "HTTP round-trip",
    "subprocess.run": "subprocess", "subprocess.Popen": "subprocess spawn",
    "subprocess.call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.check_call": "subprocess",
    "socket.create_connection": "socket connect",
    "select.select": "select",
}

_THREADISH = re.compile(r"(^|_)(thread|proc|process|worker|reader|writer)s?$")
_EVENTISH = re.compile(r"(^|_)(event|stop|done|ready|started)(_event)?$")
_QUEUEISH = re.compile(r"(^|_)(q|queue|pending|inbox|outbox)$")
_SOCKISH = re.compile(r"(^|_)(sock|socket|conn|connection)s?$")


def _terminal(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.locks: dict[str, bool] = {}        # attr -> is_reentrant
        self.guards: dict[str, str] = {}        # field -> guard spec
        self.guard_decl_method: dict[str, str] = {}   # field -> method name
        self.methods: dict[str, ast.AST] = {}
        self.method_requires: dict[str, set] = {}     # method -> lock attrs
        self.method_acquires: dict[str, set] = {}     # method -> lock keys


def _lock_key(expr: ast.AST, cls: Optional[_ClassInfo],
              module: str) -> Optional[str]:
    """Identity of the lock object in a ``with <expr>:`` item, or None if
    the expression isn't lock-shaped."""
    name = dotted(expr)
    if name is None:
        return None
    term = _terminal(name)
    lockish = ("lock" in term or "mutex" in term or term == "guard"
               or term.endswith("_cv") or term == "cond")
    root = name.split(".", 1)[0]
    if root in ("self", "cls") and "." in name:
        attr = name.split(".", 1)[1]
        if cls is not None and attr in cls.locks:
            return f"{cls.name}.{attr}"
        if lockish:
            return f"{cls.name if cls else '?'}.{attr}"
        return None
    if "." not in name:
        if lockish:
            return f"{module}.{name}"
        return None
    # foreign object (w.lock, meshlib.collective_fit_lock, ...)
    if lockish:
        return f"*.{_terminal(name)}"
    return None


def _collect_classes(sf: SourceFile) -> dict[str, _ClassInfo]:
    module = sf.rel.rsplit("/", 1)[-1].removesuffix(".py")
    out: dict[str, _ClassInfo] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = _ClassInfo(node.name)
        out[node.name] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = item
                # requires-lock annotation on (or right above) the def line
                for ln in (item.lineno, item.lineno - 1):
                    c = sf.comments.get(ln, "")
                    m = _REQUIRES_RE.search(c)
                    if m:
                        ci.method_requires.setdefault(item.name,
                                                      set()).add(m.group(1))
        # lock + guarded-by declarations anywhere in the class body
        for sub in ast.walk(node):
            targets: list = []
            value = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                targets, value = [sub.target], sub.value
            else:
                continue
            vname = dotted(value) if isinstance(value, ast.Call) \
                else None
            ctor = dotted(value.func) if isinstance(value, ast.Call) \
                else None
            for t in targets:
                tn = dotted(t)
                if tn is None:
                    continue
                if tn.startswith(("self.", "cls.")):
                    attr = tn.split(".", 1)[1]
                elif "." not in tn:
                    attr = tn            # class-level attribute
                else:
                    continue
                if ctor in _LOCK_CTORS:
                    ci.locks[attr] = ctor in _RLOCK_CTORS
                m = _GUARDED_RE.search(sf.comments.get(sub.lineno, ""))
                if m:
                    ci.guards[attr] = m.group(1)
                    meth = _enclosing_method(node, sub)
                    ci.guard_decl_method[attr] = meth or "__init__"
            del vname
    return out


def _enclosing_method(cls_node: ast.ClassDef, stmt) -> Optional[str]:
    for item in cls_node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(item):
                if sub is stmt:
                    return item.name
    return None


def _module_locks(sf: SourceFile) -> set:
    out = set()
    for node in sf.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if isinstance(value, ast.Call) and dotted(value.func) in _LOCK_CTORS:
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class _FuncWalk:
    """Walk one function body tracking lexically held locks; collects
    blocking-call findings, lock-order edges, and guarded-field events."""

    def __init__(self, sf: SourceFile, cls: Optional[_ClassInfo],
                 qual: str, module: str):
        self.sf = sf
        self.cls = cls
        self.qual = qual
        self.module = module
        self.findings: list[Optional[Finding]] = []
        self.edges: list[tuple[str, str, int]] = []   # (held, taken, line)
        self.acquired: set[str] = set()
        #: (field, node, held_locks, is_mutation) events for guarded-by
        self.field_events: list[tuple[str, ast.AST, tuple]] = []
        self.self_calls_under: list[tuple[str, tuple, ast.AST]] = []

    # -------------------------------------------------------------- blocking
    def _blocking_reason(self, call: ast.Call) -> Optional[str]:
        dn = dotted(call.func)
        if dn in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[dn]
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = _terminal(dotted(call.func.value))
        if attr in _LOG_METHODS and recv in _LOGGERISH:
            return "logging (handler IO)"
        if attr == "join" and _THREADISH.search(recv or ""):
            return f"{recv}.join"
        if attr == "wait" and (_THREADISH.search(recv or "")
                               or _EVENTISH.search(recv or "")):
            return f"{recv}.wait"
        if attr in ("get", "put") and _QUEUEISH.search(recv or ""):
            return f"blocking queue.{attr}"
        if attr in ("recv", "send", "sendall", "connect", "accept",
                    "makefile") and _SOCKISH.search(recv or ""):
            return f"socket {attr}"
        if attr in ("urlopen",):
            return "HTTP round-trip"
        return None

    # ------------------------------------------------------------------ walk
    def walk(self, stmts, held: tuple):
        for st in stmts:
            self.stmt(st, held)

    def stmt(self, st, held: tuple):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return   # nested scopes walked separately
        if isinstance(st, ast.With):
            new_held = held
            for item in st.items:
                key = _lock_key(item.context_expr, self.cls, self.module)
                if key is not None:
                    for h in new_held:
                        self.edges.append((h, key, st.lineno))
                    if key in new_held:
                        reentrant = False
                        if self.cls and key.startswith(self.cls.name + "."):
                            attr = key.split(".", 1)[1]
                            reentrant = self.cls.locks.get(attr, False)
                        if not reentrant:
                            self.findings.append(self.sf.finding(
                                "lock-reacquire", st,
                                f"`with {dotted(item.context_expr)}:` "
                                f"re-acquires a lock already held in "
                                f"`{self.qual}` — non-reentrant "
                                f"threading.Lock deadlocks here",
                                hint="restructure so the lock is taken "
                                     "once, or use an RLock deliberately",
                                context=self.qual))
                    self.acquired.add(key)
                    new_held = new_held + (key,)
                else:
                    self.expr(item.context_expr, held)
            self.walk(st.body, new_held)
            return
        if isinstance(st, (ast.If, ast.While)):
            self.expr(st.test, held)
            self.walk(st.body, held)
            self.walk(st.orelse or [], held)
            return
        if isinstance(st, ast.For):
            self.expr(st.iter, held)
            self.walk(st.body, held)
            self.walk(st.orelse or [], held)
            return
        if isinstance(st, ast.Try):
            self.walk(st.body, held)
            for h in st.handlers:
                self.walk(h.body, held)
            self.walk(st.orelse or [], held)
            self.walk(st.finalbody or [], held)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for t in targets:
                self._field_mutation(t, st, held)
            if getattr(st, "value", None) is not None:
                self.expr(st.value, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._field_mutation(t, st, held)
            return
        if isinstance(st, ast.Return) and st.value is not None:
            self.expr(st.value, held)
            return
        if isinstance(st, ast.Expr):
            self.expr(st.value, held)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self.expr(child, held)

    def _field_mutation(self, target, st, held: tuple):
        """Assign/del to self.<field> or self.<field>[...]"""
        node = target
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._field_mutation(e, st, held)
            return
        if isinstance(node, ast.Subscript):
            node = node.value
        name = dotted(node)
        if name and name.startswith("self.") and name.count(".") == 1:
            self.field_events.append((name.split(".", 1)[1], st, held))

    def expr(self, node, held: tuple):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if held:
                reason = self._blocking_reason(sub)
                if reason is not None:
                    self.findings.append(self.sf.finding(
                        "lock-blocking-call", sub,
                        f"{reason} while holding {', '.join(held)} in "
                        f"`{self.qual}` — every thread contending that "
                        f"lock stalls for the call's duration",
                        hint="move the blocking call outside the lock "
                             "(collect state under the lock, act after)",
                        context=self.qual))
            # mutating-method calls on guarded fields
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS:
                recv = dotted(sub.func.value)
                base = recv
                if base and base.startswith("self.") \
                        and base.count(".") == 1:
                    self.field_events.append(
                        (base.split(".", 1)[1], sub, held))
            # one-hop self-method call (for lock-order + reacquire)
            dn = dotted(sub.func)
            if held and dn and dn.startswith("self.") \
                    and dn.count(".") == 1:
                self.self_calls_under.append(
                    (dn.split(".", 1)[1], held, sub))


def _analyze_file(sf: SourceFile):
    module = sf.rel.rsplit("/", 1)[-1].removesuffix(".py")
    classes = _collect_classes(sf)
    mod_locks = _module_locks(sf)
    del mod_locks  # identity comes from _lock_key's name heuristics
    walks: list[tuple[Optional[_ClassInfo], str, ast.AST, _FuncWalk]] = []

    def visit(node, cls: Optional[_ClassInfo], stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, classes.get(child.name), stack + [child])
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = qualname_of(stack + [child])
                w = _FuncWalk(sf, cls, qual, module)
                held: tuple = ()
                req = (cls.method_requires.get(child.name, set())
                       if cls else set())
                if req and cls:
                    held = tuple(f"{cls.name}.{r}" for r in req)
                w.walk(child.body, held)
                walks.append((cls, child.name, child, w))
                visit(child, cls, stack + [child])
            else:
                visit(child, cls, stack)

    visit(sf.tree, None, [])
    return classes, walks


@rule("lock-blocking-call", "concurrency",
      "blocking IO / sleeps / joins executed while holding a lock")
def check_blocking(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        _cls, walks = _analyze_file(sf)
        for _c, _m, _node, w in walks:
            for f in w.findings:
                if f is not None and f.rule == "lock-blocking-call":
                    yield f


@rule("lock-reacquire", "concurrency",
      "same non-reentrant lock acquired twice on one path")
def check_reacquire(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        classes, walks = _analyze_file(sf)
        for cls, _m, _node, w in walks:
            for f in w.findings:
                if f is not None and f.rule == "lock-reacquire":
                    yield f
            # one-hop: self.method() under a held lock, where the method
            # itself acquires that same (non-reentrant) lock
            for meth, held, call in w.self_calls_under:
                if cls is None:
                    continue
                target = None
                for c2, m2, node2, w2 in walks:
                    if c2 is cls and m2 == meth:
                        target = w2
                        break
                if target is None:
                    continue
                for key in target.acquired:
                    if key in held and key.startswith(cls.name + "."):
                        attr = key.split(".", 1)[1]
                        if not cls.locks.get(attr, False):
                            f = sf.finding(
                                "lock-reacquire", call,
                                f"`self.{meth}()` called while holding "
                                f"{key} in `{w.qual}`, and `{meth}` "
                                f"acquires {key} itself — non-reentrant "
                                f"deadlock",
                                hint=f"add a `# requires-lock: {attr}` "
                                     f"variant of {meth} that assumes the "
                                     f"lock, or release before calling",
                                context=w.qual)
                            if f:
                                yield f


@rule("lock-order-cycle", "concurrency",
      "lock-order graph cycles (potential ABBA deadlock)")
def check_lock_order(project: Project) -> Iterable[Finding]:
    # global edge set across the whole project: cycles usually span files
    edges: dict[tuple[str, str], tuple[SourceFile, int, str]] = {}
    for sf in project.files:
        classes, walks = _analyze_file(sf)
        for cls, _m, _node, w in walks:
            for held, taken, line in w.edges:
                if held != taken and (held, taken) not in edges:
                    edges[(held, taken)] = (sf, line, w.qual)
            # one-hop method edges
            for meth, held, call in w.self_calls_under:
                if cls is None:
                    continue
                for c2, m2, _n2, w2 in walks:
                    if c2 is cls and m2 == meth:
                        for key in w2.acquired:
                            for h in held:
                                if h != key and (h, key) not in edges:
                                    edges[(h, key)] = (sf, call.lineno,
                                                       w.qual)
    graph: dict[str, set] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    # report every 2-node cycle and longer cycles via DFS back-edge search
    reported = set()
    for (a, b), (sf, line, qual) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].rel, kv[1][1])):
        path = _find_path(graph, b, a)
        if path is None:
            continue
        cyc = tuple(sorted(set([a, b] + path)))
        if cyc in reported:
            continue
        reported.add(cyc)
        f = sf.finding(
            "lock-order-cycle", _FakeNode(line),
            f"lock-order cycle: {a} is held while taking {b} (here), and "
            f"elsewhere the order {' -> '.join([b] + path)} closes the "
            f"loop — two threads interleaving these paths deadlock",
            hint="impose one global acquisition order (document it) or "
                 "collapse to a single lock",
            context=qual)
        if f:
            yield f


class _FakeNode:
    def __init__(self, lineno: int):
        self.lineno = lineno


def _find_path(graph: dict, src: str, dst: str):
    """Path src -> dst (list of nodes after src), or None."""
    seen = {src}
    stack = [(src, [])]
    while stack:
        node, path = stack.pop()
        for nxt in graph.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


@rule("guarded-by", "concurrency",
      "mutations of `# guarded-by:` fields outside their lock")
def check_guarded(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        classes, walks = _analyze_file(sf)
        for cls, meth, node, w in walks:
            if cls is None or not cls.guards:
                continue
            for field, st, held in w.field_events:
                guard = cls.guards.get(field)
                if guard is None:
                    continue
                if guard.startswith("!"):
                    # thread confinement: never touched from this method
                    if meth == guard[1:]:
                        f = sf.finding(
                            "guarded-by", st,
                            f"`self.{field}` is declared thread-confined "
                            f"(guarded-by: {guard}) but is touched inside "
                            f"`{w.qual}` — the excluded thread's entry "
                            f"point",
                            hint="hand the value through the queue/event "
                                 "instead of mutating the field from the "
                                 "worker thread",
                            context=w.qual)
                        if f:
                            yield f
                    continue
                if meth in ("__init__", cls.guard_decl_method.get(field)):
                    continue
                lock_key = f"{cls.name}.{guard}"
                if lock_key not in held:
                    f = sf.finding(
                        "guarded-by", st,
                        f"`self.{field}` (guarded-by: {guard}) mutated in "
                        f"`{w.qual}` without holding self.{guard}",
                        hint=f"wrap the mutation in `with self.{guard}:` "
                             f"or annotate the method "
                             f"`# requires-lock: {guard}`",
                        context=w.qual)
                    if f:
                        yield f
