"""SARIF 2.1.0 export — the interchange format CI annotation UIs speak.

One run per invocation: the tool descriptor lists every rule that
produced a finding (id + short description from the registry), results
carry ``ruleId`` / message / one physical location each, and baselined
findings are downgraded to ``note`` level with a ``suppressions`` entry
(SARIF's spelling of "known, grandfathered") so a viewer shows them
struck through instead of red.
"""

from __future__ import annotations

from typing import Iterable

from .core import Finding, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings: Iterable[Finding]) -> dict:
    findings = list(findings)
    docs = {r.name: r.doc for r in all_rules()}
    used = sorted({f.rule for f in findings})
    rules = [{"id": name,
              "shortDescription": {"text": docs.get(name, name)}}
             for name in used]
    index = {name: i for i, name in enumerate(used)}
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "note" if f.baselined else "error",
            "message": {"text": f.message + (f"\nhint: {f.hint}"
                                             if f.hint else "")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, int(f.line))},
                },
                "logicalLocations": [{"fullyQualifiedName": f.context}],
            }],
            "partialFingerprints": {"graftlint/v1": f.fingerprint()},
        }
        if f.baselined:
            res["suppressions"] = [{"kind": "external",
                                    "justification": "baselined in "
                                    "tools/graftlint_baseline.json"}]
        results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "docs/static-analysis.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }
