"""jit-safety rules: what must not happen inside a traced function.

A function is *traced* when it is reachable as a ``jax.jit`` / ``pjit`` /
``shard_map`` / ``lax.scan`` body or a ``telemetry.profiler`` target —
discovered from decorators (including ``functools.partial(jax.jit, ...)``)
and from wrapping call sites in the same module. Inside a traced body the
arguments (minus ``static_argnums``/``static_argnames``) are abstract
tracers, and a *taint* walk follows them through assignments so the rules
fire on derived values too. Shape-level attributes (``.shape``, ``.ndim``,
``.dtype``, ``len()``, ``is None`` checks) are static under tracing and
break the taint — branching on them is legal and common.

Rules:

* ``jit-host-sync`` — ``float()`` / ``int()`` / ``bool()`` / ``.item()`` /
  ``.tolist()`` / ``np.asarray()`` / ``np.array()`` on a traced value:
  a hidden device→host sync (and under jit, a tracer error or a constant
  baked at trace time).
* ``jit-traced-branch`` — Python ``if`` / ``while`` / ``assert`` on a
  traced value: either a tracer error or (via weak typing) a silent
  host sync per call. Use ``jnp.where`` / ``lax.cond``.
* ``jit-nondeterministic-iter`` — iterating a ``set`` / ``frozenset``
  inside a traced body: iteration order varies across processes/runs, so
  the traced program differs → spurious recompiles and cross-host
  divergence (the dict/set-order recompile hazard; cross-check with the
  profiler's recompile-cause counters, docs/observability.md).
* ``jit-in-loop`` — constructing ``jax.jit(...)`` (call or decorated def)
  inside a ``for``/``while`` body: a fresh jit cache per iteration, i.e.
  a compile per iteration.
* ``jit-missing-donate`` — a jitted update function taking both the
  ``params`` and ``opt``/``opt_state`` buffers the trainer documents as
  donated (models/trainer.py) without ``donate_argnums`` — doubles peak
  HBM for the largest buffers in the program.
* ``unseeded-random`` — module-level ``random.*`` / unseeded
  ``np.random.*`` in library (non-test) code: unreproducible behavior and
  shared global RNG state across threads. Use a seeded
  ``random.Random(seed)`` / ``np.random.default_rng(seed)`` instance.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import Finding, Project, SourceFile, dotted, qualname_of, rule

#: attributes of a tracer that are Python-static at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "nbytes", "itemsize"}
#: builtins whose call on a traced value is a host sync / tracer error
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
#: methods whose call on a traced value is a host sync
_SYNC_METHODS = {"item", "tolist", "__float__", "__int__", "__bool__"}
#: numpy entry points that materialize a traced value on host
_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
            "onp.asarray", "onp.array", "np.copy", "numpy.copy"}
#: calls producing untainted (static) results regardless of args
_UNTAINT_CALLS = {"len", "isinstance", "type", "getattr", "hasattr",
                  "range", "enumerate", "zip"}

_JIT_WRAPPERS = {"jax.jit", "jit", "jax.pjit", "pjit"}
_BODY_WRAPPERS = _JIT_WRAPPERS | {
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "lax.scan", "jax.checkpoint", "jax.remat",
    "profiler.wrap", "telemetry.profiler.wrap", "ProfiledFunction",
    "jax.vmap", "vmap", "jax.grad", "jax.value_and_grad",
    # the pipeline-capture entry point (core/capture.py): a function
    # handed to StageCapture(fn, ...) is traced inside the fused
    # segment's single jitted program
    "StageCapture", "capture.StageCapture", "core.capture.StageCapture"}

_PARAMS_NAMES = {"params"}
_OPT_NAMES = {"opt", "opt_state", "optstate", "optimizer_state"}

_RANDOM_FUNCS = {"random", "randint", "uniform", "choice", "choices",
                 "shuffle", "sample", "randrange", "gauss", "betavariate",
                 "expovariate", "normalvariate", "triangular", "randbytes",
                 "getrandbits"}


def _is_test_path(rel: str) -> bool:
    parts = rel.split("/")
    return (any(p in ("tests", "testing", "fixtures") for p in parts)
            or parts[-1].startswith("test_"))


# ----------------------------------------------------------- traced discovery

class _TracedDef:
    __slots__ = ("node", "statics", "reason", "qual")

    def __init__(self, node, statics: set, reason: str, qual: str):
        self.node = node
        self.statics = statics
        self.reason = reason
        self.qual = qual


def _static_names(call: ast.Call, fn_node) -> set:
    """Param names excluded from tracing by static_argnums/argnames."""
    out: set[str] = set()
    args = getattr(fn_node, "args", None)
    pos = ([a.arg for a in args.posonlyargs + args.args]
           if args is not None else [])
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(v.value)
        elif kw.arg == "static_argnums":
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    if 0 <= v.value < len(pos):
                        out.add(pos[v.value])
    return out


def _wrapper_name(call_fn: ast.AST) -> Optional[str]:
    name = dotted(call_fn)
    if name is None:
        return None
    # functools.partial(jax.jit, ...) resolves to the partial'd target
    return name


def _match_wrapper(name: Optional[str]) -> Optional[str]:
    if name is None:
        return None
    if name in _BODY_WRAPPERS:
        return name
    # tolerate aliases like `jnp.jit` never; keep exact-ish matching on
    # the terminal segments (jax.lax.scan vs lax.scan already listed)
    return None


def _collect_traced(sf: SourceFile) -> list[_TracedDef]:
    """Every def/lambda in this module that is traced, with its statics."""
    defs_by_name: dict[str, list] = {}
    parents: dict[ast.AST, ast.AST] = {}
    quals: dict[ast.AST, str] = {}

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(child.name, []).append(child)
                quals[child] = qualname_of(stack + [child])
                walk(child, stack + [child])
            elif isinstance(child, ast.ClassDef):
                quals[child] = qualname_of(stack + [child])
                walk(child, stack + [child])
            else:
                walk(child, stack)

    walk(sf.tree, [])
    traced: dict[ast.AST, _TracedDef] = {}

    def mark(fn_node, statics: set, reason: str):
        if fn_node in traced:
            traced[fn_node].statics |= statics
            return
        traced[fn_node] = _TracedDef(
            fn_node, statics, reason,
            quals.get(fn_node, getattr(fn_node, "name", "<lambda>")))

    # 1) decorators
    for name, nodes in defs_by_name.items():
        for fn in nodes:
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call):
                    dn = dotted(dec.func)
                    if dn in ("functools.partial", "partial"):
                        if dec.args and _match_wrapper(dotted(dec.args[0])):
                            mark(fn, _static_names(dec, fn),
                                 dotted(dec.args[0]))
                    elif _match_wrapper(dn):
                        mark(fn, _static_names(dec, fn), dn)
                else:
                    dn = dotted(dec)
                    if _match_wrapper(dn):
                        mark(fn, set(), dn)
    # 2) wrapping call sites: jax.jit(f), lax.scan(body, ...), shard_map(f)
    for call in ast.walk(sf.tree):
        if not isinstance(call, ast.Call):
            continue
        wname = _match_wrapper(dotted(call.func))
        if wname is None:
            continue
        target = call.args[0] if call.args else None
        if target is None:
            continue
        if isinstance(target, ast.Lambda):
            mark(target, set(), wname)
            continue
        # `jit(step_body or default, ...)`-style expressions: every name
        # inside the wrapped-function expression counts as a body
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                for fn in defs_by_name.get(sub.id, ()):
                    mark(fn, _static_names(call, fn), wname)
            elif isinstance(sub, ast.Lambda):
                mark(sub, set(), wname)
    return list(traced.values())


# --------------------------------------------------------------- taint walker

class _Taint:
    """Lexical taint over one traced body: names carrying traced values."""

    def __init__(self, tainted: set):
        self.names = set(tainted)

    def expr(self, node) -> bool:
        """Does ``node`` evaluate to a traced value?"""
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a structural (trace-time)
            # check, legal under jit
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return (self.expr(node.left)
                    or any(self.expr(c) for c in node.comparators))
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (self.expr(node.body) or self.expr(node.orelse)
                    or self.expr(node.test))
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname in _UNTAINT_CALLS:
                return False
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STATIC_ATTRS):
                return False
            # any call fed a traced value is assumed to return one
            # (jnp ops, closures); host-sync calls are flagged separately
            return (any(self.expr(a) for a in node.args)
                    or any(self.expr(k.value) for k in node.keywords)
                    or self.expr(node.func))
        return False

    def assign(self, target, value_tainted: bool):
        for t in ast.walk(target) if not isinstance(target, ast.Name) \
                else (target,):
            if isinstance(t, ast.Name):
                if value_tainted:
                    self.names.add(t.id)
                else:
                    self.names.discard(t.id)


def _traced_params(fn_node, statics: set) -> set:
    if isinstance(fn_node, ast.Lambda):
        args = fn_node.args
    else:
        args = fn_node.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return {n for n in names if n not in statics}


def _check_traced_body(sf: SourceFile, td: _TracedDef) -> Iterable[Finding]:
    taint = _Taint(_traced_params(td.node, td.statics))
    body = (td.node.body if isinstance(td.node.body, list)
            else [ast.Expr(td.node.body)])

    def visit(stmts):
        for st in stmts:
            yield from visit_stmt(st)

    def flag_sync_calls(expr_node):
        """Find host-sync calls anywhere inside an expression."""
        for node in ast.walk(expr_node):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            arg0_tainted = bool(node.args) and taint.expr(node.args[0])
            if (fname in _SYNC_BUILTINS and arg0_tainted):
                f = sf.finding(
                    "jit-host-sync", node,
                    f"`{fname}()` on a traced value inside traced "
                    f"function `{td.qual}` — device→host sync / tracer "
                    f"error at trace time",
                    hint="keep the value on device (jnp ops) or move the "
                         "conversion outside the jitted function",
                    context=td.qual)
                if f:
                    yield f
            elif fname in _NP_SYNC and any(taint.expr(a)
                                           for a in node.args):
                f = sf.finding(
                    "jit-host-sync", node,
                    f"`{fname}()` materializes a traced value on host "
                    f"inside traced function `{td.qual}`",
                    hint="use jnp.asarray / keep the computation in jax",
                    context=td.qual)
                if f:
                    yield f
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _SYNC_METHODS
                  and taint.expr(node.func.value)):
                f = sf.finding(
                    "jit-host-sync", node,
                    f"`.{node.func.attr}()` on a traced value inside "
                    f"traced function `{td.qual}` — blocking host sync",
                    hint="return the array and convert outside the jit "
                         "boundary",
                    context=td.qual)
                if f:
                    yield f

    def flag_set_iter(for_node):
        it = for_node.iter
        is_set = (isinstance(it, ast.Set)
                  or (isinstance(it, ast.Call)
                      and dotted(it.func) in ("set", "frozenset")))
        if is_set:
            f = sf.finding(
                "jit-nondeterministic-iter", for_node,
                f"iteration over a set inside traced function "
                f"`{td.qual}`: set order varies per process, so the "
                f"traced program (and its compile cache key) varies too",
                hint="iterate a sorted() list or a tuple — deterministic "
                     "order keeps the compiled program stable",
                context=td.qual)
            if f:
                yield f

    def visit_stmt(st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return   # nested defs get their own discovery pass
        if isinstance(st, ast.Assign):
            yield from flag_sync_calls(st.value)
            t = taint.expr(st.value)
            for target in st.targets:
                taint.assign(target, t)
            return
        if isinstance(st, ast.AugAssign):
            yield from flag_sync_calls(st.value)
            if taint.expr(st.value):
                taint.assign(st.target, True)
            return
        if isinstance(st, ast.AnnAssign) and st.value is not None:
            yield from flag_sync_calls(st.value)
            taint.assign(st.target, taint.expr(st.value))
            return
        if isinstance(st, (ast.If, ast.While)):
            yield from flag_sync_calls(st.test)
            if taint.expr(st.test):
                kind = "if" if isinstance(st, ast.If) else "while"
                f = sf.finding(
                    "jit-traced-branch", st,
                    f"Python `{kind}` on a traced value in traced "
                    f"function `{td.qual}` — tracer error, or a silent "
                    f"host sync on every call",
                    hint="use jnp.where / lax.cond / lax.select, or mark "
                         "the argument static",
                    context=td.qual)
                if f:
                    yield f
            yield from visit(st.body)
            yield from visit(getattr(st, "orelse", []) or [])
            return
        if isinstance(st, ast.Assert):
            if taint.expr(st.test):
                f = sf.finding(
                    "jit-traced-branch", st,
                    f"`assert` on a traced value in traced function "
                    f"`{td.qual}` — forces a host sync (or tracer error)",
                    hint="use checkify / debug.check, or assert on "
                         "static shape attributes only",
                    context=td.qual)
                if f:
                    yield f
            return
        if isinstance(st, ast.For):
            yield from flag_set_iter(st)
            yield from flag_sync_calls(st.iter)
            if taint.expr(st.iter):
                f = sf.finding(
                    "jit-traced-branch", st,
                    f"Python `for` over a traced value in traced "
                    f"function `{td.qual}` — unrolls at trace time only "
                    f"if the length is static; otherwise a tracer error",
                    hint="use lax.scan / lax.fori_loop",
                    context=td.qual)
                if f:
                    yield f
            taint.assign(st.target, taint.expr(st.iter))
            yield from visit(st.body)
            yield from visit(st.orelse or [])
            return
        if isinstance(st, ast.With):
            for item in st.items:
                yield from flag_sync_calls(item.context_expr)
            yield from visit(st.body)
            return
        if isinstance(st, ast.Try):
            yield from visit(st.body)
            for h in st.handlers:
                yield from visit(h.body)
            yield from visit(st.orelse or [])
            yield from visit(st.finalbody or [])
            return
        if isinstance(st, ast.Return) and st.value is not None:
            yield from flag_sync_calls(st.value)
            return
        if isinstance(st, ast.Expr):
            yield from flag_sync_calls(st.value)
            return
        # other statements: scan expressions for sync calls
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                yield from flag_sync_calls(child)

    yield from visit(body)


# ------------------------------------------------------------------ the rules

def _traced_body_findings(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        for td in _collect_traced(sf):
            yield from _check_traced_body(sf, td)


def _traced_rule(name: str, doc: str):
    @rule(name, "jit-safety", doc)
    def _run(project: Project, _name=name) -> Iterable[Finding]:
        return [f for f in _traced_body_findings(project)
                if f.rule == _name]
    return _run


_traced_rule("jit-host-sync",
             "host syncs (float()/.item()/np.asarray) on traced values")
_traced_rule("jit-traced-branch",
             "Python control flow on traced values")
_traced_rule("jit-nondeterministic-iter",
             "set-order iteration inside traced bodies")


#: dtype tokens for the silent-upcast rule
_BF16_CTORS = {"jnp.bfloat16", "jax.numpy.bfloat16"}
_F32_CTORS = {"jnp.float32", "jnp.float64", "jax.numpy.float32",
              "jax.numpy.float64", "np.float32", "np.float64",
              "numpy.float32", "numpy.float64"}


def _dtype_token(node) -> Optional[str]:
    """'bf16' / 'f32' when ``node`` names a dtype (attribute or string
    literal), else None."""
    dn = dotted(node)
    if dn in _BF16_CTORS or dn == "bfloat16":
        return "bf16"
    if dn in _F32_CTORS or dn in ("float32", "float64"):
        return "f32"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value == "bfloat16":
            return "bf16"
        if node.value in ("float32", "float64"):
            return "f32"
    return None


def _is_bf16_cast(node) -> bool:
    """``x.astype(jnp.bfloat16)`` / ``jnp.bfloat16(x)`` — the explicit
    downcasts that start bf16 provenance."""
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args:
        return _dtype_token(node.args[0]) == "bf16"
    return dotted(node.func) in _BF16_CTORS and bool(node.args)


def _has_precision_comment(sf: SourceFile, line: int) -> bool:
    """The rule's escape hatch: a comment mentioning 'precision' on the
    node's line (or the line above — long expressions wrap) declares the
    upcast deliberate, e.g. ``# precision: f32 accumulation``."""
    for ln in (line, line - 1):
        if "precision" in sf.comments.get(ln, "").lower():
            return True
    return False


class _Bf16Taint:
    """Lexical bf16 provenance over one traced body: names whose value
    came from an explicit bfloat16 cast (directly or through jnp ops,
    which preserve dtype)."""

    def __init__(self):
        self.names: set = set()

    def expr(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, ast.Call):
            if _is_bf16_cast(node):
                return True
            fname = dotted(node.func)
            if fname in _UNTAINT_CALLS:
                return False
            # an f32 cast ENDS the provenance (it is also where the rule
            # fires); any other call fed a bf16 value is assumed to keep
            # its dtype (jnp ops do)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" \
                    and node.args and _dtype_token(node.args[0]) == "f32":
                return False
            if fname in _F32_CTORS:
                return False
            return (any(self.expr(a) for a in node.args)
                    or any(self.expr(k.value) for k in node.keywords))
        return False

    def assign(self, target, value_tainted: bool):
        for t in (ast.walk(target) if not isinstance(target, ast.Name)
                  else (target,)):
            if isinstance(t, ast.Name):
                if value_tainted:
                    self.names.add(t.id)
                else:
                    self.names.discard(t.id)


def _silent_upcast_findings(sf: SourceFile, td) -> Iterable[Finding]:
    taint = _Bf16Taint()
    body = (td.node.body if isinstance(td.node.body, list)
            else [ast.Expr(td.node.body)])

    def flag(node, what: str):
        if _has_precision_comment(sf, getattr(node, "lineno", 1)):
            return None
        return sf.finding(
            "jit-silent-upcast", node,
            f"{what} promotes a bf16-typed value back to f32/f64 inside "
            f"traced function `{td.qual}` — the compute silently leaves "
            f"the bf16 fast path (2x the HBM traffic, off the full-rate "
            f"MXU mode)",
            hint="keep the chain in bf16, or declare the upcast with an "
                 "explicit-precision comment (e.g. `# precision: f32 "
                 "accumulation`) on the line",
            context=td.qual)

    def scan_expr(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                # x.astype(jnp.float32) on a bf16-provenance value
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "astype" and sub.args \
                        and _dtype_token(sub.args[0]) == "f32" \
                        and taint.expr(sub.func.value):
                    f = flag(sub, "`.astype(float32/float64)`")
                    if f:
                        yield f
                # jnp.float32(x) on a bf16-provenance value
                elif dotted(sub.func) in _F32_CTORS and sub.args \
                        and taint.expr(sub.args[0]):
                    f = flag(sub, f"`{dotted(sub.func)}(...)`")
                    if f:
                        yield f
            elif isinstance(sub, ast.BinOp):
                # typed-literal promotion: bf16 op jnp.float32(2.0) —
                # the f32-TYPED operand wins the promotion (a bare
                # Python float literal is weakly typed and stays bf16,
                # so it is NOT flagged)
                for a, b in ((sub.left, sub.right), (sub.right, sub.left)):
                    if taint.expr(a) and isinstance(b, ast.Call) \
                            and dotted(b.func) in _F32_CTORS:
                        f = flag(sub, "a binary op with an f32-typed "
                                      "literal operand")
                        if f:
                            yield f
                        break

    def visit(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if getattr(st, "value", None) is not None:
                    yield from scan_expr(st.value)
                    t = taint.expr(st.value)
                    targets = (st.targets if isinstance(st, ast.Assign)
                               else [st.target])
                    for target in targets:
                        taint.assign(target, t)
                continue
            if isinstance(st, (ast.If, ast.While)):
                yield from scan_expr(st.test)
                yield from visit(st.body)
                yield from visit(getattr(st, "orelse", []) or [])
                continue
            if isinstance(st, ast.For):
                yield from scan_expr(st.iter)
                taint.assign(st.target, taint.expr(st.iter))
                yield from visit(st.body)
                yield from visit(st.orelse or [])
                continue
            if isinstance(st, ast.With):
                yield from visit(st.body)
                continue
            if isinstance(st, ast.Try):
                yield from visit(st.body)
                for h in st.handlers:
                    yield from visit(h.body)
                yield from visit(st.orelse or [])
                yield from visit(st.finalbody or [])
                continue
            if isinstance(st, (ast.Return, ast.Expr)) \
                    and getattr(st, "value", None) is not None:
                yield from scan_expr(st.value)
                continue
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    yield from scan_expr(child)

    yield from visit(body)


@rule("jit-silent-upcast", "jit-safety",
      "f32/f64 promotion of a bf16-typed value inside traced bodies")
def check_silent_upcast(project: Project) -> Iterable[Finding]:
    """bf16 is the MXU's full-rate mode and half the HBM bytes; a value
    explicitly cast down to bfloat16 that later gets ``.astype(f32)``'d
    (or multiplied by an f32-TYPED literal — weakly-typed Python floats
    stay bf16 and are fine) silently walks the whole downstream chain
    back to full precision. Provenance is explicit-cast-rooted: only
    values traceable to a ``.astype(jnp.bfloat16)`` / ``jnp.bfloat16()``
    in the same traced body are tracked, so model-level deliberate
    upcasts (flax modules casting logits to f32 for the loss) never
    fire. Declare a deliberate upcast with a comment containing
    'precision' on (or above) the line."""
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        for td in _collect_traced(sf):
            yield from _silent_upcast_findings(sf, td)


@rule("jit-in-loop", "jit-safety",
      "jax.jit constructed inside a for/while body (compile per iteration)")
def check_jit_in_loop(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue

        def walk(node, loop_depth, stack):
            for child in ast.iter_child_nodes(node):
                in_loop = loop_depth + int(isinstance(
                    child, (ast.For, ast.While)))
                if isinstance(child, ast.Call) and loop_depth > 0:
                    dn = dotted(child.func)
                    if dn in _JIT_WRAPPERS:
                        f = sf.finding(
                            "jit-in-loop", child,
                            f"`{dn}(...)` constructed inside a loop in "
                            f"`{qualname_of(stack)}`: a fresh jit wrapper "
                            f"(and XLA compile) per iteration",
                            hint="hoist the jit() out of the loop so the "
                                 "compiled executable is reused",
                            context=qualname_of(stack))
                        if f:
                            yield f
                new_stack = stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    new_stack = stack + [child]
                yield from walk(child, in_loop, new_stack)

        yield from walk(sf.tree, 0, [])


@rule("jit-missing-donate", "jit-safety",
      "jitted (params, opt_state) update without donate_argnums")
def check_missing_donate(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        for td in _collect_traced(sf):
            if td.reason not in _JIT_WRAPPERS:
                continue
            node = td.node
            if isinstance(node, ast.Lambda):
                continue
            pnames = {a.arg for a in node.args.posonlyargs
                      + node.args.args}
            if not (pnames & _PARAMS_NAMES and pnames & _OPT_NAMES):
                continue
            # donation may ride the decorator or the wrapping call site
            donated = False
            for call in ast.walk(sf.tree):
                if not isinstance(call, ast.Call):
                    continue
                dn = dotted(call.func)
                if dn in ("functools.partial", "partial") and call.args \
                        and dotted(call.args[0]) in _JIT_WRAPPERS:
                    involves = call in node.decorator_list
                elif dn in _JIT_WRAPPERS:
                    involves = (call in node.decorator_list
                                or (bool(call.args) and any(
                                    isinstance(s, ast.Name)
                                    and s.id == node.name
                                    for s in ast.walk(call.args[0]))))
                else:
                    continue
                if involves and any(kw.arg in ("donate_argnums",
                                               "donate_argnames")
                                    for kw in call.keywords):
                    donated = True
                    break
            if donated:
                continue
            f = sf.finding(
                "jit-missing-donate", node,
                f"jitted update `{td.qual}` takes the documented-donated "
                f"buffers ({', '.join(sorted(pnames & (_PARAMS_NAMES | _OPT_NAMES)))}) "
                f"but declares no donate_argnums — peak HBM holds both "
                f"the old and new copies",
                hint="jit(..., donate_argnums=(...)) for the params/"
                     "opt_state positions (see models/trainer.py)",
                context=td.qual)
            if f:
                yield f


@rule("unseeded-random", "jit-safety",
      "module-level random / unseeded np.random in library code")
def check_unseeded_random(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        # only fire when the stdlib module (not a same-named local) is
        # what `random` refers to
        imports_random = any(
            isinstance(n, ast.Import)
            and any(a.name == "random" and a.asname is None
                    for a in n.names)
            for n in ast.walk(sf.tree))
        stack: list = []

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                cur = stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    cur = stack + [child]
                ctx = qualname_of(stack)
                if isinstance(child, ast.Call):
                    dn = dotted(child.func)
                    if (imports_random and dn is not None
                            and dn.startswith("random.")
                            and dn.split(".", 1)[1] in _RANDOM_FUNCS):
                        f = sf.finding(
                            "unseeded-random", child,
                            f"module-level `{dn}()` in library code: "
                            f"unreproducible and shares global RNG state "
                            f"across threads",
                            hint="use a seeded random.Random(seed) "
                                 "instance (see resilience/faults.py)",
                            context=ctx)
                        if f:
                            yield f
                    if (dn in ("np.random.default_rng",
                               "numpy.random.default_rng")
                            and not child.args and not child.keywords):
                        f = sf.finding(
                            "unseeded-random", child,
                            "unseeded np.random.default_rng() in library "
                            "code: runs are unreproducible",
                            hint="thread a seed parameter through "
                                 "(default_rng(seed))",
                            context=ctx)
                        if f:
                            yield f
                    if (dn is not None
                            and (dn.startswith("np.random.")
                                 or dn.startswith("numpy.random."))
                            and dn.rsplit(".", 1)[1] in _RANDOM_FUNCS):
                        f = sf.finding(
                            "unseeded-random", child,
                            f"legacy global-state `{dn}()` in library "
                            f"code",
                            hint="use np.random.default_rng(seed)",
                            context=ctx)
                        if f:
                            yield f
                elif (imports_random and isinstance(child, ast.Name)
                      and child.id == "random"
                      and isinstance(child.ctx, ast.Load)
                      and not isinstance(node, (ast.Attribute, ast.Import,
                                                ast.ImportFrom))):
                    # the module object used as a value (e.g. stored as an
                    # RNG): same global-state hazard as calling through it
                    f = sf.finding(
                        "unseeded-random", child,
                        "the global `random` module captured as an RNG "
                        "value: unseeded, shared across threads",
                        hint="construct random.Random(seed) instead "
                             "(Random(None) still isolates state)",
                        context=ctx)
                    if f:
                        yield f
                yield from walk(child, cur)

        yield from walk(sf.tree, stack)
