"""Donation/aliasing dataflow rules: host buffers must never be donated.

The repo shipped the same bug class twice before these rules existed.
PR 7's arrow-fitstream corruption: the fitStream step donated its batch
buffers, and on the CPU backend ``jax.device_put`` of a numpy array can
alias the host buffer ZERO-COPY — donating it hands memory the host
allocator still owns back to XLA as scratch, and training corrupts
nondeterministically. PR 9's post-resume NaN: restored checkpoints are
host-numpy trees, and the donating mixed-precision dispatch handed those
aliased buffers straight to XLA. Both cost a full debugging cycle; both
are the SAME dataflow fact — *a host-owned buffer reached a donated
argument position* — which a taint walk can see statically.

* ``donation-host-alias`` — a value whose provenance is a host buffer
  (``np.*`` constructors and ops, ``.to_numpy()``/arrow zero-copy
  decoders, ``msgpack``/``pickle`` decodes, checkpoint-restore helpers,
  ``jax.device_get``) reaches a donated argument position of a call to
  a function known to be jitted with ``donate_argnums``.
  ``jax.device_put`` does NOT launder the taint (that is exactly the
  zero-copy alias); calling through a jitted function DOES — "material-
  ized through a jitted copy" is the sanctioned sanitizer (the jit's
  outputs are XLA-owned buffers), and so do ``jnp.*`` constructors.
* ``donation-use-after-donate`` — a buffer passed at a donated position
  is read again after the dispatch (including on the next iteration of
  an enclosing loop) without being rebound: the buffer now belongs to
  XLA and may already hold the step's outputs.

The dynamic complement is :mod:`mmlspark_tpu.analysis.sanitize`
(``MMLSPARK_TPU_SANITIZE=donation``): donated host-aliased inputs are
poisoned after dispatch so anything the static walk misses fails loudly
instead of corrupting silently.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .core import Finding, Project, SourceFile, dotted, qualname_of, rule

#: calls producing static (never-buffer) results regardless of args
_UNTAINT_CALLS = {"len", "isinstance", "type", "getattr", "hasattr",
                  "range", "enumerate", "zip", "int", "float", "bool",
                  "str", "sorted", "min", "max", "sum"}
#: attribute reads that are metadata, not the buffer
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "nbytes", "itemsize"}

#: dotted-name prefixes whose call results live in HOST memory
_HOST_PREFIXES = ("np.", "numpy.", "onp.", "msgpack.", "pickle.",
                  "pd.", "pandas.", "pa.", "pyarrow.")
#: attribute calls that decode/expose a host buffer (arrow & friends)
_HOST_METHODS = {"to_numpy", "to_pandas", "numpy", "tobytes", "unpackb"}
#: function-name shapes that return restored (host) checkpoint state
_RESTORE_RE = re.compile(r"restore|read_shards|unpackb|from_msgpack"
                         r"|frombuffer|load_state")
#: jit spellings whose wrapping both donates and sanitizes
_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}

_PARTIALS = ("functools.partial", "partial")


def _is_test_path(rel: str) -> bool:
    parts = rel.split("/")
    return (any(p in ("tests", "testing", "fixtures") for p in parts)
            or parts[-1].startswith("test_"))


def _const_argnums(call: ast.Call) -> Optional[frozenset]:
    """The literal ``donate_argnums`` positions of a jit call, or None
    when absent/non-literal (a computed tuple can't be checked here —
    the runtime sanitizer covers it)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        out = set()
        for sub in ast.walk(kw.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, int):
                out.add(sub.value)
        return frozenset(out) if out else None
    return None


def _collect_donators(sf: SourceFile) -> dict[str, frozenset]:
    """``{callable_name: donated_positions}`` for every name in this
    module bound to a jitted-with-donation callable: module/local
    ``name = jax.jit(f, donate_argnums=...)`` assignments and
    ``@partial(jax.jit, donate_argnums=...)`` decorated defs."""
    out: dict[str, frozenset] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if dotted(call.func) in _JIT_NAMES:
                nums = _const_argnums(call)
                if nums:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = nums
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                dn = dotted(dec.func)
                if dn in _PARTIALS and dec.args \
                        and dotted(dec.args[0]) in _JIT_NAMES:
                    nums = _const_argnums(dec)
                    if nums:
                        out[node.name] = nums
                elif dn in _JIT_NAMES:
                    nums = _const_argnums(dec)
                    if nums:
                        out[node.name] = nums
    return out


def _direct_donating_call(call: ast.Call) -> Optional[frozenset]:
    """``jax.jit(f, donate_argnums=(0,))(x)`` — the wrapper applied
    inline."""
    if isinstance(call.func, ast.Call) \
            and dotted(call.func.func) in _JIT_NAMES:
        return _const_argnums(call.func)
    return None


def _collect_host_returners(sf: SourceFile) -> set:
    """Module-local functions whose return value is host-tainted (a
    one-level interprocedural summary: calls to these names are host
    origins at their call sites — how ``_restore_checkpoint``-style
    helpers propagate)."""
    out: set[str] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        taint = _HostTaint(set(), {}, out)
        returns_host = False
        for st in ast.walk(node):
            if isinstance(st, ast.Assign):
                t = taint.expr(st.value)
                for target in st.targets:
                    taint.assign(target, t)
            elif isinstance(st, ast.Return) and st.value is not None:
                if taint.expr(st.value):
                    returns_host = True
        if returns_host or _RESTORE_RE.search(node.name):
            out.add(node.name)
    return out


class _HostTaint:
    """Lexical host-buffer provenance over one function body."""

    def __init__(self, tainted: set, jitted_names: dict,
                 host_returners: set):
        self.names = set(tainted)
        #: every name bound to a jax.jit(...) result (donating or not):
        #: calls through them MATERIALIZE — output buffers are XLA-owned
        self.jitted = set(jitted_names)
        self.host_returners = set(host_returners)

    def _call_taint(self, node: ast.Call) -> bool:
        fname = dotted(node.func)
        term = fname.rsplit(".", 1)[-1] if fname else ""
        # sanitizers first: jitted-call outputs are device-owned
        if fname in _JIT_NAMES or term in self.jitted \
                or _direct_donating_call(node) is not None \
                or (isinstance(node.func, ast.Call)
                    and dotted(node.func.func) in _JIT_NAMES):
            return False
        if fname and (fname.startswith("jnp.")
                      or fname.startswith("jax.numpy.")):
            return False
        if fname in _UNTAINT_CALLS:
            return False
        # host origins
        if fname and fname.startswith(_HOST_PREFIXES):
            return True
        if fname in ("memoryview", "bytearray"):
            return True
        if fname in ("jax.device_get", "device_get"):
            return True
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HOST_METHODS:
            return True
        if term and term in self.host_returners:
            return True
        if term and _RESTORE_RE.search(term):
            return True
        # device_put PRESERVES host provenance: on the CPU backend the
        # placed array may alias the numpy buffer zero-copy
        if fname in ("jax.device_put", "device_put"):
            return any(self.expr(a) for a in node.args[:1])
        # any other call fed a host buffer conservatively returns one
        # (slicing/padding helpers, np-aliased wrappers)
        return (any(self.expr(a) for a in node.args)
                or any(self.expr(k.value) for k in node.keywords))

    def expr(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(v is not None and self.expr(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            return self._call_taint(node)
        return False

    def assign(self, target, value_tainted: bool):
        for t in (ast.walk(target) if not isinstance(target, ast.Name)
                  else (target,)):
            if isinstance(t, ast.Name):
                if value_tainted:
                    self.names.add(t.id)
                else:
                    self.names.discard(t.id)


class _FnWalk:
    """One function's linear walk: host-alias sinks + use-after-donate."""

    def __init__(self, sf: SourceFile, qual: str,
                 donators: dict[str, frozenset], jitted: dict,
                 host_returners: set):
        self.sf = sf
        self.qual = qual
        self.donators = donators
        self.taint = _HostTaint(set(), jitted, host_returners)
        #: name -> the donating call node that consumed it
        self.donated: dict[str, ast.Call] = {}
        self.findings: list[Finding] = []
        #: loop bodies are walked twice (cross-iteration reuse); one
        #: report per (rule, site) regardless of pass
        self._reported: set = set()

    def _donated_positions(self, call: ast.Call) -> Optional[frozenset]:
        nums = _direct_donating_call(call)
        if nums:
            return nums
        fname = dotted(call.func)
        if fname is None:
            return None
        return self.donators.get(fname.rsplit(".", 1)[-1])

    def _flag_alias(self, call, pos, arg):
        key = ("alias", getattr(call, "lineno", 0),
               getattr(call, "col_offset", 0), pos)
        if key in self._reported:
            return
        self._reported.add(key)
        f = self.sf.finding(
            "donation-host-alias", call,
            f"argument {pos} of this dispatch is DONATED but its value "
            f"traces back to a host-owned buffer (np array / zero-copy "
            f"decode / checkpoint restore) in `{self.qual}` — on the CPU "
            f"backend device_put may alias that buffer zero-copy, and "
            f"donating it hands memory the host allocator still owns to "
            f"XLA as scratch (the PR 7 arrow-fitstream / PR 9 post-resume "
            f"corruption class)",
            hint="materialize through a jitted copy first (e.g. "
                 "jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))) "
                 "or disable donation on the CPU backend",
            context=self.qual)
        if f:
            self.findings.append(f)

    def _scan_calls(self, expr_node, assigned_names: set):
        """Flag donation sinks + poisoned re-reads inside an expression."""
        for node in ast.walk(expr_node):
            if not isinstance(node, ast.Call):
                continue
            nums = self._donated_positions(node)
            if nums is None:
                continue
            for pos in sorted(nums):
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if self.taint.expr(arg):
                    self._flag_alias(node, pos, arg)
                if isinstance(arg, ast.Name):
                    # donated from here on, unless the statement's own
                    # targets rebind it (params, opt = step(params, opt))
                    if arg.id not in assigned_names:
                        self.donated[arg.id] = node

    def _check_reads(self, expr_node, skip: set):
        """A Load of a name donated earlier = use-after-donate."""
        for node in ast.walk(expr_node):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in self.donated and node.id not in skip:
                key = ("reuse", getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), node.id)
                if key in self._reported:
                    self.donated.pop(node.id, None)
                    continue
                self._reported.add(key)
                f = self.sf.finding(
                    "donation-use-after-donate", node,
                    f"`{node.id}` was passed at a donated position of a "
                    f"jitted dispatch earlier in `{self.qual}` and is "
                    f"read again here — the buffer now belongs to XLA "
                    f"and may already hold the dispatch's outputs",
                    hint="donated buffers are consumed: rebind the name "
                         "from the call's outputs, or drop the donation "
                         "for buffers you must re-read",
                    context=self.qual)
                if f:
                    self.findings.append(f)
                # one report per name per donation event
                self.donated.pop(node.id, None)

    def _assigned_names(self, st) -> set:
        out: set = set()
        targets = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        elif isinstance(st, ast.For):
            targets = [st.target]
        for t in targets:
            for sub in (ast.walk(t) if not isinstance(t, ast.Name)
                        else (t,)):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        return out

    def stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return      # nested scopes get their own walk
        assigned = self._assigned_names(st)
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(st, "value", None)
            if value is not None:
                self._check_reads(value, set())
                self._scan_calls(value, assigned)
                t = self.taint.expr(value)
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                for target in targets:
                    self.taint.assign(target, t)
            for name in assigned:
                self.donated.pop(name, None)   # rebound: fresh buffer
            return
        if isinstance(st, (ast.If, ast.While)):
            self._check_reads(st.test, set())
            self._scan_calls(st.test, set())
            snap_d, snap_t = dict(self.donated), set(self.taint.names)
            self.walk(st.body)
            d_body, t_body = self.donated, self.taint.names
            self.donated, self.taint.names = dict(snap_d), set(snap_t)
            self.walk(st.orelse or [])
            self.donated.update(d_body)           # union: conservative
            self.taint.names |= t_body
            return
        if isinstance(st, (ast.For,)):
            self._check_reads(st.iter, set())
            self._scan_calls(st.iter, assigned)
            self.taint.assign(st.target, self.taint.expr(st.iter))
            # two passes over the body: the second catches a buffer
            # donated on iteration N and re-read on iteration N+1
            for _ in range(2):
                for name in assigned:
                    self.donated.pop(name, None)  # loop target rebinds
                self.walk(st.body)
            self.walk(st.orelse or [])
            return
        if isinstance(st, ast.With):
            for item in st.items:
                self._check_reads(item.context_expr, set())
                self._scan_calls(item.context_expr, set())
            self.walk(st.body)
            return
        if isinstance(st, ast.Try):
            self.walk(st.body)
            for h in st.handlers:
                self.walk(h.body)
            self.walk(st.orelse or [])
            self.walk(st.finalbody or [])
            return
        if isinstance(st, (ast.Return, ast.Expr)) \
                and getattr(st, "value", None) is not None:
            self._check_reads(st.value, set())
            self._scan_calls(st.value, set())
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._check_reads(child, set())
                self._scan_calls(child, set())

    def walk(self, stmts):
        for st in stmts:
            self.stmt(st)


def _collect_jitted_names(sf: SourceFile) -> set:
    """Every name bound to ANY jax.jit(...) result — donating or not —
    plus defs decorated with a jit spelling: calling through one
    materializes host inputs into XLA-owned outputs (the sanitizer)."""
    out: set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            dn = dotted(node.value.func)
            if dn in _JIT_NAMES or (
                    dn in _PARTIALS and node.value.args
                    and dotted(node.value.args[0]) in _JIT_NAMES):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dn = dotted(dec.func) if isinstance(dec, ast.Call) \
                    else dotted(dec)
                if dn in _JIT_NAMES:
                    out.add(node.name)
                elif isinstance(dec, ast.Call) and dn in _PARTIALS \
                        and dec.args and dotted(dec.args[0]) in _JIT_NAMES:
                    out.add(node.name)
    return out


def _module_findings(sf: SourceFile) -> Iterable[Finding]:
    donators = _collect_donators(sf)
    jitted = _collect_jitted_names(sf)
    host_returners = _collect_host_returners(sf)

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = _FnWalk(sf, qualname_of(stack + [child]), donators,
                            jitted, host_returners)
                w.walk(child.body)
                yield from w.findings
                yield from visit(child, stack + [child])
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, stack + [child])
            else:
                yield from visit(child, stack)

    yield from visit(sf.tree, [])


def _donation_findings(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        yield from _module_findings(sf)


@rule("donation-host-alias", "donation",
      "host-owned buffers (np/arrow/restore provenance) reaching donated "
      "argument positions of jitted dispatches")
def check_host_alias(project: Project) -> Iterable[Finding]:
    return [f for f in _donation_findings(project)
            if f.rule == "donation-host-alias"]


@rule("donation-use-after-donate", "donation",
      "buffers re-read after being passed at a donated position")
def check_use_after_donate(project: Project) -> Iterable[Finding]:
    return [f for f in _donation_findings(project)
            if f.rule == "donation-use-after-donate"]
