"""Distributed-protocol rules: collectives, watcher threads, commit order.

As more of the program moves inside single traced/collective programs
(whole-program capture, communication-aware kernels — PAPERS.md arxiv
1810.09868 / 2007.01811), the bugs that remain are exactly the ones
pytest-on-one-host cannot see: a collective naming an axis the mesh
doesn't have (trace-time error only on the real mesh), a collective
dispatched under a condition that differs per rank (a deadlock that
needs two processes to reproduce), a blocking host call on the thread
that must stay responsive to unwind a wedged attempt, and a durability
protocol whose ordering invariant (fsync before rename, manifest last)
is only violated observable-y when the power goes out.

* ``protocol-collective-axis`` — a collective (``psum`` / ``pmean`` /
  ``all_gather`` / ``ppermute`` / ``all_to_all`` / ``psum_scatter``)
  whose LITERAL axis name is absent from the enclosing ``shard_map``
  call's declared axes (``in_specs``/``out_specs`` ``P(...)`` entries,
  ``axis_names=``). Variable axis names are skipped — parameterized
  helpers validate at runtime (``parallel/sequence.py`` raises on an
  unknown axis before tracing).
* ``protocol-divergent-collective`` — a collective (device collectives
  plus the host-level barrier/allgather helpers) lexically under an
  ``if``/``while`` whose condition depends on per-rank identity
  (``process_index()``, ``rank``/``host_id``/``process_id`` names) or
  per-host entropy (``random``, wall time): ranks that disagree about
  the branch leave the others blocked in the collective forever.
* ``protocol-attempt-thread-blocking`` — a blocking host call
  (``sleep`` / thread ``join`` / HTTP / ``queue.get``) in the body of a
  thread target whose thread is named like an attempt/watcher thread
  (``threading.Thread(..., name="...attempt...")``): those threads must
  stay responsive so a wedged collective can be abandoned within its
  detection bound (``resilience/elastic.py``).
* ``protocol-rename-before-fsync`` — an ``os.replace``/``os.rename``
  publishing a tmp file with no ``os.fsync`` earlier in the same
  function: after a crash the rename can land with the data still in
  the page cache — a complete-looking file with torn contents, the
  exact window the checkpoint commit protocol exists to close
  (``resilience/ckpt.py``).
* ``protocol-manifest-order`` — a manifest commit (``_commit_manifest``
  or a ``*manifest*`` helper) ordered BEFORE a payload/shard write in
  the same function: the manifest must be the LAST write so its
  presence implies every listed file landed.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .core import Finding, Project, SourceFile, dotted, qualname_of, rule

_SHARD_MAP_NAMES = {"shard_map", "jax.shard_map",
                    "jax.experimental.shard_map.shard_map"}
_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "ppermute", "pshuffle", "all_to_all", "psum_scatter"}
#: host-level collective helpers (block until every rank arrives)
_HOST_COLLECTIVES = {"process_barrier", "wait_at_barrier"}

_RANKISH = re.compile(r"^(rank|ranks|host_id|process_id|proc_id"
                      r"|process_index|pid_env)$")
_DIVERGENT_CALLS = {"jax.process_index", "process_index", "time.time",
                    "time.monotonic", "uuid.uuid4", "os.getpid"}

_BLOCKING = {
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "HTTP round-trip",
    "urlopen": "HTTP round-trip",
    "requests.get": "HTTP round-trip", "requests.post": "HTTP round-trip",
    "requests.request": "HTTP round-trip",
    "subprocess.run": "subprocess", "subprocess.check_output": "subprocess",
}
_QUEUEISH = re.compile(r"(^|_)(q|queue|pending|inbox|outbox)$")
_THREADISH = re.compile(r"(^|_)(thread|proc|process|worker)s?$")

_ATTEMPT_NAME = re.compile(r"attempt|watcher")


def _is_test_path(rel: str) -> bool:
    parts = rel.split("/")
    return (any(p in ("tests", "testing", "fixtures") for p in parts)
            or parts[-1].startswith("test_"))


def _terminal(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


# ------------------------------------------------------- collective axis rule

def _spec_axis_names(call: ast.Call) -> set:
    """Literal axis names declared by a shard_map call: every string
    constant inside ``in_specs``/``out_specs`` (``P('data', ...)``)
    plus an ``axis_names=`` kwarg."""
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("in_specs", "out_specs", "axis_names", "axes"):
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    out.add(sub.value)
    return out


def _collective_axis(call: ast.Call) -> Optional[ast.AST]:
    """The axis argument of a collective call (positional arg 1 or the
    ``axis_name=`` kwarg), or None."""
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    if len(call.args) >= 2:
        return call.args[1]
    return None


@rule("protocol-collective-axis", "protocol",
      "collectives naming an axis absent from the enclosing shard_map "
      "spec")
def check_collective_axis(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        # local defs by name, so `shard_map(body, ...)` resolves `body`
        defs: dict[str, list] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            if _terminal(dotted(call.func)) != "shard_map" \
                    and dotted(call.func) not in _SHARD_MAP_NAMES:
                continue
            axes = _spec_axis_names(call)
            if not axes:
                continue       # specs not statically determinable
            bodies: list = []
            target = call.args[0] if call.args else None
            if isinstance(target, ast.Lambda):
                bodies.append(target)
            elif target is not None:
                for sub in ast.walk(target):
                    if isinstance(sub, ast.Name):
                        bodies.extend(defs.get(sub.id, ()))
            for body in bodies:
                for sub in ast.walk(body):
                    if not isinstance(sub, ast.Call):
                        continue
                    if _terminal(dotted(sub.func)) not in _COLLECTIVES:
                        continue
                    ax = _collective_axis(sub)
                    if not (isinstance(ax, ast.Constant)
                            and isinstance(ax.value, str)):
                        continue     # variable axis: runtime-validated
                    if ax.value in axes:
                        continue
                    qual = getattr(body, "name", "<lambda>")
                    f = sf.finding(
                        "protocol-collective-axis", sub,
                        f"collective `{_terminal(dotted(sub.func))}` "
                        f"names axis '{ax.value}' but the enclosing "
                        f"shard_map declares only {sorted(axes)} — a "
                        f"trace-time error on the real mesh (and "
                        f"invisible on a 1-device test mesh)",
                        hint="use an axis the mesh spec declares, or "
                             "thread the axis name through as a "
                             "parameter validated against "
                             "mesh.axis_names",
                        context=qual)
                    if f:
                        yield f


# --------------------------------------------------- divergent collective

def _is_divergent(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            dn = dotted(sub.func)
            if dn in _DIVERGENT_CALLS \
                    or _terminal(dn) == "process_index" \
                    or (dn or "").startswith(("random.", "np.random.")):
                return True
        elif isinstance(sub, ast.Name) and _RANKISH.match(sub.id):
            return True
        elif isinstance(sub, ast.Attribute) and _RANKISH.match(sub.attr):
            return True
    return False


def _is_collective_call(call: ast.Call) -> bool:
    term = _terminal(dotted(call.func))
    return (term in _COLLECTIVES or term in _HOST_COLLECTIVES
            or term.startswith("allgather"))


@rule("protocol-divergent-collective", "protocol",
      "collectives under a condition that can diverge per rank "
      "(deadlock: some ranks enter, the rest never arrive)")
def check_divergent_collective(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue

        def walk(node, divergent_stack, stack):
            for child in ast.iter_child_nodes(node):
                new_stack = stack
                div = divergent_stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    new_stack = stack + [child]
                    div = 0       # conditions don't cross function scopes
                elif isinstance(child, (ast.If, ast.While)) \
                        and _is_divergent(child.test):
                    div = divergent_stack + 1
                if isinstance(child, ast.Call) and divergent_stack > 0 \
                        and _is_collective_call(child):
                    qual = qualname_of(stack)
                    f = sf.finding(
                        "protocol-divergent-collective", child,
                        f"collective `{_terminal(dotted(child.func))}` "
                        f"dispatched under a per-rank-divergent "
                        f"condition in `{qual}` — ranks that take the "
                        f"other branch never enter it, and the ranks "
                        f"that did block until the collective timeout",
                        hint="hoist the collective out of the branch "
                             "(every rank must dispatch it), or derive "
                             "the condition from replicated state",
                        context=qual)
                    if f:
                        yield f
                yield from walk(child, div, new_stack)

        yield from walk(sf.tree, 0, [])


# --------------------------------------------------- attempt-thread blocking

def _blocking_reason(call: ast.Call) -> Optional[str]:
    dn = dotted(call.func)
    if dn in _BLOCKING:
        return _BLOCKING[dn]
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = _terminal(dotted(call.func.value))
        if attr == "join" and _THREADISH.search(recv or ""):
            return f"{recv}.join"
        if attr in ("get", "put") and _QUEUEISH.search(recv or ""):
            return f"blocking queue.{attr}"
        if attr == "urlopen":
            return "HTTP round-trip"
    return None


@rule("protocol-attempt-thread-blocking", "protocol",
      "blocking host calls in attempt/watcher thread targets")
def check_attempt_thread_blocking(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        # local + method defs by bare name
        defs: dict[str, list] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            if _terminal(dotted(call.func)) != "Thread":
                continue
            target = None
            tname = ""
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            tname += sub.value
            if target is None or not _ATTEMPT_NAME.search(tname):
                continue
            tn = _terminal(dotted(target))
            for body in defs.get(tn, ()):
                for sub in ast.walk(body):
                    if not isinstance(sub, ast.Call):
                        continue
                    reason = _blocking_reason(sub)
                    if reason is None:
                        continue
                    f = sf.finding(
                        "protocol-attempt-thread-blocking", sub,
                        f"{reason} on attempt/watcher thread "
                        f"'{tname}' (target `{body.name}`) — this "
                        f"thread must stay responsive so a wedged "
                        f"attempt can be unwound within its detection "
                        f"bound",
                        hint="move the blocking work to its own thread "
                             "or replace it with a bounded poll",
                        context=body.name)
                    if f:
                        yield f


# ---------------------------------------------------- commit-order rules

#: a call that COMMITS the manifest (``manifest_path``/``load_manifest``
#: are reads, not commits)
_MANIFEST_COMMIT_RE = re.compile(r"(commit|write|publish).*manifest"
                                 r"|manifest.*(commit|write|publish)")


def _ordered_events(fn_node) -> list:
    """(kind, node) in statement order for the commit-protocol rules:
    'fsync' (os.fsync), 'rename' (os.replace/os.rename of a tmp-ish
    source), 'payload' (a shard/payload write helper), 'manifest' (a
    manifest-commit helper)."""
    events = []
    for sub in ast.walk(fn_node):
        if not isinstance(sub, ast.Call):
            continue
        dn = dotted(sub.func)
        term = _terminal(dn)
        if dn in ("os.fsync", "fsync"):
            events.append(("fsync", sub))
        elif dn in ("os.replace", "os.rename"):
            src = dotted(sub.args[0]) if sub.args else None
            src_txt = src or ""
            if isinstance(sub.args[0] if sub.args else None, ast.JoinedStr):
                src_txt = "tmp"     # f"...tmp..." templates
            if "tmp" in src_txt.lower():
                events.append(("rename", sub))
        elif term in ("write_shard", "publish", "publish_sharded") \
                or "shard" in term and term.startswith("write"):
            events.append(("payload", sub))
        elif _MANIFEST_COMMIT_RE.search(term.lower()):
            events.append(("manifest", sub))
    events.sort(key=lambda e: (getattr(e[1], "lineno", 0),
                               getattr(e[1], "col_offset", 0)))
    return events


@rule("protocol-rename-before-fsync", "protocol",
      "tmp-file publish renamed with no fsync first (torn-write window)")
def check_rename_before_fsync(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            events = _ordered_events(node)
            fsynced = False
            for kind, call in events:
                if kind == "fsync":
                    fsynced = True
                elif kind == "rename" and not fsynced:
                    f = sf.finding(
                        "protocol-rename-before-fsync", call,
                        f"`{dotted(call.func)}` publishes a tmp file in "
                        f"`{node.name}` with no os.fsync first — after "
                        f"a crash the rename can be durable while the "
                        f"data is still in the page cache, leaving a "
                        f"complete-looking file with torn contents",
                        hint="flush + os.fsync(f.fileno()) before the "
                             "rename (see resilience/ckpt.py publish)",
                        context=node.name)
                    if f:
                        yield f


@rule("protocol-manifest-order", "protocol",
      "manifest committed before payload/shard writes in the same "
      "function (manifest must be LAST)")
def check_manifest_order(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if _is_test_path(sf.rel):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if _MANIFEST_COMMIT_RE.search(node.name.lower()):
                continue    # this function's own rename IS the manifest
            events = [e for e in _ordered_events(node)
                      if e[0] in ("manifest", "payload", "rename")]
            manifest_seen = None
            for kind, call in events:
                if kind == "manifest":
                    manifest_seen = call
                elif manifest_seen is not None:
                    f = sf.finding(
                        "protocol-manifest-order", manifest_seen,
                        f"the manifest is committed BEFORE a later "
                        f"payload write in `{node.name}` — a crash "
                        f"between the two leaves a manifest vouching "
                        f"for files that never landed (resume trusts "
                        f"the manifest)",
                        hint="commit the manifest LAST, after every "
                             "payload/shard rename has landed",
                        context=node.name)
                    if f:
                        yield f
                    break
