"""graftlint core: findings, source model, suppressions, baseline, rules.

The analyzer is AST-first: every rule family receives a :class:`Project`
holding the parsed module set (plus comments, because the concurrency
pass reads ``# guarded-by:`` / ``# requires-lock:`` annotations and every
rule honors ``# graftlint: disable=<rule>`` suppressions) and yields
:class:`Finding` records — rule id, file:line, message, fix hint.

Grandfathering: a finding whose :meth:`Finding.fingerprint` appears in
the checked-in baseline (``tools/graftlint_baseline.json``) is reported
as baselined and does NOT fail the run; anything new does. Fingerprints
deliberately exclude line numbers (they key on rule + file + enclosing
scope + the offending source line) so unrelated edits above a
grandfathered finding don't resurrect it.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Optional

#: suppression comment: ``# graftlint: disable=rule-a,rule-b`` on the
#: flagged line silences those rules there; ``disable-file=`` in the
#: module's first comment block silences them for the whole file.
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable(-file)?\s*=\s*"
                          r"([\w*,\- ]+)")


@dataclass
class Finding:
    """One analyzer result. ``context`` is the enclosing qualname
    (``Class.method`` / function / ``<module>``) — part of the stable
    fingerprint, so baselines survive reflows."""

    rule: str
    path: str            # repo-relative, '/'-separated
    line: int
    message: str
    hint: str = ""
    context: str = "<module>"
    code: str = ""       # stripped source of the flagged line
    baselined: bool = False

    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.context}|{self.code}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "context": self.context, "message": self.message,
                "hint": self.hint, "code": self.code,
                "baselined": self.baselined}

    def render(self) -> str:
        mark = " [baselined]" if self.baselined else ""
        out = f"{self.path}:{self.line}: {self.rule}{mark}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class SourceFile:
    """One parsed module: AST + raw lines + comment map + suppressions."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.comments: dict[int, str] = {}   # line -> comment text
        self._line_suppress: dict[int, set[str]] = {}
        self._file_suppress: set[str] = set()
        self._scan_comments()

    def _scan_comments(self):
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                self.comments[line] = tok.string
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(2).split(",")
                             if r.strip()}
                    if m.group(1):          # disable-file
                        self._file_suppress |= rules
                    else:
                        self._line_suppress.setdefault(line,
                                                       set()).update(rules)
        except tokenize.TokenError:
            pass

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, line: int, rule: str) -> bool:
        for pool in (self._file_suppress,
                     self._line_suppress.get(line, ()),
                     # the line ABOVE the statement also counts — long
                     # statements often have no room on the line itself
                     self._line_suppress.get(line - 1, ())):
            if rule in pool or "*" in pool:
                return True
        return False

    def finding(self, rule: str, node, message: str, hint: str = "",
                context: str = "<module>") -> Optional[Finding]:
        """Build a Finding for ``node`` unless suppressed there."""
        line = getattr(node, "lineno", 1)
        if self.suppressed(line, rule):
            return None
        return Finding(rule=rule, path=self.rel, line=line,
                       message=message, hint=hint, context=context,
                       code=self.line_text(line))


class Project:
    """The unit a rule family analyzes: parsed sources + repo context."""

    def __init__(self, files: list[SourceFile], root: str,
                 options: Optional[dict] = None):
        self.files = files
        self.root = root
        self.options = options or {}

    def by_rel(self, rel: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel == rel or f.rel.endswith("/" + rel):
                return f
        return None


def qualname_of(stack: list) -> str:
    """Dotted name of an AST scope stack (ClassDef/FunctionDef nodes)."""
    names = [getattr(n, "name", "?") for n in stack]
    return ".".join(names) if names else "<module>"


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------- rule registry

@dataclass
class Rule:
    name: str
    family: str
    doc: str
    run: Callable[[Project], Iterable[Finding]]
    #: ``file`` — findings depend only on one module's source, so the
    #: incremental mode may reuse cached results for unchanged files;
    #: ``project`` — findings depend on cross-file state (lock graphs,
    #: docs catalogues, the tests/ index), re-run whenever anything in
    #: the digest changes.
    scope: str = "file"


_RULES: list[Rule] = []


def rule(name: str, family: str, doc: str, scope: str = "file"):
    """Register a rule runner: ``fn(project) -> Iterable[Finding]``."""
    def deco(fn):
        _RULES.append(Rule(name, family, doc, fn, scope))
        return fn
    return deco


def all_rules() -> list[Rule]:
    # importing the families registers their rules
    from . import (jit_safety, concurrency, consistency,  # noqa: F401
                   donation, protocol, races)  # noqa: F401
    return list(_RULES)


# ------------------------------------------------------------------- discovery

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".graftlint"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for base, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for n in sorted(names):
                if n.endswith(".py"):
                    yield os.path.join(base, n)


def load_project(paths: list[str], root: Optional[str] = None,
                 options: Optional[dict] = None) -> Project:
    root = os.path.abspath(root or os.path.commonpath(
        [os.path.abspath(p) for p in paths]))
    if os.path.isfile(root):
        root = os.path.dirname(root)
    files = []
    for fp in iter_python_files(paths):
        ap = os.path.abspath(fp)
        rel = os.path.relpath(ap, root)
        try:
            with open(ap, "r", encoding="utf-8") as f:
                text = f.read()
            files.append(SourceFile(ap, rel, text))
        except (OSError, SyntaxError, ValueError):
            # unparsable files are someone else's problem (CI syntax
            # checks); the analyzer must not crash on them
            continue
    return Project(files, root, options)


# -------------------------------------------------------------------- baseline

class Baseline:
    """The checked-in grandfather list. Entries are readable dicts —
    reviewers should see WHAT was grandfathered, not a hash."""

    def __init__(self, entries: Optional[list[dict]] = None):
        self.entries = entries or []
        self._keys = {self._key(e) for e in self.entries}

    @staticmethod
    def _key(e: dict) -> str:
        return (f"{e.get('rule')}|{e.get('file')}|{e.get('context')}"
                f"|{e.get('code')}")

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.isfile(path):
            return cls([])
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("findings", []))

    def matches(self, finding: Finding) -> bool:
        return finding.fingerprint() in self._keys

    @staticmethod
    def write(path: str, findings: list[Finding]):
        doc = {"version": 1,
               "note": ("grandfathered graftlint findings; fix and remove "
                        "entries rather than adding new ones"),
               "findings": [
                   {"rule": f.rule, "file": f.path, "context": f.context,
                    "code": f.code, "todo": "grandfathered; fix and remove"}
                   for f in sorted(findings,
                                   key=lambda f: (f.rule, f.path, f.line))]}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")


# ------------------------------------------------------------------ entrypoint

def _run_file_rules_chunk(file_paths: list[str], root: str,
                          rule_names: list[str],
                          options: Optional[dict]) -> list[Finding]:
    """Worker-process body for ``--jobs``: run the named FILE-scoped
    rules over one chunk of files. File scope is the contract that makes
    this sound — each finding depends only on its own module's source,
    so a sub-project per chunk sees everything those rules need."""
    project = load_project(file_paths, root=root, options=options)
    wanted = set(rule_names)
    out: list[Finding] = []
    for r in all_rules():
        if r.name in wanted:
            out.extend(f for f in r.run(project) if f is not None)
    return out


def _chunk_by_size(files: list[SourceFile], n: int) -> list[list[str]]:
    """Split into ``n`` chunks balanced by source size (greedy LPT), so
    one chunk of 2k-line modules doesn't serialize the whole pool."""
    chunks: list[list[str]] = [[] for _ in range(n)]
    weights = [0] * n
    for sf in sorted(files, key=lambda s: -len(s.text)):
        i = weights.index(min(weights))
        chunks[i].append(sf.path)
        weights[i] += len(sf.text) + 1
    return [c for c in chunks if c]


def run_analysis(paths: list[str], root: Optional[str] = None,
                 baseline: Optional[str] = None,
                 rules: Optional[Iterable[str]] = None,
                 options: Optional[dict] = None,
                 jobs: int = 1) -> list[Finding]:
    """Run every (selected) rule over ``paths``; returns all findings with
    ``baselined`` marked. Callers decide what a failure is (the CLI and
    the tier-1 shim fail on any non-baselined finding). ``jobs > 1``
    fans the file-scoped rules out over worker processes (chunked by
    source size); project-scoped rules always run in this process —
    their cross-file state (lock graphs, docs catalogues, the thread-
    root index) doesn't partition."""
    project = load_project(paths, root=root, options=options)
    selected = all_rules()
    if rules is not None:
        wanted = set(rules)
        selected = [r for r in selected
                    if r.name in wanted or r.family in wanted]
    findings: list[Finding] = []
    jobs = max(1, int(jobs or 1))
    serial = list(selected)
    if jobs > 1 and len(project.files) > 1:
        file_rules = [r for r in selected if r.scope == "file"]
        if file_rules:
            names = [r.name for r in file_rules]
            chunks = _chunk_by_size(project.files,
                                    min(jobs, len(project.files)))
            try:
                import concurrent.futures as cf
                with cf.ProcessPoolExecutor(max_workers=len(chunks)) as ex:
                    futs = [ex.submit(_run_file_rules_chunk, c,
                                      project.root, names, options)
                            for c in chunks]
                    for fut in futs:
                        findings.extend(fut.result())
            except Exception:
                # a broken pool (pickling, fork limits, sandboxing) must
                # degrade to the serial path, never to missed findings
                findings = []
            else:
                serial = [r for r in selected if r.scope != "file"]
    for r in serial:
        findings.extend(f for f in r.run(project) if f is not None)
    base = Baseline.load(baseline) if baseline else Baseline([])
    for f in findings:
        f.baselined = base.matches(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
