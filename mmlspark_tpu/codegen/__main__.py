"""Regenerate all codegen artifacts in-repo: ``python -m mmlspark_tpu.codegen``."""

import os
import sys

from . import generate_all

root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if len(sys.argv) > 1:
    root = sys.argv[1]
out = generate_all(root)
for kind, paths in out.items():
    print(f"{kind}: {len(paths)} files")
