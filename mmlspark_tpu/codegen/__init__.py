"""Codegen: single-source-of-truth artifacts reflected from the Param DSL.

Re-design of the reference's codegen layer (reference:
src/codegen/src/main/scala/CodeGen.scala:44-96), which reflects every
``PipelineStage`` out of the built jars and emits PySpark/SparklyR wrappers,
per-stage smoke tests (PySparkWrapperTest.scala) and Sphinx docs (DocGen.scala).

This framework is Python-first so wrappers invert (SURVEY.md §2.6): the Param
DSL *is* the API. What codegen still owes the user, generated from the same
single source of truth (the stage registry + Param descriptors):

  * ``generate_docs``   — markdown API reference, one page per stage with the
    param table (name/type/default/domain/doc), plus an index (DocGen analog);
  * ``generate_stubs``  — ``.pyi`` typing stubs declaring the metaclass-made
    ``setFoo``/``getFoo`` accessors so IDEs/type-checkers see the full
    surface (PySparkWrapper analog);
  * ``generate_smoke_tests`` — a pytest file with one construct/param-
    round-trip/copy test per stage (PySparkWrapperTest analog).

All three iterate ``registered_stages()`` the way CodeGen iterates jars, so a
new stage is covered the moment its class is defined.
"""

from __future__ import annotations

import os
from collections import defaultdict

from ..core.params import Param
from ..core.pipeline import (Estimator, Model, Transformer, registered_stages)

_NO_DEFAULT_REPR = "(required)"


def _framework_stages() -> dict[str, type]:
    return {q: c for q, c in registered_stages().items()
            if q.startswith("mmlspark_tpu.")}


def _kind(cls: type) -> str:
    if issubclass(cls, Model):
        return "Model"
    if issubclass(cls, Estimator):
        return "Estimator"
    if issubclass(cls, Transformer):
        return "Transformer"
    return "PipelineStage"


def _ptype_name(p: Param) -> str:
    if p.ptype is None:
        return "complex" if not p.jsonable else "any"
    if isinstance(p.ptype, tuple):
        return "/".join(t.__name__ for t in p.ptype)
    return p.ptype.__name__


def _default_repr(p: Param) -> str:
    return repr(p.default) if p.has_default else _NO_DEFAULT_REPR


# --------------------------------------------------------------------- docs

def stage_doc_markdown(cls: type) -> str:
    """One markdown page for a stage: docstring + param table."""
    lines = [f"# {cls.__name__}", ""]
    lines.append(f"*{_kind(cls)}* — `{cls.__module__}.{cls.__qualname__}`")
    lines.append("")
    if cls.__doc__:
        lines.append(cls.__doc__.strip())
        lines.append("")
    params = cls.params()
    if params:
        lines.append("## Parameters")
        lines.append("")
        lines.append("| name | type | default | doc |")
        lines.append("|---|---|---|---|")
        for name in sorted(params):
            p = params[name]
            doc = (p.doc or "").replace("|", "\\|").replace("\n", " ")
            lines.append(f"| `{name}` | {_ptype_name(p)} "
                         f"| `{_default_repr(p)}` | {doc} |")
        lines.append("")
        lines.append("Accessors: " + ", ".join(
            f"`set{n[0].upper()+n[1:]}` / `get{n[0].upper()+n[1:]}`"
            for n in sorted(params)))
        lines.append("")
    return "\n".join(lines)


def generate_docs(out_dir: str) -> list[str]:
    """Write one markdown page per registered stage + an index; returns the
    written paths (reference DocGen.scala emits .rst the same way)."""
    os.makedirs(out_dir, exist_ok=True)
    by_module: dict[str, list[type]] = defaultdict(list)
    paths = []
    for qual, cls in sorted(_framework_stages().items()):
        module = qual.split(".")[1]  # mmlspark_tpu.<pkg>...
        by_module[module].append(cls)
        path = os.path.join(out_dir, f"{cls.__name__}.md")
        with open(path, "w") as f:
            f.write(stage_doc_markdown(cls))
        paths.append(path)
    index = [
        "# API reference", "",
        "Generated from the stage registry by `mmlspark_tpu.codegen` — "
        "do not edit by hand; regenerate with "
        "`python -m mmlspark_tpu.codegen`.", "",
    ]
    for module in sorted(by_module):
        index.append(f"## {module}")
        index.append("")
        for cls in sorted(by_module[module], key=lambda c: c.__name__):
            first = (cls.__doc__ or "").strip().split("\n")[0]
            index.append(f"- [{cls.__name__}]({cls.__name__}.md) "
                         f"(*{_kind(cls)}*) — {first}")
        index.append("")
    path = os.path.join(out_dir, "index.md")
    with open(path, "w") as f:
        f.write("\n".join(index))
    paths.append(path)
    return paths


# -------------------------------------------------------------------- stubs

_PYI_TYPES = {"bool": "bool", "int": "int", "float": "float", "str": "str",
              "dict": "dict", "list/tuple": "list | tuple"}


def _pyi_type(p: Param) -> str:
    return _PYI_TYPES.get(_ptype_name(p), "object")


def stage_stub(cls: type) -> str:
    """.pyi class body declaring every generated accessor."""
    lines = [f"class {cls.__name__}:"]
    params = cls.params()
    if not params:
        lines.append("    ...")
        return "\n".join(lines)
    for name in sorted(params):
        p = params[name]
        cap = name[0].upper() + name[1:]
        t = _pyi_type(p)
        lines.append(f"    {name}: {t}")
        lines.append(f"    def set{cap}(self, value: {t}) -> "
                     f"\"{cls.__name__}\": ...")
        lines.append(f"    def get{cap}(self) -> {t}: ...")
    return "\n".join(lines)


def generate_stubs(out_dir: str) -> list[str]:
    """Write one ``<module>.pyi`` per framework module containing stage stubs
    (the role of the reference's generated PySpark wrapper classes,
    PySparkWrapper.scala:33-160: make the set/get surface visible to tools)."""
    os.makedirs(out_dir, exist_ok=True)
    by_srcmod: dict[str, list[type]] = defaultdict(list)
    for qual, cls in sorted(_framework_stages().items()):
        by_srcmod[cls.__module__].append(cls)
    paths = []
    for mod in sorted(by_srcmod):
        rel = mod.replace(".", os.sep) + ".pyi"
        path = os.path.join(out_dir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        chunks = ["# Generated by mmlspark_tpu.codegen — do not edit.", ""]
        for cls in sorted(by_srcmod[mod], key=lambda c: c.__name__):
            chunks.append(stage_stub(cls))
            chunks.append("")
        with open(path, "w") as f:
            f.write("\n".join(chunks))
        paths.append(path)
    return paths


# -------------------------------------------------------------- smoke tests

def generate_smoke_tests(out_path: str) -> str:
    """Write a pytest module with one generated test per stage: construct,
    set/get round-trip every simple param, copy(), repr (reference
    PySparkWrapperTest.scala emits one python smoke test per wrapped stage).
    Values are synthesized from the param type + validator."""
    lines = [
        '"""Generated by mmlspark_tpu.codegen — do not edit."""',
        "import pytest",
        "import mmlspark_tpu  # populate the registry",
        "from mmlspark_tpu.core.pipeline import lookup_stage_class",
        "from mmlspark_tpu.codegen import synth_value",
        "",
    ]
    for qual, cls in sorted(_framework_stages().items()):
        name = cls.__name__
        lines += [
            f"def test_{name}_params():",
            f"    cls = lookup_stage_class({qual!r})",
            "    stage = cls()",
            "    for pname, p in cls.params().items():",
            "        value = synth_value(p, stage)",
            "        if value is NotImplemented:",
            "            continue",
            "        getattr(stage, 'set' + pname[0].upper() + pname[1:])(value)",
            "        got = getattr(stage, 'get' + pname[0].upper() + pname[1:])()",
            "        assert got == value or got is value",
            "    clone = stage.copy()",
            "    assert clone._paramMap == stage._paramMap",
            "    assert cls.__name__ in repr(stage)",
            "",
        ]
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    with open(out_path, "w") as f:
        f.write("\n".join(lines))
    return out_path


def synth_value(p: Param, stage=None):
    """A legal value for a param, derived from type + default + validator;
    NotImplemented when no safe value can be synthesized (complex params)."""
    if not p.jsonable:
        return NotImplemented
    if p.has_default and p.default is not None:
        return p.default
    t = _ptype_name(p)
    candidates = {
        "bool": [True, False],
        "int": [1, 2, 10, 100, 0],
        "float": [0.5, 1.0, 0.0, 2.0],
        "str": ["x"],
        "dict": [{}],
        "list/tuple": [()],
    }.get(t, [None])
    for v in candidates:
        try:
            p.validate(v)
            return v
        except Exception:
            continue
    return NotImplemented


def generate_all(repo_root: str) -> dict[str, list[str]]:
    docs = generate_docs(os.path.join(repo_root, "docs", "api"))
    stubs = generate_stubs(os.path.join(repo_root, "stubs"))
    tests = [generate_smoke_tests(
        os.path.join(repo_root, "tests", "test_generated_smoke.py"))]
    return {"docs": docs, "stubs": stubs, "tests": tests}
