"""Codegen: single-source-of-truth artifacts reflected from the Param DSL.

Re-design of the reference's codegen layer (reference:
src/codegen/src/main/scala/CodeGen.scala:44-96), which reflects every
``PipelineStage`` out of the built jars and emits PySpark/SparklyR wrappers,
per-stage smoke tests (PySparkWrapperTest.scala) and Sphinx docs (DocGen.scala).

This framework is Python-first so wrappers invert (SURVEY.md §2.6): the Param
DSL *is* the API. What codegen still owes the user, generated from the same
single source of truth (the stage registry + Param descriptors):

  * ``generate_docs``   — markdown API reference, one page per stage with the
    param table (name/type/default/domain/doc), plus an index (DocGen analog);
  * ``generate_stubs``  — ``.pyi`` typing stubs declaring the metaclass-made
    ``setFoo``/``getFoo`` accessors so IDEs/type-checkers see the full
    surface (PySparkWrapper analog);
  * ``generate_smoke_tests`` — a pytest file with one construct/param-
    round-trip/copy test per stage (PySparkWrapperTest analog).

All three iterate ``registered_stages()`` the way CodeGen iterates jars, so a
new stage is covered the moment its class is defined.
"""

from __future__ import annotations

import os
from collections import defaultdict

from ..core.params import Param
from ..core.pipeline import (Estimator, Model, Transformer, registered_stages)


def _write_text(path: str, text: str) -> None:
    """Every generated-artifact write goes through here: one chaos site
    (``codegen.write``) covers full-disk / read-only-checkout failures
    for all of docs/stubs/R/smoke generation."""
    from ..resilience import faults
    faults.inject("codegen.write")
    with open(path, "w") as f:
        f.write(text)


_NO_DEFAULT_REPR = "(required)"


def _framework_stages() -> dict[str, type]:
    return {q: c for q, c in registered_stages().items()
            if q.startswith("mmlspark_tpu.")}


def _kind(cls: type) -> str:
    if issubclass(cls, Model):
        return "Model"
    if issubclass(cls, Estimator):
        return "Estimator"
    if issubclass(cls, Transformer):
        return "Transformer"
    return "PipelineStage"


def _ptype_name(p: Param) -> str:
    if p.ptype is None:
        return "complex" if not p.jsonable else "any"
    if isinstance(p.ptype, tuple):
        return "/".join(t.__name__ for t in p.ptype)
    return p.ptype.__name__


def _default_repr(p: Param) -> str:
    return repr(p.default) if p.has_default else _NO_DEFAULT_REPR


# --------------------------------------------------------------------- docs

def stage_doc_markdown(cls: type) -> str:
    """One markdown page for a stage: docstring + param table."""
    lines = [f"# {cls.__name__}", ""]
    lines.append(f"*{_kind(cls)}* — `{cls.__module__}.{cls.__qualname__}`")
    lines.append("")
    if cls.__doc__:
        lines.append(cls.__doc__.strip())
        lines.append("")
    params = cls.params()
    if params:
        lines.append("## Parameters")
        lines.append("")
        lines.append("| name | type | default | doc |")
        lines.append("|---|---|---|---|")
        for name in sorted(params):
            p = params[name]
            doc = (p.doc or "").replace("|", "\\|").replace("\n", " ")
            lines.append(f"| `{name}` | {_ptype_name(p)} "
                         f"| `{_default_repr(p)}` | {doc} |")
        lines.append("")
        lines.append("Accessors: " + ", ".join(
            f"`set{n[0].upper()+n[1:]}` / `get{n[0].upper()+n[1:]}`"
            for n in sorted(params)))
        lines.append("")
    return "\n".join(lines)


def generate_docs(out_dir: str) -> list[str]:
    """Write one markdown page per registered stage + an index; returns the
    written paths (reference DocGen.scala emits .rst the same way)."""
    os.makedirs(out_dir, exist_ok=True)
    by_module: dict[str, list[type]] = defaultdict(list)
    paths = []
    for qual, cls in sorted(_framework_stages().items()):
        module = qual.split(".")[1]  # mmlspark_tpu.<pkg>...
        by_module[module].append(cls)
        path = os.path.join(out_dir, f"{cls.__name__}.md")
        _write_text(path, stage_doc_markdown(cls))
        paths.append(path)
    index = [
        "# API reference", "",
        "Generated from the stage registry by `mmlspark_tpu.codegen` — "
        "do not edit by hand; regenerate with "
        "`python -m mmlspark_tpu.codegen`.", "",
    ]
    for module in sorted(by_module):
        index.append(f"## {module}")
        index.append("")
        for cls in sorted(by_module[module], key=lambda c: c.__name__):
            first = (cls.__doc__ or "").strip().split("\n")[0]
            index.append(f"- [{cls.__name__}]({cls.__name__}.md) "
                         f"(*{_kind(cls)}*) — {first}")
        index.append("")
    path = os.path.join(out_dir, "index.md")
    _write_text(path, "\n".join(index))
    paths.append(path)
    return paths


# -------------------------------------------------------------------- stubs

_PYI_TYPES = {"bool": "bool", "int": "int", "float": "float", "str": "str",
              "dict": "dict", "list/tuple": "list | tuple"}


def _pyi_type(p: Param) -> str:
    return _PYI_TYPES.get(_ptype_name(p), "object")


def stage_stub(cls: type) -> str:
    """.pyi class body declaring every generated accessor."""
    lines = [f"class {cls.__name__}:"]
    params = cls.params()
    if not params:
        lines.append("    ...")
        return "\n".join(lines)
    for name in sorted(params):
        p = params[name]
        cap = name[0].upper() + name[1:]
        t = _pyi_type(p)
        lines.append(f"    {name}: {t}")
        lines.append(f"    def set{cap}(self, value: {t}) -> "
                     f"\"{cls.__name__}\": ...")
        lines.append(f"    def get{cap}(self) -> {t}: ...")
    return "\n".join(lines)


def generate_stubs(out_dir: str) -> list[str]:
    """Write one ``<module>.pyi`` per framework module containing stage stubs
    (the role of the reference's generated PySpark wrapper classes,
    PySparkWrapper.scala:33-160: make the set/get surface visible to tools)."""
    os.makedirs(out_dir, exist_ok=True)
    by_srcmod: dict[str, list[type]] = defaultdict(list)
    for qual, cls in sorted(_framework_stages().items()):
        by_srcmod[cls.__module__].append(cls)
    paths = []
    for mod in sorted(by_srcmod):
        rel = mod.replace(".", os.sep) + ".pyi"
        path = os.path.join(out_dir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        chunks = ["# Generated by mmlspark_tpu.codegen — do not edit.", ""]
        for cls in sorted(by_srcmod[mod], key=lambda c: c.__name__):
            chunks.append(stage_stub(cls))
            chunks.append("")
        _write_text(path, "\n".join(chunks))
        paths.append(path)
    return paths


# --------------------------------------------------------------- R wrappers

def _r_name(cls_name: str) -> str:
    """CamelCase -> mt_snake_case (sparklyr's ml_logistic_regression style).
    Acronym runs stay fused until their last letter (GBTClassifier ->
    gbt_classifier, HTTPTransformer -> http_transformer); digits glue
    (Word2Vec -> word2vec, sparklyr's ft_word2vec)."""
    out = []
    for i, ch in enumerate(cls_name):
        if ch.isupper() and i:
            prev = cls_name[i - 1]
            nxt = cls_name[i + 1] if i + 1 < len(cls_name) else ""
            if prev.islower() or (prev.isupper() and nxt.islower()):
                out.append("_")
        out.append(ch.lower())
    return "mt_" + "".join(out)


def _r_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, int):
        return f"{v}L"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        return "list(" + ", ".join(_r_literal(x) for x in v) + ")"
    if isinstance(v, dict):
        return "list(" + ", ".join(
            f"{k} = {_r_literal(x)}" for k, x in v.items()) + ")"
    return "NULL"


def stage_r_wrapper(qual: str, cls: type) -> str:
    """One R constructor function per stage, sparklyr-shaped: named args with
    the Param defaults, passed through to the Python setters via reticulate
    (reference SparklyRWrapper.scala emits the same per-stage surface)."""
    params = cls.params()
    simple = [n for n in sorted(params) if params[n].jsonable]
    required = [n for n in simple if not params[n].has_default]
    optional = [n for n in simple if params[n].has_default]
    args = required + [f"{n} = {_r_literal(params[n].default)}"
                       for n in optional]
    first = (cls.__doc__ or "").strip().split("\n")[0]
    sig = ", ".join(args)
    lines = [f"#' {cls.__name__} ({_kind(cls)}). {first}".rstrip(),
             "#' Integer params take R integers (5L); complex params via"
             " mt_set_param().",
             f"{_r_name(cls.__name__)} <- function({sig}) {{",
             f'  stage <- mt_stage("{qual}")']
    if simple:
        # only args the CALLER supplied become set params: stages
        # distinguish explicitly-set values from defaults (isSet drives
        # e.g. the GBDT auto growth policy), and materializing every
        # default here would erase that signal for all R-built stages
        lines.append("  vals <- list()")
        for n in simple:
            lines.append(
                f"  if (!missing({n})) vals${n} <- {n}")
        lines += ["  mt_set_params(stage, vals)", "}", ""]
    else:
        lines += ["  stage", "}", ""]
    return "\n".join(lines)


def generate_r_wrappers(out_path: str) -> str:
    """Write the generated half of the R binding: one wrapper per registered
    stage. The static runtime half (mt_stage/mt_set_params/mt_fit/...) lives
    in R/ml_utils.R, the analog of the reference's hand-written
    core/ml/src/main/R/ml_utils.R."""
    chunks = ["# Generated by mmlspark_tpu.codegen -- do not edit.",
              "# Requires R/ml_utils.R (reticulate runtime glue).", ""]
    for qual, cls in sorted(_framework_stages().items()):
        if issubclass(cls, Model):
            continue  # fitted models come back from mt_fit, not constructors
        chunks.append(stage_r_wrapper(qual, cls))
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    _write_text(out_path, "\n".join(chunks))
    return out_path


# -------------------------------------------------------------- smoke tests

def generate_smoke_tests(out_path: str) -> str:
    """Write a pytest module with one generated test per stage: construct,
    set/get round-trip every simple param, copy(), repr (reference
    PySparkWrapperTest.scala emits one python smoke test per wrapped stage).
    Values are synthesized from the param type + validator."""
    lines = [
        '"""Generated by mmlspark_tpu.codegen — do not edit."""',
        "import pytest",
        "import mmlspark_tpu  # populate the registry",
        "from mmlspark_tpu.core.pipeline import lookup_stage_class",
        "from mmlspark_tpu.codegen import synth_value",
        "",
    ]
    for qual, cls in sorted(_framework_stages().items()):
        name = cls.__name__
        lines += [
            f"def test_{name}_params():",
            f"    cls = lookup_stage_class({qual!r})",
            "    stage = cls()",
            "    for pname, p in cls.params().items():",
            "        value = synth_value(p, stage)",
            "        if value is NotImplemented:",
            "            continue",
            "        getattr(stage, 'set' + pname[0].upper() + pname[1:])(value)",
            "        got = getattr(stage, 'get' + pname[0].upper() + pname[1:])()",
            "        assert got == value or got is value",
            "    clone = stage.copy()",
            "    assert clone._paramMap == stage._paramMap",
            "    assert cls.__name__ in repr(stage)",
            "",
        ]
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    _write_text(out_path, "\n".join(lines))
    return out_path


def synth_value(p: Param, stage=None):
    """A legal value for a param, derived from type + default + validator;
    NotImplemented when no safe value can be synthesized (complex params)."""
    if not p.jsonable:
        return NotImplemented
    if p.has_default and p.default is not None:
        return p.default
    t = _ptype_name(p)
    candidates = {
        "bool": [True, False],
        "int": [1, 2, 10, 100, 0],
        "float": [0.5, 1.0, 0.0, 2.0],
        "str": ["x"],
        "dict": [{}],
        "list/tuple": [()],
    }.get(t, [None])
    for v in candidates:
        try:
            p.validate(v)
            return v
        except Exception:
            continue
    return NotImplemented


def generate_all(repo_root: str) -> dict[str, list[str]]:
    docs = generate_docs(os.path.join(repo_root, "docs", "api"))
    stubs = generate_stubs(os.path.join(repo_root, "stubs"))
    tests = [generate_smoke_tests(
        os.path.join(repo_root, "tests", "test_generated_smoke.py"))]
    r = [generate_r_wrappers(
        os.path.join(repo_root, "R", "generated_wrappers.R"))]
    return {"docs": docs, "stubs": stubs, "tests": tests, "r": r}
