"""Serving through a SPARK pipeline: the readStream analog.

The reference's §3.5 workflow is continuous request/response over Spark
structured streaming: ``DistributedHTTPSource`` (a streaming Source whose
executors run HTTP servers, DistributedHTTPSource.scala:270-368) feeds
micro-batches through a scoring pipeline and ``DistributedHTTPSink``
answers the in-flight exchanges (:418-450). The TPU-native fleet —
:class:`mmlspark_tpu.io.http.fleet.ProcessHTTPSource` — already
implements the identical offset/getBatch/commit contract over real
worker OS processes; this module drives that contract FROM the Spark
surface, so a Spark user serves through a Spark pipeline:

    from mmlspark_tpu.spark import wrap
    from mmlspark_tpu.spark.streaming import serveThroughSpark
    source, stream = serveThroughSpark(spark, wrap(fitted_pipeline),
                                       n_workers=4)
    ... clients POST to source.urls ...
    stream.stop()

Each micro-batch is exactly the reference's cycle: ``getOffset`` (poll
the worker fleet) -> ``getBatch(start, end]`` (replay-stable rows as a
Spark DataFrame of (id, value)) -> the wrapped pipeline's ``transform``
(executes via mapInArrow — Spark's executors do the scoring) ->
per-exchange replies through the fleet sink -> ``commit``. A transform
failure replays the same offset range once (the source guarantees
identical rows) before failing those clients with 500s — the
recovery-semantics half of the reference's structured-streaming story.

On real pyspark this is the ``foreachBatch`` shape (a driver loop handing
micro-batches to Spark jobs); the rows originate from the fleet's own
sockets rather than a Spark-native Source, which keeps the adapter free
of pyspark's DataSource V2 plugin ABI while preserving every observable
semantic: offsets, replay, commit, per-exchange replies.
"""

from __future__ import annotations

import json
import threading
import time

from ..core.utils import get_logger

log = get_logger("spark.streaming")


class SparkServingStream:
    """Drives a :class:`ProcessHTTPSource` micro-batch loop through a
    Spark-side transformer (normally a ``wrap()``'d fitted pipeline whose
    ``transform`` runs on the executors via mapInArrow).

    The transformer sees a Spark DataFrame with columns ``(id, value)``
    and must produce a ``reply`` column (plus an optional ``code``
    column), exactly the single-process ``ServingLoop`` contract."""

    def __init__(self, spark, source, transformer, reply_col: str = "reply",
                 code_col: str = "code", max_retries: int = 1,
                 idle_sleep: float = 0.005):
        self.spark = spark
        self.source = source
        self.transformer = transformer
        self.reply_col = reply_col
        self.code_col = code_col
        self.max_retries = max_retries
        self.idle_sleep = idle_sleep
        # processBatch is public (tests / foreachBatch step it) while
        # _run drives it from the daemon thread: the counter increment
        # is a read-modify-write and must hold the lock
        self._lock = threading.Lock()
        self.batches_done = 0                           # guarded-by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    # ---- one micro-batch (public so tests / foreachBatch can step it) ----
    def processBatch(self) -> int:
        """Run one poll->transform->reply->commit cycle; returns the number
        of requests answered (0 = idle)."""
        import pandas as pd

        start = self.source.committedOffset()
        end = self.source.getOffset()
        if end == start:
            return 0
        n = 0
        for attempt in range(self.max_retries + 1):
            batch = self.source.getBatch(start, end)   # replay-stable
            ids = [str(i) for i in batch.col("id")]
            sdf = self.spark.createDataFrame(pd.DataFrame({
                "id": ids, "value": [str(v) for v in batch.col("value")]}))
            try:
                out = self.transformer.transform(sdf).toPandas()
                codes = (out[self.code_col].astype(int)
                         if self.code_col in out.columns
                         else [200] * len(out))
                answered = set()
                for ex_id, code, reply in zip(out["id"], codes,
                                              out[self.reply_col]):
                    self.source.respond(str(ex_id), int(code), str(reply))
                    answered.add(str(ex_id))
                # a transformer that filters rows would otherwise leave the
                # dropped exchanges unanswered until the client's socket
                # times out; fail them explicitly before the commit
                for ex_id in ids:
                    if ex_id not in answered:
                        self.source.respond(ex_id, 500, json.dumps(
                            {"error": "transformer returned no row for "
                                      "this request id"}))
                n = len(ids)   # every request was answered (some with 500)
                break
            except Exception as e:
                log.warning("spark micro-batch (%d, %d] attempt %d "
                            "failed: %s", start, end, attempt, e)
                if attempt == self.max_retries:
                    for ex_id in ids:
                        self.source.respond(ex_id, 500,
                                            json.dumps({"error": str(e)}))
                    n = len(ids)
        self.source.flush()
        self.source.commit(end)
        with self._lock:
            self.batches_done += 1
        return n

    # ---- continuous loop (the foreachBatch-style driver thread) ----
    def _run(self):
        while not self._stop.is_set():
            try:
                if self.processBatch() == 0:
                    time.sleep(self.idle_sleep)
            except Exception as e:   # the loop itself must survive
                log.warning("serving stream cycle failed: %s", e)
                time.sleep(self.idle_sleep)

    def start(self) -> "SparkServingStream":
        self._thread.start()
        return self

    def stop(self, close_source: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        if close_source:
            self.source.close()


def serveThroughSpark(spark, transformer, n_workers: int = 2,
                      host: str = "127.0.0.1", base_port: int = 0,
                      **stream_kw):
    """One-call serve: spawn the worker-process fleet, start the Spark
    micro-batch loop, return ``(source, stream)``. Clients POST to
    ``source.urls``; every request is answered by the Spark-side
    pipeline. The reference analog is readStream on DistributedHTTPSource
    + writeStream into DistributedHTTPSink (§3.5)."""
    from ..io.http.fleet import ProcessHTTPSource
    source = ProcessHTTPSource(n_workers=n_workers, host=host,
                               base_port=base_port)
    try:
        stream = SparkServingStream(spark, source, transformer,
                                    **stream_kw).start()
    except Exception:
        source.close()
        raise
    return source, stream
