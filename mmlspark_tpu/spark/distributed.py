"""Distributed fit launched FROM the Spark data plane.

The reference's signature architecture is that distributed training starts
INSIDE the cluster's executors: LightGBM workers ARE the Spark partitions
(reference: LightGBMClassifier.scala:35-47 — coalesce -> mapPartitions ->
``LGBM_NetworkInit`` with a machine list aggregated on the driver,
LightGBMUtils.scala:98-160), and CNTK training is launched from the driver
onto the worker ring (CommandBuilders.scala:149-267). This module is that
move for the TPU-native framework: a **barrier-stage** job in which every
partition task joins the JAX coordination service, wraps its partition's
Arrow batches as its :class:`ShardedDataFrame` shard, and runs the
existing multi-process collective fit (``TpuLearner.fit`` / GBDT
``fit``) — the histogram/gradient all-reduces ride XLA collectives over
the fleet exactly as they do under the MMLTPU_* launcher contract.

The rendezvous replaces the reference's driver-socket machine-list
aggregation with Spark's own ``BarrierTaskContext.allGather``: task 0
binds a free port on its host and gathers ``host:port`` to everyone;
that address seeds :func:`mmlspark_tpu.parallel.distributed.initialize`
(process_id = partitionId). Every task ends the fit holding the IDENTICAL
replicated model (the collective-fit invariant the fleet tests pin);
task 0 ships it back to the driver as one Arrow binary row.

Requires ``DataFrame.mapInArrow(..., barrier=True)`` (pyspark >= 3.5; the
test shim implements the same contract with real concurrent OS
processes). Use :func:`wrapDistributed`::

    from mmlspark_tpu.spark import wrapDistributed
    est = wrapDistributed(LightGBMClassifier(), numWorkers=4)
    model = est.fit(spark_df)          # fits ACROSS the executors
    scored = model.transform(spark_df)
"""

from __future__ import annotations

import io
import os
import tempfile
import zipfile
from typing import Optional


def stage_to_bytes(stage) -> bytes:
    """Serialize any registered stage (fitted models included) to a
    self-contained zip of its ``save_stage`` directory — the wire format
    for shipping estimators driver->executors and the fitted model back."""
    from ..core.serialize import save_stage
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "stage")
        save_stage(stage, path)
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for root, _, files in os.walk(path):
                for f in files:
                    full = os.path.join(root, f)
                    z.write(full, os.path.relpath(full, path))
        return buf.getvalue()


def stage_from_bytes(blob: bytes):
    from ..core.serialize import load_stage
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "stage")
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(path)
        return load_stage(path)


class BarrierFitTask:
    """The function object ``mapInArrow(..., barrier=True)`` runs on every
    partition. Deliberately a plain picklable class (no closures): real
    pyspark ships it via cloudpickle, the test shim via spawn+pickle.

    Protocol per task:
      1. ``BarrierTaskContext.allGather`` elects task 0's ``host:port`` as
         the JAX coordinator (the machine-list role,
         LightGBMUtils.scala:98-160).
      2. ``distributed.initialize(process_id=partitionId)`` — fleet
         rendezvous, bounded by MMLTPU_INIT_TIMEOUT.
      3. Partition batches -> native frame -> ``ShardedDataFrame`` shard;
         the wrapped estimator's fit runs its collective path.
      4. Task 0 yields the fitted model as a single binary Arrow row.
    """

    def __init__(self, est_blob: bytes, schema_blob: bytes):
        self.est_blob = est_blob
        self.schema_blob = schema_blob   # input Arrow schema (empty shards)

    def __call__(self, batches):
        import socket

        import pyarrow as pa
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        pid = ctx.partitionId()
        n = len(ctx.getTaskInfos())

        # task 0 binds the coordinator port on its own host; allGather is
        # the broadcast (replaces the reference's driver-socket
        # aggregation). The probe-close-rebind dance is racy by nature
        # (the reference's findFreePort is too); a stolen port fails the
        # rendezvous inside MMLTPU_INIT_TIMEOUT rather than hanging
        msg = ""
        if pid == 0:
            host = _task_host(ctx)
            with socket.socket() as s:
                s.bind((host, 0))
                msg = f"{host}:{s.getsockname()[1]}"
        coordinator = ctx.allGather(msg)[0]

        from ..parallel import distributed as dist
        if n > 1:
            dist.configure_xla_cache()
            try:
                dist.initialize(coordinator_address=coordinator,
                                num_processes=n, process_id=pid)
            except RuntimeError as e:
                # a REUSED executor python worker has often already run
                # JAX (e.g. a mapInArrow transform), and jax.distributed
                # cannot initialize after backends exist
                raise RuntimeError(
                    "distributed fit needs a fresh executor python worker "
                    "per barrier task (JAX's coordination service must "
                    "initialize before any other JAX work in the "
                    "process). Set spark.python.worker.reuse=false on the "
                    "SparkSession, or run the distributed fit before "
                    "executor-side transforms") from e
        try:
            from ..parallel.dataplane import ShardedDataFrame
            from . import _pdf_to_native

            schema = pa.ipc.read_schema(pa.py_buffer(self.schema_blob))
            got = list(batches)
            table = (pa.Table.from_batches(got) if got
                     else schema.empty_table())
            shard = ShardedDataFrame.fromLocal(_pdf_to_native(
                table.to_pandas()))
            model = stage_from_bytes(self.est_blob).fit(shard)
            if pid == 0:   # model is replicated; one task reports it
                yield pa.RecordBatch.from_arrays(
                    [pa.array([stage_to_bytes(model)], type=pa.binary())],
                    names=["model"])
        finally:
            if n > 1:
                dist.shutdown()


def _task_host(ctx) -> str:
    """Task 0's rendezvous host from the barrier context (executor address;
    loopback when Spark reports none — local[...] masters)."""
    try:
        addr = ctx.getTaskInfos()[ctx.partitionId()].address or ""
    except Exception:
        addr = ""
    host = addr.rsplit(":", 1)[0].strip("[]")
    return host if host and host != "localhost" else "127.0.0.1"


def fit_distributed(inner, sdf, num_workers: Optional[int] = None):
    """Run ``inner.fit`` as a barrier-stage job across ``sdf``'s partitions
    (coalesced/repartitioned to ``num_workers`` when given) and return the
    fitted native model. Every partition becomes one fleet process."""
    import pyarrow as pa
    from pyspark.sql import types as T

    if num_workers is None:
        # a post-shuffle frame can carry hundreds of partitions; a barrier
        # stage needs that many SIMULTANEOUS slots and that many fleet
        # processes, so default to the cluster's parallelism instead of
        # whatever partitioning the frame happens to have
        try:
            num_workers = min(sdf.rdd.getNumPartitions(),
                              sdf.sparkSession.sparkContext
                              .defaultParallelism)
        except Exception:
            num_workers = None     # shim / exotic sessions: keep as-is
    if num_workers is not None:
        try:
            have = sdf.rdd.getNumPartitions()
        except Exception:
            have = None
        if have != num_workers:
            # coalesce when shrinking (the reference's own move,
            # LightGBMClassifier.scala:35 — no shuffle); repartition
            # only when the fleet must GROW
            sdf = (sdf.coalesce(num_workers)
                   if have is not None and have > num_workers
                   else sdf.repartition(num_workers))

    # input schema, captured driver-side so EMPTY partitions can still
    # build a typed zero-row shard (uneven shards are a fleet invariant).
    # Prefer the catalyst-schema conversion (no Spark job); fall back to
    # sampling rows where the session can't convert (the shim)
    schema = None
    try:
        from pyspark.sql.pandas.types import to_arrow_schema
        schema = to_arrow_schema(sdf.schema)
    except Exception:
        pass
    if schema is None:
        head = sdf.limit(64)
        to_arrow = getattr(head, "toArrow", None)
        if callable(to_arrow):
            schema = to_arrow().schema
        else:
            schema = pa.Table.from_pandas(head.toPandas()).schema
    task = BarrierFitTask(stage_to_bytes(inner),
                          schema.serialize().to_pybytes())
    out_schema = T.StructType([T.StructField("model", T.BinaryType(), True)])
    try:
        res = sdf.mapInArrow(task, out_schema, barrier=True)
    except TypeError as e:
        raise RuntimeError(
            "distributed fit needs DataFrame.mapInArrow(..., barrier=True) "
            "(pyspark >= 3.5); upgrade pyspark or use wrap() for a "
            "driver-side fit") from e
    rows = res.toPandas()
    if len(rows) != 1:
        raise RuntimeError(
            f"barrier fit returned {len(rows)} model rows (expected exactly "
            f"1 from task 0) — did a task fail silently?")
    return stage_from_bytes(bytes(rows["model"].iloc[0]))


def wrapDistributed(stage, numWorkers: Optional[int] = None):
    """Wrap a TPU-native Estimator so ``fit`` runs ACROSS the Spark
    executors as one collective fleet (the reference's
    partitions-are-workers architecture) instead of collecting to the
    driver. ``transform`` on the result runs via mapInArrow as usual."""
    from ..core.pipeline import Estimator
    from . import SparkEstimator
    if not isinstance(stage, Estimator):
        raise TypeError(
            f"wrapDistributed expects an Estimator (got "
            f"{type(stage).__name__}); transformers have no fit to "
            f"distribute — use wrap()")
    return SparkEstimator(stage, distributed=True, numWorkers=numWorkers)
