"""PySpark adapter: the reference's front door, over the Arrow bridge.

MMLSpark is reached from Spark — codegen'd PySpark wrappers around every
stage (reference: PySparkWrapper.scala:33-160) and `spark.readImages`
implicits (Readers.scala:14-45). This module is that surface for the
TPU-native framework: any registered stage becomes a Spark-side stage
object (duck-typed ``fit``/``transform`` driven exactly like the
reference's wrappers) whose data crosses in COLUMNS through Arrow, never
Python rows. Compose multiple stages with ``mmlspark_tpu.Pipeline`` on
the native side (wrap the fitted pipeline once); ``pyspark.ml.Pipeline``
itself validates for its own Params subclasses and is not supported.

  * ``transform`` runs on the EXECUTORS via ``DataFrame.mapInArrow``: each
    Spark partition's record batches convert zero-copy-ish into the
    native :class:`mmlspark_tpu.DataFrame`, the wrapped stage transforms
    them, and the result flows back as Arrow (the mapPartitions shape the
    reference uses, CNTKModel.scala:255-261 — with the JVM<->Python wall
    crossed columnar instead of per-row).
  * ``fit`` collects the (driver-sized, as in the reference's own
    estimators) dataset to the driver as Arrow, fits the TPU-native
    estimator there, and returns the fitted model re-wrapped for Spark —
    or, via :func:`wrapDistributed`, runs as a barrier-stage job where
    every partition joins the JAX coordination service and the
    collective fit spans the executors (see ``spark/distributed.py``).
  * ``readImages(spark, path)`` mirrors the reference's reader implicit.
  * ``spark/streaming.py`` serves HTTP through a Spark-driven micro-batch
    loop over the worker-process fleet (the §3.5 readStream workflow).

pyspark is NOT a dependency of the framework — everything here imports it
lazily and raises a clear error when absent. The wrappers hold the
wrapped stage in ``.inner`` and forward every ``set*``/``get*`` chain, so
codegen'd param surfaces need no second binding layer:

    from mmlspark_tpu.spark import wrap
    from mmlspark_tpu.automl import TrainClassifier
    model = wrap(TrainClassifier().setLabelCol("income")).fit(spark_df)
    scored = model.transform(spark_df)        # executes via mapInArrow

Run the end-to-end demo with
``spark-submit --master 'local[*]' examples/spark_submit_101.py``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: rows sampled on the driver to infer a transform's output schema (the
#: stage runs once on this slice; Arrow needs the schema before executors
#: stream batches)
_SCHEMA_SAMPLE_ROWS = 32


def _pyspark():
    try:
        import pyspark  # noqa: F401
        import pyspark.ml
        import pyspark.sql
        return pyspark
    except ImportError as e:
        raise ImportError(
            "mmlspark_tpu.spark needs pyspark on the PYTHONPATH (it is an "
            "optional integration, not a dependency — `pip install "
            "pyspark` in the Spark-side environment, or run under "
            "spark-submit)") from e


# ---------------------------------------------------------------- conversion

def _pdf_to_native(pdf):
    """pandas (from Spark/Arrow) -> native DataFrame. Arrow list columns
    arrive as object columns of np/list values; vector-consuming stages
    expect float32 ndarray cells."""
    from ..core.dataframe import DataFrame
    cols = {}
    for c in pdf.columns:
        v = pdf[c].to_numpy()
        if v.dtype.kind == "O" and len(v) and isinstance(
                v[0], (list, tuple, np.ndarray)):
            out = np.empty(len(v), dtype=object)
            for i, item in enumerate(v):
                out[i] = np.asarray(item, dtype=np.float32)
            v = out
        cols[c] = v
    return DataFrame(cols)


def _native_to_arrow(df):
    """Native DataFrame -> pyarrow Table (object columns of ndarrays
    become Arrow lists; scalars pass through)."""
    import pyarrow as pa
    arrays, names = [], []
    for name in df.columns:
        v = df.col(name)
        names.append(name)
        if v.dtype.kind == "O":
            first = next((x for x in v if x is not None), None)
            if isinstance(first, np.ndarray):
                arrays.append(pa.array(
                    [None if x is None else np.asarray(
                        x, np.float32).tolist() for x in v],
                    type=pa.list_(pa.float32())))
                continue
            if isinstance(first, dict):
                # struct cells (image rows) become Arrow STRUCT arrays;
                # pyspark's from_arrow_schema maps them to Spark structs
                arrays.append(pa.array(v.tolist()))
                continue
            arrays.append(pa.array([None if x is None else str(x)
                                    for x in v]))
            continue
        arrays.append(pa.array(v))
    return pa.table(dict(zip(names, arrays)))


def _spark_df_to_native(sdf, limit: Optional[int] = None):
    """Spark DataFrame -> native DataFrame via the driver (Arrow path when
    available, pandas otherwise)."""
    if limit is not None:
        sdf = sdf.limit(limit)
    to_arrow = getattr(sdf, "toArrow", None)
    if callable(to_arrow):           # Spark 4 / shim fast path
        return _pdf_to_native(to_arrow().to_pandas())
    return _pdf_to_native(sdf.toPandas())


def _arrow_schema_to_spark(schema):
    """pyarrow schema -> Spark StructType (via pyspark's own converter
    when present; minimal manual mapping otherwise)."""
    try:
        from pyspark.sql.pandas.types import from_arrow_schema
        return from_arrow_schema(schema)
    except Exception:
        import pyarrow as pa
        from pyspark.sql import types as T
        simple = {pa.int64(): T.LongType(), pa.int32(): T.IntegerType(),
                  pa.float64(): T.DoubleType(), pa.float32(): T.FloatType(),
                  pa.bool_(): T.BooleanType(), pa.string(): T.StringType(),
                  pa.binary(): T.BinaryType()}
        fields = []
        for f in schema:
            if isinstance(f.type, pa.ListType):
                t = T.ArrayType(simple.get(f.type.value_type,
                                           T.DoubleType()))
            elif isinstance(f.type, pa.StructType):
                raise NotImplementedError(
                    f"column {f.name!r} is an Arrow struct and this "
                    f"pyspark lacks from_arrow_schema; flatten the struct "
                    f"(e.g. UnrollImage) before crossing to Spark")
            else:
                t = simple.get(f.type, T.StringType())
            fields.append(T.StructField(f.name, t, True))
        return T.StructType(fields)


# ------------------------------------------------------------------ wrappers

def _forward_params(self, name):
    """get*/set* forwarding so Spark-side code keeps the exact param-chain
    surface the codegen documents (set* chains return the WRAPPER)."""
    if name == "inner":                       # guard before __init__ runs
        raise AttributeError(name)
    attr = getattr(self.inner, name)
    if callable(attr) and name.startswith("set"):
        def chain(*a, **k):
            attr(*a, **k)
            return self
        return chain
    return attr


class SparkTransformer:
    """A TPU-native Transformer driven from Spark.

    Executor execution: ``mapInArrow`` streams each partition's record
    batches through the wrapped stage. The output schema is inferred on
    the driver by transforming a small sample (Arrow requires it up
    front)."""

    def __init__(self, inner):
        _pyspark()
        self.inner = inner
        self.uid = f"mmltpu_{type(inner).__name__}_{id(inner):x}"

    __getattr__ = _forward_params

    def _output_schema(self, sdf):
        sample = _spark_df_to_native(sdf, limit=_SCHEMA_SAMPLE_ROWS)
        if sample.count() == 0:
            raise ValueError(
                "cannot infer the transform's output schema from an EMPTY "
                "DataFrame (Arrow needs the schema before executors "
                "stream batches); give transform() at least one row")
        out = self.inner.transform(sample)
        return _native_to_arrow(out).schema

    def transform(self, sdf):
        import pyarrow as pa
        schema = self._output_schema(sdf)
        inner = self.inner

        def run(batches):
            for batch in batches:
                native = _pdf_to_native(
                    pa.Table.from_batches([batch]).to_pandas())
                if native.count() == 0:
                    continue
                out = _native_to_arrow(inner.transform(native))
                yield from out.cast(schema).to_batches()

        return sdf.mapInArrow(run, _arrow_schema_to_spark(schema))

    def save(self, path):
        self.inner.save(path)


class SparkEstimator:
    """A TPU-native Estimator driven from Spark. Two fit modes:

      * default — collects the (driver-sized, as in the reference's own
        estimators) training set as Arrow, fits natively on the driver.
      * ``distributed=True`` (see :func:`wrapDistributed`) — runs fit as a
        barrier-stage job across the executors; every partition joins the
        JAX coordination service and the collective fit spans the fleet
        (the reference's partitions-are-workers architecture,
        LightGBMClassifier.scala:35-47)."""

    def __init__(self, inner, distributed: bool = False,
                 numWorkers: Optional[int] = None):
        _pyspark()
        self.inner = inner
        self.distributed = distributed
        self.numWorkers = numWorkers
        self.uid = f"mmltpu_{type(inner).__name__}_{id(inner):x}"

    __getattr__ = _forward_params

    def fit(self, sdf):
        if self.distributed:
            from .distributed import fit_distributed
            return SparkTransformer(
                fit_distributed(self.inner, sdf, self.numWorkers))
        native = _spark_df_to_native(sdf)
        return SparkTransformer(self.inner.fit(native))

    def save(self, path):
        self.inner.save(path)


def wrap(stage):
    """The one entry point: wrap any registered TPU-native stage for
    Spark. Estimators wrap as :class:`SparkEstimator`, everything else as
    :class:`SparkTransformer` (the reference's codegen emitted one wrapper
    class per stage; the Param DSL lets one adapter serve all)."""
    from ..core.pipeline import Estimator
    if isinstance(stage, Estimator):
        return SparkEstimator(stage)
    return SparkTransformer(stage)


# ------------------------------------------------------------------ readers

def readImages(spark, path: str, recursive: bool = True,
               sampleRatio: float = 1.0, seed: int = 0):
    """``spark.readImages`` implicit analog (Readers.scala:14-45): decode
    images through the native C++ loader on the driver, hand Spark a
    DataFrame of (path, height, width, channels, data:binary)."""
    _pyspark()
    import pandas as pd

    from ..io import readImages as native_read
    df = native_read(path, recursive=recursive,
                     sample_ratio=sampleRatio, seed=seed)
    rows = df.col("image")
    pdf = pd.DataFrame({
        "path": [r["path"] for r in rows],
        "height": [int(r["height"]) for r in rows],
        "width": [int(r["width"]) for r in rows],
        "channels": [int(r["type"]) for r in rows],
        "data": [bytes(r["bytes"]) for r in rows],
    })
    return spark.createDataFrame(pdf)


# fit-across-the-executors entry point (module imports stay lazy for
# pyspark: distributed.py's top level is stdlib-only)
from .distributed import wrapDistributed  # noqa: E402

__all__ = ["wrap", "wrapDistributed", "SparkTransformer", "SparkEstimator",
           "readImages"]
