"""Plotting helpers (reference: src/plot/src/main/python/plot.py).

The reference ships two matplotlib helpers — an annotated, row-normalized
confusion matrix and an ROC curve — that pull columns out of a Spark frame.
Here they pull from the columnar DataFrame and compute the statistics with
the framework's own numpy metrics (automl.metrics) instead of sklearn.
matplotlib is imported lazily so headless / minimal environments that never
plot pay nothing.
"""

from __future__ import annotations

import numpy as np

from .core.dataframe import DataFrame
from .automl.metrics import confusion_matrix as _confusion_counts
from .automl.metrics import roc_points


def _column(df, name):
    if isinstance(df, DataFrame):
        return np.asarray(df.col(name))
    return np.asarray(df[name])  # pandas or dict-like


def confusionMatrix(df, y_col: str, y_hat_col: str, labels=None, ax=None):
    """Row-normalized confusion-matrix heatmap with per-cell counts and an
    accuracy banner (reference plot.py:17-43)."""
    import matplotlib.pyplot as plt

    y = _column(df, y_col)
    y_hat = _column(df, y_hat_col)
    accuracy = float(np.mean(y == y_hat))
    # map arbitrary (possibly string) labels to indices for the count matrix;
    # when `labels` names the class values themselves, its ORDER defines the
    # matrix axes (absent classes get zero rows, sklearn/Spark-style); when
    # it's display text of matching length, it only renames the ticks
    uniq = np.unique(np.concatenate([y, y_hat]))
    if labels is not None:
        if set(labels) >= set(uniq.tolist()):
            uniq = np.asarray(labels)
        elif len(labels) != len(uniq):
            raise ValueError(f"labels {list(labels)} neither covers the data "
                             f"values {uniq.tolist()} nor matches their count")
    lut = {v: i for i, v in enumerate(uniq)}
    y_idx = np.array([lut[v] for v in y], dtype=np.int64)
    yh_idx = np.array([lut[v] for v in y_hat], dtype=np.int64)
    cm = _confusion_counts(y_idx, yh_idx)
    if cm.shape[0] < len(uniq):       # classes listed but absent from data
        k = len(uniq)
        cm = np.pad(cm, ((0, k - cm.shape[0]), (0, k - cm.shape[1])))
    row_sums = cm.sum(axis=1, keepdims=True)
    cmn = cm.astype(float) / np.maximum(row_sums, 1)

    if ax is None:
        ax = plt.gca()
    if labels is None:
        labels = [str(v) for v in uniq]
    ticks = np.arange(len(labels))
    im = ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    ax.set_xticks(ticks, labels=labels)
    ax.set_yticks(ticks, labels=labels)
    ax.set_title(f"Accuracy = {accuracy * 100:.1f}%")
    thresh = 0.1
    for i in range(cm.shape[0]):
        for j in range(cm.shape[1]):
            ax.text(j, i, str(int(cm[i, j])), ha="center",
                    color="white" if cmn[i, j] > thresh else "black")
    ax.figure.colorbar(im, ax=ax)
    ax.set_xlabel("Predicted Label")
    ax.set_ylabel("True Label")
    return ax


def roc(df, y_col: str, y_hat_col: str, thresh: float = 0.5, ax=None):
    """ROC curve: y binarized at ``thresh``, scores from ``y_hat_col``
    (reference plot.py:45-60)."""
    import matplotlib.pyplot as plt

    y = (_column(df, y_col).astype(float) > thresh).astype(int)
    score = _column(df, y_hat_col).astype(float)
    fpr, tpr = roc_points(y, score)
    if ax is None:
        ax = plt.gca()
    ax.plot(fpr, tpr)
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
    ax.set_xlabel("False Positive Rate")
    ax.set_ylabel("True Positive Rate")
    return ax
