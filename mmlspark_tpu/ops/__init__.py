from . import image_ops, text_ops
from .image_stages import ImageSetAugmenter, ImageTransformer, UnrollImage
from .text_stages import TextFeaturizer, TextFeaturizerModel
from .word2vec import Word2Vec, Word2VecModel

__all__ = ["image_ops", "text_ops", "ImageTransformer", "UnrollImage",
           "ImageSetAugmenter", "TextFeaturizer", "TextFeaturizerModel",
           "Word2Vec", "Word2VecModel"]
