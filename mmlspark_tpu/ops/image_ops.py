"""Batched image ops as pure jnp functions on NHWC float32 arrays.

TPU-first redesign of the reference's OpenCV per-row Mat pipeline
(reference: src/image-transformer/src/main/scala/ImageTransformer.scala:21-210
— ResizeImage:34, CropImage:66, ColorFormat:92, Flip:111, Blur:136,
Threshold:159, GaussianKernel:185). The reference applies OpenCV to one image
at a time inside a row UDF; here every op is a vectorized function over a
whole batch (N,H,W,C), so XLA fuses the chain and the convs (blur/gaussian)
tile onto the MXU. Stages group rows by shape and jit one program per shape
bucket (static shapes for XLA).

Convention: images are float32 in [0,255], channel order as stored (OpenCV
BGR for decoded files). Flip codes match OpenCV: 0=up/down, 1=left/right,
-1=both.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def resize(batch: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Bilinear resize, matching OpenCV resize's default interpolation."""
    n, _, _, c = batch.shape
    return jax.image.resize(batch, (n, height, width, c), method="bilinear")


def crop(batch: jnp.ndarray, x: int, y: int, height: int, width: int) -> jnp.ndarray:
    """Crop with OpenCV Rect(x, y, w, h) semantics — x is the column offset,
    y the row offset (reference CropImage builds Rect(x, y, width, height)).
    Like OpenCV's Mat(image, rect), an out-of-bounds rect is an error rather
    than a silent truncation."""
    _, h, w, _ = batch.shape
    if height <= 0 or width <= 0 or x < 0 or y < 0 \
            or y + height > h or x + width > w:
        raise ValueError(f"crop rect (x={x}, y={y}, h={height}, w={width}) "
                         f"exceeds image bounds {h}x{w}")
    return batch[:, y:y + height, x:x + width, :]


def flip(batch: jnp.ndarray, flip_code: int) -> jnp.ndarray:
    if flip_code == 0:
        return jnp.flip(batch, axis=1)
    if flip_code == 1:
        return jnp.flip(batch, axis=2)
    if flip_code == -1:
        return jnp.flip(batch, axis=(1, 2))
    raise ValueError(f"flipCode must be 0, 1 or -1, got {flip_code}")


def color_format(batch: jnp.ndarray, conversion: str) -> jnp.ndarray:
    """Channel-order / colorspace conversion. Supported: BGR2RGB, RGB2BGR,
    BGR2GRAY, RGB2GRAY, GRAY2BGR, GRAY2RGB."""
    conv = conversion.upper()
    if conv in ("BGR2RGB", "RGB2BGR"):
        return batch[..., ::-1]
    if conv in ("BGR2GRAY", "RGB2GRAY"):
        # ITU-R BT.601 luma weights, as OpenCV uses
        w = jnp.array([0.114, 0.587, 0.299] if conv == "BGR2GRAY"
                      else [0.299, 0.587, 0.114], dtype=batch.dtype)
        return jnp.tensordot(batch, w, axes=[[3], [0]])[..., None]
    if conv in ("GRAY2BGR", "GRAY2RGB"):
        return jnp.repeat(batch, 3, axis=3)
    raise ValueError(f"unsupported color conversion {conversion!r}")


def _depthwise_conv(batch: jnp.ndarray, kernel2d: jnp.ndarray) -> jnp.ndarray:
    """Depthwise SAME conv with reflect-101 padding (OpenCV's default border)."""
    kh, kw = kernel2d.shape
    _, h, w, c = batch.shape
    ph, pw = kh // 2, kw // 2
    # reflect-101 needs pad < dim; fall back to edge padding for tiny images
    # (OpenCV never crashes on small image / large kernel combinations)
    mode = "reflect" if max(ph, kh - 1 - ph) < h and max(pw, kw - 1 - pw) < w else "edge"
    padded = jnp.pad(batch, ((0, 0), (ph, kh - 1 - ph), (pw, kw - 1 - pw), (0, 0)),
                     mode=mode)
    rhs = jnp.broadcast_to(kernel2d[:, :, None, None].astype(batch.dtype),
                           (kh, kw, 1, c))
    return jax.lax.conv_general_dilated(
        padded, rhs, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)


def blur(batch: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Normalized box filter. The reference passes ``new Size(height, width)``
    to Imgproc.blur, and OpenCV Size is (width, height) — so the reference's
    ``height`` param is the kernel's horizontal extent. Mirrored here:
    kernel rows = width param, kernel cols = height param."""
    k = jnp.full((int(width), int(height)), 1.0 / (int(height) * int(width)),
                 dtype=batch.dtype)
    return _depthwise_conv(batch, k)


def gaussian_kernel_1d(aperture: int, sigma: float) -> np.ndarray:
    """OpenCV getGaussianKernel: if sigma<=0, sigma = 0.3*((ksize-1)*0.5-1)+0.8."""
    if sigma <= 0:
        sigma = 0.3 * ((aperture - 1) * 0.5 - 1) + 0.8
    xs = np.arange(aperture, dtype=np.float64) - (aperture - 1) / 2.0
    k = np.exp(-(xs ** 2) / (2.0 * sigma ** 2))
    return (k / k.sum()).astype(np.float32)


def gaussian_blur(batch: jnp.ndarray, aperture: int, sigma: float) -> jnp.ndarray:
    """The reference applies a 1-D gaussian column kernel via filter2D
    (GaussianKernel stage): convolve along H only. We match that."""
    k1 = jnp.asarray(gaussian_kernel_1d(aperture, sigma))
    return _depthwise_conv(batch, k1[:, None])


def threshold(batch: jnp.ndarray, thresh: float, max_val: float,
              threshold_type: str = "binary") -> jnp.ndarray:
    """OpenCV threshold types on batched images."""
    t = threshold_type.lower()
    if t == "binary":
        return jnp.where(batch > thresh, max_val, 0.0).astype(batch.dtype)
    if t == "binary_inv":
        return jnp.where(batch > thresh, 0.0, max_val).astype(batch.dtype)
    if t == "trunc":
        return jnp.minimum(batch, thresh).astype(batch.dtype)
    if t == "tozero":
        return jnp.where(batch > thresh, batch, 0.0).astype(batch.dtype)
    if t == "tozero_inv":
        return jnp.where(batch > thresh, 0.0, batch).astype(batch.dtype)
    raise ValueError(f"unknown threshold type {threshold_type!r}")


def unroll(batch: jnp.ndarray) -> jnp.ndarray:
    """(N,H,W,C) -> (N, C*H*W) in CHW order — the layout deep-net inputs
    expect; replaces the reference's per-pixel loop with signed-byte fix-up
    (UnrollImage.scala:18-43) by a transpose+reshape XLA handles for free."""
    n = batch.shape[0]
    return jnp.transpose(batch, (0, 3, 1, 2)).reshape(n, -1)


# op registry: name -> (fn, param names); drives ImageTransformer stage lists
OP_TABLE = {
    "resize": (resize, ("height", "width")),
    "crop": (crop, ("x", "y", "height", "width")),
    "flip": (flip, ("flipCode",)),
    "colorformat": (color_format, ("format",)),
    "blur": (blur, ("height", "width")),
    "gaussiankernel": (gaussian_blur, ("appertureSize", "sigma")),
    "threshold": (threshold, ("threshold", "maxVal", "type")),
}


@functools.partial(jax.jit, static_argnums=(1,))
def _run_chain(batch: jnp.ndarray, chain: tuple) -> jnp.ndarray:
    """chain: tuple of (opname, tuple(sorted param items)) — hashable so the
    whole op pipeline compiles to ONE fused XLA program per shape bucket."""
    for name, items in chain:
        fn, argnames = OP_TABLE[name]
        kw = dict(items)
        batch = fn(batch, *[kw[a] for a in argnames])
    return batch


def apply_op_chain(batch_np: np.ndarray, ops: list[dict]) -> np.ndarray:
    """Apply a list of {'op': name, **params} dicts to an NHWC uint8/float
    batch; returns float32. Host->device once, fused chain, device->host once."""
    chain = tuple((d["op"], tuple(sorted((k, v) for k, v in d.items()
                                         if k != "op"))) for d in ops)
    x = jnp.asarray(np.asarray(batch_np, dtype=np.float32))
    return np.asarray(_run_chain(x, chain))
