"""Hand-written Pallas TPU kernels for the framework's hot ops.

Two places where a custom kernel beats what XLA emits from jnp-level code
(everything else in the framework deliberately leans on XLA fusion):

  * ``flash_attention`` — attention with the online-softmax recurrence run
    block-by-block in VMEM: the (Tq, Tk) score matrix never touches HBM, the
    QK^T and PV matmuls hit the MXU per (block_q, block_k) tile, and softmax
    statistics live in VMEM scratch across the KV grid dimension. This is the
    single-chip engine under the long-context path; ring/Ulysses (parallel/
    sequence.py) shard sequence across chips and can call this per shard.
  * the GBDT histogram build (the op LightGBM does in native C++ with a
    socket all-reduce, reference TrainUtils.scala:70-77) ships three
    backends: ``compare_reduce_histogram`` (scatter-free per-bin masked
    sums — the fastest on TPU for uint8 id spaces, 0.13 s per 1M x 28
    build), XLA ``segment_histogram`` (the general case), and the
    original ``histogram_fused`` Pallas one-hot-matmul kernel. Round-4
    SYNCED measurements corrected round 1's call: the one-hot staging
    makes the Pallas kernel HBM/VMEM-bound (4.0 s per 1M x 28 build vs
    segment's 0.50 s), so the engine's auto policy now picks
    compare-reduce/segment; the kernel stays selectable for A/B.

Both kernels run in interpret mode off-TPU (CI runs them on the CPU mesh);
``_interpret()`` flips automatically so the same call sites work everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------ flash attention

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, block_q: int, block_k: int, causal: bool, scale: float,
                  seq_k: int):
    """Grid = (BH, num_q_blocks, num_k_blocks); KV innermost so the softmax
    state in scratch carries across the k dimension for one q block. Also
    emits the row logsumexp (the residual the backward kernels need)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: skip blocks strictly above the diagonal
    run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(run)
    def _compute():
        # matmul operands stay in the input dtype (bf16 on chip): the MXU
        # runs bf16xbf16->f32 at full rate, while f32 inputs force slow
        # multi-pass emulation; accumulation is f32 either way
        q = q_ref[0]                                   # (bq, D)
        k = k_ref[0]                                   # (bk, D)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 1)
        valid = kpos < seq_k                            # mask KV padding
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 0)
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:, 0]                            # (bq,)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(m_new[:, None] <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        m_ref[:, 0] = m_new
        acc_ref[:] = (acc_ref[:] * corr[:, None]
                      + jnp.dot(p.astype(v.dtype), v,
                                preferred_element_type=jnp.float32))

    @pl.when(ki == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0], 1e-30)[:, None]
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))
        lse_ref[0] = jnp.where(m_ref[:, 0] <= NEG_INF / 2, NEG_INF,
                               lse)[:, None]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                         dq_ref, acc_ref, *, block_q: int, block_k: int,
                         causal: bool, scale: float, seq_k: int):
    """dq = (P * (dO V^T - D)) K * scale, accumulated over KV blocks.
    Grid = (BH, num_q_blocks, num_k_blocks), KV innermost."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]                 # native dtype: full-rate MXU (see fwd)
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]                           # (bq,)
        dvec = dvec_ref[0][:, 0]                         # (bq,)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 1)
        valid = kpos < seq_k
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 0)
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(lse[:, None] <= NEG_INF / 2, 0.0, p)   # padded q rows
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dvec[:, None])
        acc_ref[:] += jnp.dot(ds.astype(k.dtype), k,
                              preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                          block_k: int, causal: bool, scale: float,
                          seq_k: int):
    """dv = P^T dO; dk = (P * (dO V^T - D))^T Q * scale, accumulated over
    Q blocks. Grid = (BH, num_k_blocks, num_q_blocks), Q innermost."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]                 # native dtype: full-rate MXU (see fwd)
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        dvec = dvec_ref[0][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 1)
        valid = kpos < seq_k
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 0)
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(lse[:, None] <= NEG_INF / 2, 0.0, p)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, D)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dvec[:, None])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, D)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = None, block_k: int = None,
                    interpret=None):
    """FlashAttention on TPU. q/k/v: (B, T, H, D) -> (B, T, H, D).

    The score matrix stays in VMEM tiles; HBM traffic is O(T*D) instead of
    O(T^2). Sequence dims are padded to block multiples internally (padded
    keys masked, padded queries sliced off).

    Differentiable: pallas_call has no JVP, so a custom VJP pairs this
    forward with hand-written Pallas backward kernels (dq and dk/dv passes
    over the saved row logsumexp) — O(T) memory in both directions, the full
    FlashAttention recurrence.

    Default blocks are head-dim and mask aware (``block_q/block_k=None``):
    D >= 128 picks 512x1024 causal / 1024x2048 non-causal, smaller D
    keeps 1024x1024 — from strict chained-loop sweeps on v5e. At (8,4096,4,128) causal (same H*D as the round-4
    (8,4096,8,64) shape): 512x512 17.3 TF/s, **512x1024 30.8**, 1024x512
    26.3, 1024x1024 21.8, 2048x512 24.8 — the D=128 contraction fills the
    MXU's 128-deep systolic array where D=64 half-fills it (19.5 TF/s at
    its best blocks), a 1.58x end-to-end gain, which is why transformer
    configs in this repo default to head_dim 128. Blocks clamp to the
    sequence length for short inputs.

    Round-4 re-measurement with a STRICTER harness (20 chained calls in one
    fori_loop, single scalar sync — the per-call numbers above let the
    tunnel's async queue flatter throughput): 19.5 TF/s causal / 28.8
    non-causal at 1024x1024, vs 17.0 TF/s for jax's own
    pallas.ops.tpu.flash_attention on the identical shape/blocks/harness —
    this kernel is ~15% faster than the reference implementation and at
    the practical ceiling for head_dim 64 (the QK^T contraction half-fills
    the 128-deep MXU; packing two heads into one contraction would sum
    cross-head scores, so the structural fix is model-level: prefer
    head_dim 128 on TPU). Variants measured and rejected as no faster:
    2-heads-per-grid-step blocks, interior-block mask skipping,
    dimension_semantics hints (see BASELINE.md round-4 row).
    """
    out, _ = _flash_attention_fwd_impl(q, k, v, causal, scale, block_q,
                                       block_k, interpret)
    return out


def _flash_attention_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_attention_fwd_impl(q, k, v, causal, scale, block_q,
                                         block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(causal, scale, block_q, block_k, interpret,
                         residuals, g):
    q, k, v, out, lse = residuals
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    interpret = _interpret() if interpret is None else interpret
    block_q, block_k = _default_blocks(D, causal, block_q, block_k)
    # the (bq, bk) temporaries (S, P, dP, dS) quadruple the block footprint
    # vs the forward — halve the blocks to stay inside scoped VMEM
    block_q = min(block_q, 512, max(8, Tq))
    block_k = min(block_k, 512, max(8, Tk))

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    pq = (-Tq) % block_q
    pk = (-Tk) % block_k
    qb = jnp.pad(to_bh(q), ((0, 0), (0, pq), (0, 0)))
    kb = jnp.pad(to_bh(k), ((0, 0), (0, pk), (0, 0)))
    vb = jnp.pad(to_bh(v), ((0, 0), (0, pk), (0, 0)))
    dob = jnp.pad(to_bh(g).astype(q.dtype), ((0, 0), (0, pq), (0, 0)))
    # D_i = rowsum(dO * O) — cheap elementwise residual
    dvec = jnp.sum(to_bh(g).astype(jnp.float32)
                   * to_bh(out).astype(jnp.float32), axis=-1)
    dvec = jnp.pad(dvec, ((0, 0), (0, pq)))[..., None]   # (BH, Tq_pad, 1)
    lse_b = jnp.pad(lse, ((0, 0), (0, pq)),
                    constant_values=NEG_INF)[..., None]
    nq = qb.shape[1] // block_q
    nk = kb.shape[1] // block_k

    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  scale=scale, seq_k=Tk)
    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    qrow = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(B * H, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, qrow, qrow],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(qb.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse_b, dvec)

    # dkv grid: K blocks outer, Q blocks inner (accumulators live per-K)
    qspec_i = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0))
    kspec_i = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))
    qrow_i = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(B * H, nk, nq),
        in_specs=[qspec_i, kspec_i, kspec_i, qspec_i, qrow_i, qrow_i],
        out_specs=(kspec_i, kspec_i),
        out_shape=(jax.ShapeDtypeStruct(kb.shape, k.dtype),
                   jax.ShapeDtypeStruct(vb.shape, v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse_b, dvec)

    def from_bh(x, T):
        return x[:, :T].reshape(B, H, T, D).transpose(0, 2, 1, 3)

    return (from_bh(dq, Tq).astype(q.dtype),
            from_bh(dk, Tk).astype(k.dtype),
            from_bh(dv, Tk).astype(v.dtype))


flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def _default_blocks(D, causal, block_q, block_k):
    """Head-dim- and mask-aware default tiles (flash_attention docstring
    has the measured sweeps): at D >= 128 the causal path wants a half q
    block (512x1024, 30.8 TF/s) while the non-causal path wants a deep k
    block (1024x2048, 51.8 TF/s); smaller D keeps 1024x1024."""
    if D >= 128:
        dq, dk = (512, 1024) if causal else (1024, 2048)
    else:
        dq, dk = 1024, 1024
    return block_q or dq, block_k or dk


def _flash_attention_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                              interpret):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    interpret = _interpret() if interpret is None else interpret
    block_q, block_k = _default_blocks(D, causal, block_q, block_k)
    block_q = min(block_q, max(8, Tq))
    block_k = min(block_k, max(8, Tk))

    def to_bh(x):     # (B, T, H, D) -> (B*H, T, D)
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], x.shape[1], D)

    pq = (-Tq) % block_q
    pk = (-Tk) % block_k
    qb = jnp.pad(to_bh(q), ((0, 0), (0, pq), (0, 0)))
    kb = jnp.pad(to_bh(k), ((0, 0), (0, pk), (0, 0)))
    vb = jnp.pad(to_bh(v), ((0, 0), (0, pk), (0, 0)))
    nq = qb.shape[1] // block_q
    nk = kb.shape[1] // block_k

    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               seq_k=Tk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))),
        out_shape=(jax.ShapeDtypeStruct(qb.shape, q.dtype),
                   jax.ShapeDtypeStruct(qb.shape[:2] + (1,), jnp.float32)),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    out = out[:, :Tq].reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    return out, lse[:, :Tq, 0]


# ------------------------------------------------------------ GBDT histogram

def segment_histogram(bins, grad, hess, n_bins: int):
    """Flat XLA scatter-add histograms (the portable non-Pallas path).

    bins (N, F) int32 in [0, n_bins); grad/hess (N,) f32.
    Returns (hist_g, hist_h), each (F, n_bins) f32.
    """
    N, F = bins.shape
    feat_ids = jnp.arange(F, dtype=jnp.int32)
    seg = (feat_ids[None, :] * n_bins + bins.astype(jnp.int32)).reshape(-1)
    bcast = lambda v: jnp.broadcast_to(
        v.astype(jnp.float32)[:, None], (N, F)).reshape(-1)
    hg = jax.ops.segment_sum(bcast(grad), seg, num_segments=F * n_bins)
    hh = jax.ops.segment_sum(bcast(hess), seg, num_segments=F * n_bins)
    return hg.reshape(F, n_bins), hh.reshape(F, n_bins)

def compare_reduce_histogram(bins, grad, hess, n_bins: int):
    """Per-bin compare-and-reduce histograms: ``lax.map`` over the bin ids,
    each step one masked sum over the whole (N, F) matrix — pure VPU
    elementwise + reduction, no scatter. HBM-bound at ~N*F bytes per bin
    pass, which beats segment_sum's sort/scatter by 4-10x on TPU when the
    bin-id space fits uint8 (measured v5e, 28 features x 1M rows:
    0.13 s vs 0.56 s at 256 ids — but 1.05 s vs 0.50 s already at 512
    ids, where the id matrix must widen to int32 and the per-id HBM pass
    quadruples). Callers route here ONLY when n_bins <= 256 (the GBDT
    engine: single-node builds — the root level of every iteration).

    Same contract as segment_histogram: bins (N, F) int in [0, n_bins);
    returns ((F, n_bins), (F, n_bins)) f32.
    """
    assert n_bins <= 256, "compare-reduce needs a uint8 id space"
    bins = bins.astype(jnp.uint8)

    def one(b):
        m = bins == b
        return (jnp.where(m, grad[:, None], 0.0).sum(0),
                jnp.where(m, hess[:, None], 0.0).sum(0))

    hg, hh = jax.lax.map(one, jnp.arange(n_bins, dtype=jnp.uint8))
    return hg.T, hh.T


def _split3_bf16(a):
    """Exact 3-way bf16 decomposition of f32: a == hi + mid + lo (each
    extraction residual is an exact fp subtraction; 3 x 8 mantissa bits
    cover f32's 24). Lets the MXU run full-rate bf16 passes on f32 data
    with f32-level accuracy — the one-hot operand is exactly representable
    in bf16 already."""
    hi = a.astype(jnp.bfloat16)
    r1 = a - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    r2 = r1 - mid.astype(jnp.float32)
    return hi, mid, r2.astype(jnp.bfloat16)


def _node_hist_kernel(bins_ref, node_ref, g_ref, h_ref, hg_ref, hh_ref, *,
                      n_nodes: int, feat_chunk: int, width: int):
    """Grid = (feature_chunks, row_blocks), rows innermost so the output
    block (one feature chunk's histograms) stays VMEM-resident across the
    whole row sweep. Everything is laid out rows-along-lanes: the node
    one-hot, the masked grad/hess operand A, and the per-feature bin
    one-hot B are all built broadcast-natural, and the MXU contraction
    runs over the shared lane (row) dimension — no transposes anywhere."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        hg_ref[:] = jnp.zeros_like(hg_ref)
        hh_ref[:] = jnp.zeros_like(hh_ref)

    node = node_ref[:].astype(jnp.int32)                    # (bn,)
    bn = node.shape[0]
    g = g_ref[:]                                            # (bn,) f32
    h = h_ref[:]
    node1h = (node[None, :] == jax.lax.broadcasted_iota(
        jnp.int32, (n_nodes, bn), 0))                       # (n_nodes, bn)
    ag = jnp.where(node1h, g[None, :], 0.0)
    ah = jnp.where(node1h, h[None, :], 0.0)
    a = jnp.concatenate([ag, ah], axis=0)                   # (2n, bn) f32
    hi, mid, lo = _split3_bf16(a)
    A = jnp.concatenate([hi, mid, lo], axis=0)              # (6n, bn) bf16

    for fc in range(feat_chunk):
        bf = bins_ref[fc, :].astype(jnp.int32)              # (bn,)
        B = (bf[None, :] == jax.lax.broadcasted_iota(
            jnp.int32, (width, bn), 0)).astype(jnp.bfloat16)
        out = jax.lax.dot_general(
            A, B, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (6n, width)
        out = out.reshape(3, 2 * n_nodes, width).sum(axis=0)
        hg_ref[fc * n_nodes:(fc + 1) * n_nodes, :] += out[:n_nodes]
        hh_ref[fc * n_nodes:(fc + 1) * n_nodes, :] += out[n_nodes:]


def mxu_node_histogram(bins_t, node, g, h, *, n_nodes: int,
                       n_bins: int = 256, block_n: int = 2048,
                       feat_chunk: int = 8, interpret=None):
    """Per-(node, feature, bin) grad/hess histograms as MXU matmuls.

    bins_t (F, N) int — the TRANSPOSED bin matrix; node (N,) int32 row ->
    tree-node ids in [0, n_nodes) (out-of-range rows contribute nothing);
    g/h (N,) f32. Returns (hg, hh), each (n_nodes, F, n_bins) f32.

    This is the round-5 replacement for the whole histogram-backend zoo on
    TPU: per feature it builds a 256-wide bin one-hot in VMEM (bf16 —
    exactly representable) and contracts it against the node-masked
    grad/hess rows, so the id space never widens with the node count (the
    node dimension rides in the matmul M axis, not the one-hot width —
    the flaw that made both segment_sum and the v1 one-hot kernel scale
    with n_nodes * n_bins ids). f32 accuracy comes from a 3-way bf16
    split of the grad operand (see _split3_bf16); measured max relative
    error vs segment_sum is ~1e-6 at 1M rows.

    Measured on v5e (1M x 28, chained-loop scalar-sync, round 5):
    19.1 ms (n_nodes=1) / 19.4 ms (2) / 28.0 ms (16) per build vs
    segment_sum's 384-425 ms and compare-reduce's 25.7 ms (single-node
    only) — and, unlike segment_sum's sort, it is LINEAR in N, which
    removes the 10M-row super-linearity (BASELINE round-4 row). The
    gather-compaction alternative (nonzero(size=N/2) + row gather +
    half-size build) measured 12.6 ms for the index build alone, so
    compacting the smaller child LOSES to just histogramming all rows
    through the MXU; histogram subtraction is likewise dominated because
    a build's cost is independent of how many nodes it covers.

    The reference hands this op to native LightGBM's C++ histogram loop
    per Spark partition (TrainUtils.scala:63-77); here it is one Pallas
    kernel per boosting level with the tree_learner collectives applied
    by the caller.
    """
    F, N = bins_t.shape
    interpret = _interpret() if interpret is None else interpret
    assert n_nodes <= 256, "node axis rides the matmul M dim; cap at 256"
    width = max(128, -(-n_bins // 128) * 128)
    # VMEM budget: the A operand ((6*n_nodes, block_n) bf16 + its f32
    # staging) scales with n_nodes — shrink the row block as the node
    # count grows so deep levels stay under the ~16 MB scoped limit
    # instead of failing Mosaic allocation. feat_chunk stays 8: Mosaic
    # requires the bins block's sublane dim be 8-divisible (or equal F).
    block_n = min(block_n, max(128, (2 << 20) // (12 * n_nodes) // 128 * 128))
    block_n = min(block_n, max(128, -(-N // 128) * 128))
    feat_chunk = min(feat_chunk, F)
    pad_n = (-N) % block_n
    if pad_n:
        # padded rows carry g = h = 0 -> no histogram contribution
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad_n)))
        node = jnp.pad(node, (0, pad_n))
        g = jnp.pad(g, (0, pad_n))
        h = jnp.pad(h, (0, pad_n))
    pad_f = (-F) % feat_chunk
    if pad_f:   # junk rows in the padded feature slots; sliced off below
        bins_t = jnp.pad(bins_t, ((0, pad_f), (0, 0)))
    F_pad = F + pad_f
    nfc = F_pad // feat_chunk
    nblk = bins_t.shape[1] // block_n

    kernel = functools.partial(_node_hist_kernel, n_nodes=n_nodes,
                               feat_chunk=feat_chunk, width=width)
    hg, hh = pl.pallas_call(
        kernel,
        grid=(nfc, nblk),
        in_specs=[
            pl.BlockSpec((feat_chunk, block_n), lambda j, i: (j, i)),
            pl.BlockSpec((block_n,), lambda j, i: (i,)),
            pl.BlockSpec((block_n,), lambda j, i: (i,)),
            pl.BlockSpec((block_n,), lambda j, i: (i,)),
        ],
        out_specs=(
            pl.BlockSpec((feat_chunk * n_nodes, width), lambda j, i: (j, 0)),
            pl.BlockSpec((feat_chunk * n_nodes, width), lambda j, i: (j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((F_pad * n_nodes, width), jnp.float32),
            jax.ShapeDtypeStruct((F_pad * n_nodes, width), jnp.float32),
        ),
        interpret=interpret,
    )(bins_t.astype(jnp.int32), node.astype(jnp.int32),
      g.astype(jnp.float32), h.astype(jnp.float32))
    hg = hg.reshape(F_pad, n_nodes, width)[:F, :, :n_bins]
    hh = hh.reshape(F_pad, n_nodes, width)[:F, :, :n_bins]
    return hg.transpose(1, 0, 2), hh.transpose(1, 0, 2)


# ------------------------------------------------- GBDT quantized predict

#: pallas predict eligibility caps: the per-tree traversal unrolls one
#: compare-select per internal node (level-wise) or split round (leaf-
#: wise) plus one per leaf — past these the unroll outgrows what Mosaic
#: schedules well, and the engine's dense path (which streams past the
#: same bound via its test-table guards) is the right tool anyway.
PREDICT_QUANT_MAX_NODES = 127     # 2^depth - 1  (mirrors engine's cap)
PREDICT_QUANT_MAX_LEAVES = 128


def _gbdt_quant_lvl_kernel(feat_ref, thr_ref, leaf_ref, bins_ref, out_ref,
                           *, n_trees: int, n_class: int, depth: int):
    """Grid = (row_blocks,). One row block's uint8 bins stay VMEM-resident
    while EVERY tree of the ensemble walks it: per level the node's
    feature row is one dynamic-sublane VMEM load and the heap descent is
    a pure compare-select chain (VPU elementwise — no (nodes, n) test
    table ever exists, in VMEM or HBM). The tree tables ride the scalar-
    prefetch path (SMEM), so feature/threshold lookups are scalar reads
    indexed by the fori_loop tree counter."""
    bn = out_ref.shape[1]
    n_leaves = 2 ** depth

    def tree_body(t, acc):
        for k in range(n_class):
            pos = jnp.zeros((bn,), jnp.int32)
            for level in range(depth):
                off = 2 ** level - 1
                go_right = jnp.zeros((bn,), jnp.bool_)
                for j in range(2 ** level):
                    f = feat_ref[t, k, off + j]
                    thr = thr_ref[t, k, off + j]
                    row = pl.load(bins_ref,
                                  (pl.ds(f, 1), slice(None)))[0]
                    test = row.astype(jnp.int32) > thr
                    go_right = jnp.where(pos == j, test, go_right)
                pos = pos * 2 + go_right.astype(jnp.int32)
            contrib = jnp.zeros((bn,), jnp.float32)
            for leaf_id in range(n_leaves):
                contrib = jnp.where(pos == leaf_id,
                                    leaf_ref[t, k, leaf_id], contrib)
            acc = acc.at[k].add(contrib)
        return acc

    out_ref[:] = jax.lax.fori_loop(
        0, n_trees, tree_body, jnp.zeros((n_class, bn), jnp.float32))


def _gbdt_quant_lw_kernel(split_ref, feat_ref, thr_ref, leaf_ref, bins_ref,
                          out_ref, *, n_trees: int, n_class: int,
                          n_rounds: int, n_leaves: int):
    """Leaf-wise twin: replay the split sequence (round r splits leaf
    ``split_ref[t,k,r]``, right child becomes leaf r+1) as compare-
    selects over the VMEM-resident row block. A no-op round stores
    split_leaf -1, which can never equal a (>= 0) position — the skip
    needs no branch."""
    bn = out_ref.shape[1]

    def tree_body(t, acc):
        for k in range(n_class):
            pos = jnp.zeros((bn,), jnp.int32)
            for r in range(n_rounds):
                s = split_ref[t, k, r]
                f = feat_ref[t, k, r]
                thr = thr_ref[t, k, r]
                row = pl.load(bins_ref, (pl.ds(f, 1), slice(None)))[0]
                right = (pos == s) & (row.astype(jnp.int32) > thr)
                pos = jnp.where(right, r + 1, pos)
            contrib = jnp.zeros((bn,), jnp.float32)
            for leaf_id in range(n_leaves):
                contrib = jnp.where(pos == leaf_id,
                                    leaf_ref[t, k, leaf_id], contrib)
            acc = acc.at[k].add(contrib)
        return acc

    out_ref[:] = jax.lax.fori_loop(
        0, n_trees, tree_body, jnp.zeros((n_class, bn), jnp.float32))


def _quant_predict_call(kernel, bins_t, scalar_args, n_class: int,
                        block_n: int, interpret):
    """Shared pallas_call driver for both quantized predict kernels:
    pad the (d, n) uint8 matrix to tile-friendly blocks, prefetch the
    scalar tree tables, return (n, K) f32 contributions (no base)."""
    d, n = bins_t.shape
    interpret = _interpret() if interpret is None else interpret
    block_n = max(128, min(block_n, -(-n // 128) * 128))
    pad_n = (-n) % block_n
    pad_d = (-d) % 32          # uint8 sublane tile is 32-deep
    if pad_n or pad_d:
        bins_t = jnp.pad(bins_t, ((0, pad_d), (0, pad_n)))
    nblk = bins_t.shape[1] // block_n
    d_pad = d + pad_d

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalar_args),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((d_pad, block_n), lambda i, *_: (0, i))],
        out_specs=pl.BlockSpec((n_class, block_n), lambda i, *_: (0, i)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_class, bins_t.shape[1]),
                                       jnp.float32),
        interpret=interpret,
    )(*scalar_args, bins_t)
    return out[:, :n].T


def gbdt_predict_quant_levelwise(bins_t, feature, threshold, leaf, *,
                                 depth: int, block_n: int = 512,
                                 interpret=None):
    """Quantized level-wise ensemble predict: one Pallas dispatch scores
    every tree against uint8 rows that never leave VMEM.

    bins_t (d, n) uint8 — the transposed bin matrix (the predict wire
    format); feature/threshold (T, K, 2^depth - 1) uint8 — the
    structure-of-arrays quantized test tables (threshold carries the
    255-clamped route-all-left sentinel, see engine.quantize_ensemble);
    leaf (T, K, 2^depth) bf16. Returns (n, K) f32 — the summed leaf
    contributions, base NOT included (callers add it; keeps the kernel a
    pure ensemble reduction).

    Contrast with the dense path (engine._predict_tree_t): that one
    stages a (2^depth - 1, n) bool test table per tree in HBM (bounded
    by the _TEST_TABLE byte caps) and re-reads the f32/int32 tree
    arrays per tree; here rows are read ONCE per (block, node-visit)
    from VMEM, the tables are uint8/bf16, and the only HBM traffic is
    the bin matrix in and (K, n) f32 out. Runs in interpret mode
    off-TPU (CPU CI) — same results, no Mosaic."""
    T, K, n_nodes = feature.shape
    assert n_nodes <= PREDICT_QUANT_MAX_NODES, (n_nodes, "unroll cap")
    assert 2 ** depth <= PREDICT_QUANT_MAX_LEAVES, depth
    kernel = functools.partial(_gbdt_quant_lvl_kernel, n_trees=T,
                               n_class=K, depth=depth)
    scalars = (jnp.asarray(feature, jnp.int32),
               jnp.asarray(threshold, jnp.int32),
               # exact widening of the stored bf16 table (scalar memory
               # holds f32; the quantization already happened at the
               # bf16 round)  # precision: exact bf16->f32 widening
               jnp.asarray(leaf).astype(jnp.float32))
    return _quant_predict_call(kernel, bins_t, scalars, K, block_n,
                               interpret)


def gbdt_predict_quant_leafwise(bins_t, split_leaf, feature, threshold,
                                leaf, *, block_n: int = 512,
                                interpret=None):
    """Quantized leaf-wise ensemble predict (numeric splits only —
    categorical bitsets stay on the dense path). split_leaf (T, K, L-1)
    int32; feature/threshold (T, K, L-1) uint8; leaf (T, K, L) bf16.
    Returns (n, K) f32 contributions, base not included."""
    T, K, n_rounds = split_leaf.shape
    n_leaves = leaf.shape[2]
    assert n_rounds <= PREDICT_QUANT_MAX_NODES, (n_rounds, "unroll cap")
    assert n_leaves <= PREDICT_QUANT_MAX_LEAVES, n_leaves
    kernel = functools.partial(_gbdt_quant_lw_kernel, n_trees=T,
                               n_class=K, n_rounds=n_rounds,
                               n_leaves=n_leaves)
    scalars = (jnp.asarray(split_leaf, jnp.int32),
               jnp.asarray(feature, jnp.int32),
               jnp.asarray(threshold, jnp.int32),
               # precision: exact bf16->f32 widening of the stored table
               jnp.asarray(leaf).astype(jnp.float32))
    return _quant_predict_call(kernel, bins_t, scalars, K, block_n,
                               interpret)


def node_sums(node, g, h, n_ids: int, impl: str = "auto"):
    """Per-node grad/hess sums (the leaf-value reduction) without the
    scatter: a one-hot f32 matmul at HIGHEST precision. Measured 11 ms vs
    segment_sum's 20.6 ms at 1M rows x 32 ids (v5e, round 5). Falls back
    to segment_sum when the (N, n_ids) f32 one-hot staging would exceed
    ~2 GB of HBM (e.g. 10M rows x 256 leaves = 10 GB — the budget keeps
    the 10M x 32-leaf BASELINE shape on the matmul path) — correct either
    way. Every PINNED hist_impl ("segment", "compare", "pallas") forces
    segment_sum: those knobs select the histogram build, and their
    pre-round-5 leaf sums were all segment_sum — pinning exists to
    bit-reproduce older ensembles, so the leaf reduction order must not
    drift under them (ADVICE r5; only "auto"/"mxu" ride the matmul).
    node (N,) int32; returns (lg, lh), each (n_ids,) f32."""
    if impl in ("segment", "compare", "pallas") \
            or node.shape[0] * n_ids * 4 > (2 << 30):
        return (jax.ops.segment_sum(g, node, num_segments=n_ids),
                jax.ops.segment_sum(h, node, num_segments=n_ids))
    oh = (node[:, None] == jnp.arange(n_ids, dtype=node.dtype)
          ).astype(jnp.float32)
    out = jax.lax.dot_general(
        oh, jnp.stack([g, h], axis=1), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)             # (n_ids, 2)
    return out[:, 0], out[:, 1]


def _hist_kernel(bins_ref, g_ref, h_ref, hg_ref, hh_ref, *, n_bins: int,
                 block_n: int, n_rows: int):
    """Grid = (num_row_blocks,). One-hot expand the row block's bins in VMEM,
    then two (1, bn) @ (bn, F*n_bins) MXU matmuls accumulate grad/hess sums
    straight into the output block (sequential grid -> safe accumulation)."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        hg_ref[:] = jnp.zeros_like(hg_ref)
        hh_ref[:] = jnp.zeros_like(hh_ref)

    bins = bins_ref[:]                                  # (bn, F) int32
    bn, F = bins.shape
    row_ok = (step * block_n + jax.lax.broadcasted_iota(
        jnp.int32, (bn, 1), 0)) < n_rows                # mask row padding
    # n_bins here is the 128-padded bin count: Mosaic only reshapes away a
    # trailing dim that is lane-aligned
    onehot = (bins[:, :, None] ==
              jax.lax.broadcasted_iota(jnp.int32, (bn, F, n_bins), 2))
    onehot = (onehot & row_ok[:, :, None]).astype(jnp.float32)
    flat = onehot.reshape(bn, F * n_bins)
    g = g_ref[:].reshape(1, bn)                         # (1, bn)
    h = h_ref[:].reshape(1, bn)
    # HIGHEST: full-f32 MXU passes — bf16 truncation of grads would put
    # ~4e-3 relative error on every histogram entry and perturb split gains
    hg_ref[:] += jnp.dot(
        g, flat, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).reshape(F, n_bins)
    hh_ref[:] += jnp.dot(
        h, flat, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).reshape(F, n_bins)


def histogram_fused(bins, grad, hess, n_bins: int = 256,
                    block_n: int = 1024, interpret=None):
    """Gradient/hessian histograms for GBDT split finding.

    bins: (N, F) int32 in [0, n_bins); grad/hess: (N,) float32.
    Returns (hist_g, hist_h), each (F, n_bins) float32.

    The scatter-add the reference does row-wise in native LightGBM
    (lightgbm/.../TrainUtils.scala:70-77) becomes a dense one-hot matmul per
    row block — contraction dim = rows, so the MXU does 2*N*F*n_bins FLOPs of
    "useless" multiplies by 0/1 and still beats a serialized scatter on TPU.
    Per-leaf histograms: pass grad pre-masked by node membership.
    """
    N, F = bins.shape
    interpret = _interpret() if interpret is None else interpret
    # lane-align the bin axis (Mosaic can only collapse/split a trailing dim
    # that is a 128 multiple); extra bins never match any bin id -> zero rows
    n_pad = -(-n_bins // 128) * 128
    # VMEM sizing: the kernel's scoped allocation is ~4x the f32 one-hot
    # staging (bool compare + mask + f32 cast + reshape copy of the
    # (block_n, F, n_pad) tensor) — measured on v5e: F=10/block 512 one-hot
    # 5.2MB allocates 20.6MB scoped and OOMs the 16MB limit. Budget the
    # whole scoped footprint, not just the one-hot.
    scoped_limit = 15 << 20          # stay under the 16MB scoped-vmem limit
    onehot_row_bytes = F * n_pad * 4
    rows_cap = (scoped_limit // (4 * onehot_row_bytes)) // 128 * 128
    # the row block can't shrink below 128 (lane alignment); if even that
    # exceeds the budget the one-hot tiling is infeasible on TPU — use the
    # XLA scatter-add instead (same result, no VMEM staging)
    if not interpret and rows_cap < 128:
        return segment_histogram(bins, grad, hess, n_bins)
    # rows are the matmul contraction dim: keep blocks lane-aligned (128) so
    # the TPU lowering accepts them even when the call is vmapped (per-node
    # masked grads batch the 1xN operands)
    block_n = min(block_n, -(-N // 128) * 128, max(128, rows_cap))
    pad = (-N) % block_n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    nblk = bins.shape[0] // block_n

    kernel = functools.partial(_hist_kernel, n_bins=n_pad, block_n=block_n,
                               n_rows=N)
    hg, hh = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((block_n, F), lambda i: (i, 0)),
            pl.BlockSpec((1, block_n), lambda i: (0, i)),
            pl.BlockSpec((1, block_n), lambda i: (0, i)),
        ],
        out_specs=(pl.BlockSpec((F, n_pad), lambda i: (0, 0)),
                   pl.BlockSpec((F, n_pad), lambda i: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((F, n_pad), jnp.float32),
                   jax.ShapeDtypeStruct((F, n_pad), jnp.float32)),
        interpret=interpret,
    )(bins.astype(jnp.int32), grad.astype(jnp.float32).reshape(1, -1),
      hess.astype(jnp.float32).reshape(1, -1))
    return hg[:, :n_bins], hh[:, :n_bins]
