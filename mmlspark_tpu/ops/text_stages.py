"""TextFeaturizer estimator (reference: text-featurizer/.../
TextFeaturizer.scala:179,274-325): a toggleable tokenize -> stopwords ->
ngram -> hashingTF -> IDF chain fit as one stage."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (BooleanParam, ComplexParam, IntParam, StringParam)
from ..core.pipeline import Estimator, Model
from . import text_ops


class _TextChainParams:
    """Shared param block between estimator and model."""
    useTokenizer = BooleanParam("tokenize the input text", default=True)
    tokenizerPattern = StringParam("regex for the tokenizer", default=r"\s+")
    tokenizerGaps = BooleanParam("pattern matches gaps (else tokens)", default=True)
    toLowercase = BooleanParam("lowercase before tokenizing", default=True)
    minTokenLength = IntParam("minimum token length", default=1, min=0)
    useStopWordsRemover = BooleanParam("remove stop words", default=False)
    caseSensitiveStopWords = BooleanParam("case sensitive stop words", default=False)
    useNGram = BooleanParam("emit n-grams", default=False)
    nGramLength = IntParam("n-gram length", default=2, min=1)
    binary = BooleanParam("binary term frequencies", default=False)
    numFeatures = IntParam("hash feature dimension", default=1 << 18, min=1)
    useIDF = BooleanParam("scale by inverse document frequency", default=True)
    minDocFreq = IntParam("minimum doc frequency for IDF", default=1, min=0)


def _featurize_tokens(params, texts):
    if params.getOrDefault("useTokenizer"):
        docs = text_ops.tokenize(
            ["" if t is None or t != t else str(t) for t in texts],
            pattern=params.getOrDefault("tokenizerPattern"),
            to_lowercase=params.getOrDefault("toLowercase"),
            gaps=params.getOrDefault("tokenizerGaps"),
            min_token_length=params.getOrDefault("minTokenLength"))
    else:
        docs = []
        for t in texts:
            if t is None:
                docs.append([])
            elif isinstance(t, (list, tuple, np.ndarray)):
                docs.append([str(x) for x in t])
            else:
                raise TypeError(
                    "useTokenizer=False requires pre-tokenized rows "
                    f"(list/tuple/array of tokens), got {type(t).__name__}")
    if params.getOrDefault("useStopWordsRemover"):
        docs = text_ops.remove_stopwords(
            docs, case_sensitive=params.getOrDefault("caseSensitiveStopWords"))
    if params.getOrDefault("useNGram"):
        docs = text_ops.ngrams(docs, params.getOrDefault("nGramLength"))
    return text_ops.hashing_tf(docs, params.getOrDefault("numFeatures"),
                               binary=params.getOrDefault("binary"))


class TextFeaturizerModel(Model, _TextChainParams):
    inputCol = StringParam("input text column", default="text")
    outputCol = StringParam("output feature column", default="features")
    idfWeights = ComplexParam("fitted IDF weights", default=None)

    def transform(self, df: DataFrame) -> DataFrame:
        tf = _featurize_tokens(self, df.col(self.getInputCol()))
        w = self.getIdfWeights()
        if self.getUseIDF() and w is not None:
            tf = text_ops.apply_idf(tf, np.asarray(w))
        return df.withColumn(self.getOutputCol(),
                             text_ops.csr_to_row_objects(tf))


class TextFeaturizer(Estimator, _TextChainParams):
    inputCol = StringParam("input text column", default="text")
    outputCol = StringParam("output feature column", default="features")

    def fit(self, df: DataFrame) -> TextFeaturizerModel:
        model = TextFeaturizerModel()
        model.set(**{k: self.getOrDefault(k) for k in self._params
                     if k not in ("idfWeights",)})
        if self.getUseIDF():
            tf = _featurize_tokens(self, df.col(self.getInputCol()))
            from ..parallel import dataplane
            if dataplane.is_sharded(df):
                # fleet-wide IDF: document frequencies and the corpus size
                # sum across shards in one collective (Spark's IDF
                # aggregates over the whole cluster the same way)
                df_local = np.asarray((tf > 0).sum(axis=0)).ravel() \
                    .astype(np.float64)
                tot = dataplane.allreduce_sum(
                    np.concatenate([[float(tf.shape[0])], df_local]))
                m, dfreq = tot[0], tot[1:]
                w = np.log((m + 1.0) / (dfreq + 1.0))
                if self.getMinDocFreq() > 0:
                    w = np.where(dfreq >= self.getMinDocFreq(), w, 0.0)
                model.setIdfWeights(w.astype(np.float32))
            else:
                model.setIdfWeights(
                    text_ops.idf_weights(tf, self.getMinDocFreq()))
        return model
