"""Image pipeline stages: ImageTransformer, UnrollImage, ImageSetAugmenter.

API parity with the reference's image-transformer module
(ImageTransformer.scala:261, UnrollImage.scala:18-43,
image-featurizer/.../ImageSetAugmenter.scala:15), redesigned for TPU: rows are
grouped by image shape into NHWC batches, each batch runs the whole op chain
as one fused jitted XLA program (see ops.image_ops), instead of the
reference's per-row OpenCV Mat calls.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (BooleanParam, ListParam, StringParam)
from ..core.pipeline import Transformer
from ..core.schema import image_to_array, make_image_row, tag_image_column
from . import image_ops


def _rows_to_batches(col: np.ndarray):
    """Group image-struct rows by (h, w, c) so every batch is static-shape.
    Yields (indices, NHWC uint8 batch, paths)."""
    groups: dict[tuple, list[int]] = {}
    for i, row in enumerate(col):
        arr_shape = (row["height"], row["width"], row["type"])
        groups.setdefault(arr_shape, []).append(i)
    for shape, idxs in groups.items():
        batch = np.stack([image_to_array(col[i]) for i in idxs])
        yield idxs, batch, [col[i]["path"] for i in idxs]


class ImageTransformer(Transformer):
    """Pipelined image processing (reference: ImageTransformer.scala:261).

    Ops are recorded as a list of ``{"op": name, **params}`` dicts via the
    fluent builder methods, exactly mirroring the reference's stage-list
    param, and execute as one fused XLA program per shape bucket.
    """

    #: image-struct rows (path/height/width/bytes dicts) have no columnar
    #: device encoding — the stage runs its own per-shape-bucket programs
    _uncapturable = True
    inputCol = StringParam("input image column", default="image")
    outputCol = StringParam("output image column", default="out")
    stages = ListParam("list of {op, **params} dicts", default=())

    def _add(self, d: dict) -> "ImageTransformer":
        self.setStages(tuple(self.getStages()) + (d,))
        return self

    def resize(self, height: int, width: int):
        return self._add({"op": "resize", "height": int(height), "width": int(width)})

    def crop(self, x: int, y: int, height: int, width: int):
        return self._add({"op": "crop", "x": int(x), "y": int(y),
                          "height": int(height), "width": int(width)})

    def flip(self, flipCode: int = 1):
        return self._add({"op": "flip", "flipCode": int(flipCode)})

    def colorFormat(self, format: str):
        return self._add({"op": "colorformat", "format": format})

    def blur(self, height: float, width: float):
        return self._add({"op": "blur", "height": int(height), "width": int(width)})

    def threshold(self, threshold: float, maxVal: float, thresholdType: str = "binary"):
        return self._add({"op": "threshold", "threshold": float(threshold),
                          "maxVal": float(maxVal), "type": thresholdType})

    def gaussianKernel(self, appertureSize: int, sigma: float):
        return self._add({"op": "gaussiankernel",
                          "appertureSize": int(appertureSize), "sigma": float(sigma)})

    def transform(self, df: DataFrame) -> DataFrame:
        col = df.col(self.getInputCol())
        ops = [dict(d) for d in self.getStages()]
        out = np.empty(len(col), dtype=object)
        for idxs, batch, paths in _rows_to_batches(col):
            res = image_ops.apply_op_chain(batch, ops) if ops else batch.astype(np.float32)
            res = np.clip(np.rint(res), 0, 255).astype(np.uint8)
            for j, i in enumerate(idxs):
                h, w, c = res[j].shape
                out[i] = make_image_row(paths[j], h, w, c, res[j])
        return tag_image_column(df.withColumn(self.getOutputCol(), out),
                                self.getOutputCol())


class UnrollImage(Transformer):
    """Image struct column -> flat CHW float vector column (reference:
    UnrollImage.scala:18-43). The reference loops pixels to fix up JVM signed
    bytes; with uint8 numpy the unroll is a transpose+reshape."""

    inputCol = StringParam("input image column", default="image")
    outputCol = StringParam("output vector column", default="unrolled")

    def transform(self, df: DataFrame) -> DataFrame:
        col = df.col(self.getInputCol())
        out = np.empty(len(col), dtype=object)
        for i, row in enumerate(col):
            arr = image_to_array(row).astype(np.float64)
            out[i] = np.transpose(arr, (2, 0, 1)).ravel()
        return df.withColumn(self.getOutputCol(), out)


class ImageSetAugmenter(Transformer):
    """Dataset augmentation by flips (reference: ImageSetAugmenter.scala:15):
    emits the original rows plus flipped copies."""

    inputCol = StringParam("input image column", default="image")
    outputCol = StringParam("output image column", default="image")
    flipLeftRight = BooleanParam("add left-right flipped copies", default=True)
    flipUpDown = BooleanParam("add up-down flipped copies", default=False)

    def transform(self, df: DataFrame) -> DataFrame:
        frames = [df.withColumn(self.getOutputCol(), df.col(self.getInputCol()))]
        for flag, code in ((self.getFlipLeftRight(), 1), (self.getFlipUpDown(), 0)):
            if flag:
                t = (ImageTransformer().setInputCol(self.getInputCol())
                     .setOutputCol(self.getOutputCol()).flip(code))
                frames.append(t.transform(df))
        out = frames[0]
        for f in frames[1:]:
            out = out.union(f.select(*out.columns))
        return tag_image_column(out, self.getOutputCol())
