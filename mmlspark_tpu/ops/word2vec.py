"""Word2Vec estimator — notebook-202 parity (`notebooks/samples/202 - Amazon
Book Reviews - Word2Vec.ipynb` in the reference uses Spark ML's
``org.apache.spark.ml.feature.Word2Vec``; MMLSpark itself ships no
re-implementation, but a reference user relies on it in the documented text
workflow, so this build provides one).

TPU-first design, not a port of Spark's: Spark MLlib trains skip-gram with
hierarchical softmax — a per-word binary-tree walk that is branchy, scalar,
and hostile to the MXU. Here the objective is skip-gram with **negative
sampling** (Mikolov et al. 2013b), which reduces each step to embedding
gathers + one batched dot per (center, context±negatives) — dense, static
shapes, all inside a single jitted update:

    gather E_in[center]  (B,D)
    gather E_out[pos | negs]  (B,1+K,D)
    loss = -logsigmoid(s_pos) - sum logsigmoid(-s_neg),  s = einsum bd,bkd->bk

The gradient of the gathers is a scatter-add XLA emits natively, so sparse
updates never materialize a (V,D) dense gradient per step. Negatives are
drawn from the unigram^0.75 distribution via a precomputed alias-style table
(one int32 gather per sample — the classic 1e8-slot trick, sized down).

Model surface follows Spark ML (`Word2VecModel`): ``transform`` averages the
vectors of a document's in-vocab tokens (all-OOV rows get the zero vector),
``findSynonyms`` returns cosine top-k, ``getVectors`` the vocab table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

# one shared transform so fit() init and _sgns_step update can never drift
_ADAM = optax.scale_by_adam()

from ..core.dataframe import DataFrame
from ..core.params import (ComplexParam, FloatParam, IntParam, StringParam)
from ..core.pipeline import Estimator, Model
from ..core.utils import object_column


def _tokenized(col) -> list[list[str]]:
    """Accept pre-tokenized rows (Spark requires array<string>) or raw
    strings (whitespace-split convenience)."""
    docs = []
    for row in col:
        if row is None:
            docs.append([])
        elif isinstance(row, str):
            docs.append(row.split())
        elif isinstance(row, (list, tuple, np.ndarray)):
            docs.append([str(t) for t in row])
        else:
            raise TypeError(
                f"Word2Vec input rows must be token lists or strings, "
                f"got {type(row).__name__}")
    return docs


def _build_vocab(docs, min_count):
    counts: dict[str, int] = {}
    for doc in docs:
        for tok in doc:
            counts[tok] = counts.get(tok, 0) + 1
    # frequency-descending, ties lexicographic: deterministic ids
    vocab = sorted((w for w, c in counts.items() if c >= min_count),
                   key=lambda w: (-counts[w], w))
    return vocab, np.array([counts[w] for w in vocab], dtype=np.int64)


def _corpus_ids(docs, word2id):
    """One-time docs -> (token id stream, document id per token); the
    per-epoch work below only resamples windows over these arrays."""
    ids_parts, doc_parts = [], []
    for di, doc in enumerate(docs):
        ids = [word2id[t] for t in doc if t in word2id]
        if len(ids) >= 2:
            ids_parts.append(np.asarray(ids, dtype=np.int32))
            doc_parts.append(np.full(len(ids), di, dtype=np.int64))
    if not ids_parts:
        return (np.empty(0, dtype=np.int32), np.empty(0, dtype=np.int64))
    return np.concatenate(ids_parts), np.concatenate(doc_parts)


def _skipgram_pairs(ids, docm, window, rng):
    """(center, context) int32 pairs with per-position random window
    reduction (word2vec's dynamic window ~ distance down-weighting).

    Vectorized over the whole corpus — one numpy pass per distance d,
    pairing i with i±d where the center's sampled span covers d and both
    positions fall in the same document — so pair generation stays a small
    fraction of the jitted training steps even at notebook-202 scale."""
    if len(ids) < 2:
        return (np.empty(0, dtype=np.int32), np.empty(0, dtype=np.int32))
    spans = rng.integers(1, window + 1, size=len(ids))
    centers, contexts = [], []
    for d in range(1, min(window, len(ids) - 1) + 1):
        same = docm[:-d] == docm[d:]
        right = same & (spans[:-d] >= d)   # center i, context i+d
        left = same & (spans[d:] >= d)     # center i+d, context i
        centers.append(ids[:-d][right])
        contexts.append(ids[d:][right])
        centers.append(ids[d:][left])
        contexts.append(ids[:-d][left])
    return (np.concatenate(centers), np.concatenate(contexts))


def _unigram_table(counts, size=1 << 18):
    p = counts.astype(np.float64) ** 0.75
    p /= p.sum()
    # deterministic proportional fill (largest-remainder), then exact top-up
    slots = np.floor(p * size).astype(np.int64)
    rem = size - slots.sum()
    if rem > 0:
        order = np.argsort(-(p * size - slots))
        slots[order[:rem]] += 1
    return np.repeat(np.arange(len(counts), dtype=np.int32), slots)


@functools.partial(jax.jit, static_argnums=(7,))
def _sgns_step(emb_in, emb_out, opt_state, centers, contexts, valid, key,
               num_neg, table, lr):
    negs = table[jax.random.randint(key, (centers.shape[0], num_neg),
                                    0, table.shape[0])]

    def loss_fn(params):
        e_in, e_out = params
        v_c = e_in[centers]                                   # (B, D)
        tgt = jnp.concatenate([contexts[:, None], negs], axis=1)  # (B, 1+K)
        v_t = e_out[tgt]                                      # (B, 1+K, D)
        scores = jnp.einsum("bd,bkd->bk", v_c, v_t)
        sign = jnp.concatenate(
            [jnp.ones((centers.shape[0], 1), scores.dtype),
             -jnp.ones((centers.shape[0], num_neg), scores.dtype)], axis=1)
        per_pair = -jnp.sum(jax.nn.log_sigmoid(sign * scores), axis=1)
        return jnp.sum(per_pair * valid) / jnp.maximum(jnp.sum(valid), 1.0)

    loss, grads = jax.value_and_grad(loss_fn)((emb_in, emb_out))
    # Adam direction with the (decayed) lr applied outside: large-batch
    # SGNS needs per-coordinate scaling — word2vec.c's per-pair SGD either
    # stalls (mean loss) or blows up (sum loss) once pairs are batched
    updates, opt_state = _ADAM.update(grads, opt_state, (emb_in, emb_out))
    emb_in = emb_in - lr * updates[0]
    emb_out = emb_out - lr * updates[1]
    return emb_in, emb_out, opt_state, loss


class _W2VParams:
    inputCol = StringParam("input token-list column", default="text")
    outputCol = StringParam("output document-vector column", default="features")
    vectorSize = IntParam("embedding dimension", default=100, min=1)
    windowSize = IntParam("max skip-gram window", default=5, min=1)
    minCount = IntParam("minimum token frequency", default=5, min=1)
    maxIter = IntParam("training epochs", default=1, min=1)
    stepSize = FloatParam("Adam learning rate (batched SGNS, not Spark's "
                          "per-pair SGD)", default=0.025, min=0.0)
    negativeSamples = IntParam(
        "negatives per positive (this build trains SGNS, not Spark's "
        "hierarchical softmax)", default=5, min=1)
    batchSize = IntParam("pairs per jitted step", default=1 << 14, min=1)
    seed = IntParam("rng seed", default=0)


class Word2VecModel(Model, _W2VParams):
    """Fitted word embeddings: transform averages a document's in-vocab
    word vectors (Spark Word2VecModel semantics); findSynonyms/getVectors
    expose the vocabulary geometry."""

    vocabulary = ComplexParam("vocab words, id order", default=None)
    wordVectors = ComplexParam("(V, D) float32 embeddings", default=None)

    def _word2id(self):
        return {w: i for i, w in enumerate(self.getVocabulary() or [])}

    def getVectors(self) -> DataFrame:
        vecs = np.asarray(self.getWordVectors())
        return DataFrame({
            "word": np.array(list(self.getVocabulary()), dtype=object),
            "vector": object_column([vecs[i] for i in range(len(vecs))])})

    def findSynonyms(self, word: str, num: int) -> DataFrame:
        w2i = self._word2id()
        if word not in w2i:
            raise KeyError(f"'{word}' not in vocabulary")
        vecs = np.asarray(self.getWordVectors(), dtype=np.float64)
        norms = np.linalg.norm(vecs, axis=1) + 1e-12
        q = vecs[w2i[word]] / norms[w2i[word]]
        sims = (vecs / norms[:, None]) @ q
        order = np.argsort(-sims)
        top = order[order != w2i[word]][:num]  # Spark never returns the query
        vocab = list(self.getVocabulary())
        return DataFrame({
            "word": np.array([vocab[i] for i in top], dtype=object),
            "similarity": sims[top].astype(np.float64)})

    def transform(self, df: DataFrame) -> DataFrame:
        docs = _tokenized(df.col(self.getInputCol()))
        w2i = self._word2id()
        vecs = np.asarray(self.getWordVectors(), dtype=np.float32)
        d = vecs.shape[1]
        out = []
        for doc in docs:
            ids = [w2i[t] for t in doc if t in w2i]
            out.append(vecs[ids].mean(axis=0) if ids
                       else np.zeros(d, dtype=np.float32))
        return df.withColumn(self.getOutputCol(), object_column(out))


class Word2Vec(Estimator, _W2VParams):
    """Learn word embeddings by skip-gram negative sampling, batched into
    jitted MXU steps (Spark ML Word2Vec surface; notebook-202 workflow)."""

    #: consumes a token-sequence column through host vocab building and
    #: subsampling — no array-in/array-out featurize body to fuse with
    _uncapturable = True

    def _make_model(self, vocab, vectors) -> Word2VecModel:
        model = Word2VecModel()
        model.set(**{k: self.getOrDefault(k) for k in self._params
                     if k in _W2VParams.__dict__})
        model.setVocabulary(list(vocab))
        model.setWordVectors(np.asarray(vectors, dtype=np.float32))
        return model

    def fit(self, df: DataFrame) -> Word2VecModel:
        docs = _tokenized(df.col(self.getInputCol()))
        vocab, counts = _build_vocab(docs, self.getMinCount())
        d = self.getVectorSize()
        rng = np.random.default_rng(self.getSeed())
        if not vocab:
            return self._make_model([], np.zeros((0, d), dtype=np.float32))

        word2id = {w: i for i, w in enumerate(vocab)}
        v = len(vocab)
        emb_in = jnp.asarray(
            (rng.random((v, d), dtype=np.float32) - 0.5) / d)
        emb_out = jnp.zeros((v, d), dtype=jnp.float32)
        table = jnp.asarray(_unigram_table(counts))
        bs = self.getBatchSize()
        key = jax.random.PRNGKey(self.getSeed())
        opt_state = _ADAM.init((emb_in, emb_out))

        ids, docm = _corpus_ids(docs, word2id)
        for epoch in range(self.getMaxIter()):
            centers, contexts = _skipgram_pairs(
                ids, docm, self.getWindowSize(), rng)
            n = len(centers)
            if n == 0:
                break
            perm = rng.permutation(n)
            centers, contexts = centers[perm], contexts[perm]
            # linear lr decay across the whole run, floored like word2vec.c
            for start in range(0, n, bs):
                done = (epoch * n + start) / (self.getMaxIter() * n)
                lr = max(self.getStepSize() * (1.0 - done),
                         self.getStepSize() * 1e-4)
                c = centers[start:start + bs]
                t = contexts[start:start + bs]
                valid = np.ones(bs, dtype=np.float32)
                if len(c) < bs:  # pad to the one compiled shape, mask out
                    pad = bs - len(c)
                    valid[len(c):] = 0.0
                    c = np.concatenate([c, np.zeros(pad, np.int32)])
                    t = np.concatenate([t, np.zeros(pad, np.int32)])
                key, sub = jax.random.split(key)
                emb_in, emb_out, opt_state, _ = _sgns_step(
                    emb_in, emb_out, opt_state, jnp.asarray(c),
                    jnp.asarray(t), jnp.asarray(valid), sub,
                    self.getNegativeSamples(), table, jnp.float32(lr))

        return self._make_model(vocab, emb_in)
