"""Text featurization primitives: tokenize, stopwords, n-grams, hashing TF,
IDF.

Reference: text-featurizer builds RegexTokenizer -> StopWordsRemover -> NGram
-> HashingTF -> IDF (TextFeaturizer.scala:274-325). Tokenization/hashing is
inherently host-side string work; the numeric tail (TF matrices, IDF weights,
TF-IDF scaling) is vectorized so dense feature blocks ship to TPU in one
device_put. Sparse TF uses scipy CSR (the reference uses Spark sparse
vectors).
"""

from __future__ import annotations

import re
import zlib
from typing import Iterable, Sequence

import numpy as np
import scipy.sparse as sp

# Spark ML's default english stop word list (abridged, stable subset)
ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are as at be because been
before being below between both but by could did do does doing down during
each few for from further had has have having he her here hers herself him
himself his how i if in into is it its itself me more most my myself no nor
not of off on once only or other our ours ourselves out over own same she
should so some such than that the their theirs them themselves then there
these they this those through to too under until up very was we were what
when where which while who whom why will with you your yours yourself
yourselves
""".split())


def tokenize(texts: Iterable[str], pattern: str = r"\s+",
             to_lowercase: bool = True, gaps: bool = True,
             min_token_length: int = 1) -> list[list[str]]:
    """Spark RegexTokenizer semantics: `gaps` means the pattern matches
    separators; otherwise it matches tokens."""
    rx = re.compile(pattern)
    out = []
    for t in texts:
        t = t if t is not None else ""
        if to_lowercase:
            t = t.lower()
        toks = rx.split(t) if gaps else rx.findall(t)
        out.append([tok for tok in toks if len(tok) >= min_token_length])
    return out


def remove_stopwords(docs: Sequence[list[str]],
                     stopwords: frozenset = ENGLISH_STOP_WORDS,
                     case_sensitive: bool = False) -> list[list[str]]:
    if case_sensitive:
        return [[t for t in doc if t not in stopwords] for doc in docs]
    low = {w.lower() for w in stopwords}
    return [[t for t in doc if t.lower() not in low] for doc in docs]


def ngrams(docs: Sequence[list[str]], n: int) -> list[list[str]]:
    """Spark NGram: join each n-token window with a space."""
    return [[" ".join(doc[i:i + n]) for i in range(len(doc) - n + 1)]
            for doc in docs]


def hash_token(token: str, num_features: int) -> int:
    """Deterministic, process-stable token hash (crc32 of utf-8 bytes)."""
    return zlib.crc32(token.encode("utf-8")) % num_features


def hashing_tf(docs: Sequence[list[str]], num_features: int = 1 << 18,
               binary: bool = False) -> sp.csr_matrix:
    """Token lists -> (n_docs, num_features) sparse CSR term-frequency matrix."""
    indptr, indices, data = [0], [], []
    for doc in docs:
        counts: dict[int, int] = {}
        for tok in doc:
            h = hash_token(tok, num_features)
            counts[h] = 1 if binary else counts.get(h, 0) + 1
        indices.extend(counts.keys())
        data.extend(counts.values())
        indptr.append(len(indices))
    return sp.csr_matrix(
        (np.asarray(data, dtype=np.float32),
         np.asarray(indices, dtype=np.int64),
         np.asarray(indptr, dtype=np.int64)),
        shape=(len(docs), num_features))


def idf_weights(tf: sp.csr_matrix, min_doc_freq: int = 0) -> np.ndarray:
    """Spark IDF formula: log((m + 1) / (df + 1)); features with
    df < minDocFreq get weight 0."""
    m = tf.shape[0]
    df = np.asarray((tf > 0).sum(axis=0)).ravel().astype(np.float64)
    w = np.log((m + 1.0) / (df + 1.0))
    if min_doc_freq > 0:
        w = np.where(df >= min_doc_freq, w, 0.0)
    return w.astype(np.float32)


def apply_idf(tf: sp.csr_matrix, weights: np.ndarray) -> sp.csr_matrix:
    out = tf.copy()
    out.data = out.data * weights[out.indices]
    return out


def csr_to_row_objects(mat: sp.csr_matrix) -> np.ndarray:
    """CSR matrix -> object column of 1-row CSR slices (sparse row vectors)."""
    from ..core.utils import object_column
    return object_column([mat.getrow(i) for i in range(mat.shape[0])])


def rows_to_matrix(col: np.ndarray):
    """Column of sparse row vectors / dense vectors -> single matrix
    (CSR if sparse, dense float32 otherwise)."""
    if len(col) and sp.issparse(col[0]):
        return sp.vstack(list(col), format="csr")
    from ..core.utils import to_float32_matrix
    return to_float32_matrix(np.asarray(col))
