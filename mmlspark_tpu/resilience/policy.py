"""Shared retry and circuit-breaking policies.

Every network/IO call site in the framework recovers through these two
classes instead of hand-rolled loops: :class:`RetryPolicy` decides *whether*
and *when* to try again (transient-vs-fatal classification, exponential
backoff with full jitter, a hard deadline budget), :class:`CircuitBreaker`
decides whether to try *at all* (a target that keeps failing is skipped
until a reset-timeout probe succeeds, so a dead worker is not hammered on
every poll round).

Both are cheap when idle and thread-safe when shared: serving loops, the
fleet driver, and the supervisor all update the same breaker concurrently.
Telemetry: ``mmlspark_retry_attempts_total{policy}``,
``mmlspark_retry_exhausted_total{policy}``,
``mmlspark_breaker_state{breaker,target}`` (0 closed / 1 half-open /
2 open), ``mmlspark_breaker_opens_total{breaker,target}`` and
``mmlspark_breaker_short_circuits_total{breaker,target}``.
"""

from __future__ import annotations

import http.client
import random
import threading
import time
import urllib.error
import weakref
from typing import Callable, Optional, Sequence, Union

from .. import telemetry
from ..core.utils import get_logger

log = get_logger("resilience.policy")

_m_retries = telemetry.registry.counter(
    "mmlspark_retry_attempts_total",
    "retried attempts (beyond the first) by policy name",
    labels=("policy",))
_m_exhausted = telemetry.registry.counter(
    "mmlspark_retry_exhausted_total",
    "operations that failed after exhausting their retry budget",
    labels=("policy",))
_m_breaker_state = telemetry.registry.gauge(
    "mmlspark_breaker_state",
    "circuit state per target: 0 closed, 1 half-open, 2 open",
    labels=("breaker", "target"))
_m_breaker_opens = telemetry.registry.counter(
    "mmlspark_breaker_opens_total",
    "closed/half-open -> open transitions", labels=("breaker", "target"))
_m_breaker_short = telemetry.registry.counter(
    "mmlspark_breaker_short_circuits_total",
    "calls rejected without attempting because the circuit was open",
    labels=("breaker", "target"))


def default_transient(exc: BaseException) -> bool:
    """The shared transient-vs-fatal classification: network-shaped errors
    (connection loss, timeouts, 5xx/429 responses, a peer dying
    mid-response) are worth another attempt; everything else — bad input,
    assertion failures, programming errors — is fatal and re-raises
    immediately. Call sites can tag any exception transient explicitly by
    setting ``exc.transient = True`` (the PowerBI writer does this for 5xx
    status codes carried inside an IOError)."""
    marked = getattr(exc, "transient", None)
    if marked is not None:
        return bool(marked)
    if isinstance(exc, urllib.error.HTTPError):  # URLError subclass: check
        return exc.code >= 500 or exc.code == 429  # the code first
    return isinstance(exc, (ConnectionError, TimeoutError,
                            InterruptedError, urllib.error.URLError,
                            http.client.HTTPException, OSError))


class RetryPolicy:
    """Exponential backoff with FULL jitter and a deadline budget.

    Full jitter (delay ~ U(0, min(max_delay, base * mult**attempt))) is the
    AWS-architecture-blog result: under correlated failure a fleet of
    retriers with deterministic backoff re-synchronizes into thundering
    herds; uniform jitter spreads them. ``deadline`` bounds the TOTAL time
    budget across attempts (sleeps are clipped to the remaining budget and
    an attempt never starts past it) — a serving path must fail a request
    while the client is still listening, not 2^n seconds later.

    ``retryable`` is the transient classification: ``None`` uses
    :func:`default_transient`, a tuple of exception types uses isinstance,
    a callable is a predicate. Fatal errors re-raise immediately without
    consuming the budget.

    Use ``run(fn)``: ``fn(attempt)`` is called with the 0-based attempt
    index (call sites that re-read replayable state on retry — the fleet's
    ``getBatch`` — key off it; most ignore it).
    """

    def __init__(self, name: str = "retry", max_attempts: int = 4,
                 base_delay: float = 0.05, multiplier: float = 2.0,
                 max_delay: float = 2.0, deadline: Optional[float] = None,
                 retryable: Union[None, Sequence[type], Callable] = None,
                 seed: Optional[int] = None, sleep: Callable = time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.name = name
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.multiplier = multiplier
        self.max_delay = max_delay
        self.deadline = deadline
        self._retryable = retryable
        # a private Random instance even when unseeded (Random(None) seeds
        # from OS entropy): jitter draws never contend on — or reseed —
        # the process-global random state other threads may rely on
        self._rng = random.Random(seed)
        self._sleep = sleep

    def is_transient(self, exc: BaseException) -> bool:
        r = self._retryable
        if r is None:
            return default_transient(exc)
        if callable(r) and not isinstance(r, (tuple, list)):
            return bool(r(exc))
        return isinstance(exc, tuple(r))

    def backoff(self, attempt: int) -> float:
        """Full-jitter delay before attempt ``attempt + 1``."""
        cap = min(self.max_delay,
                  self.base_delay * (self.multiplier ** attempt))
        return self._rng.uniform(0.0, cap) if cap > 0 else 0.0

    def run(self, fn: Callable, *, on_retry: Optional[Callable] = None):
        """``fn(attempt)`` until success / fatal error / budget exhausted.
        ``on_retry(attempt, exc)`` fires before each backoff sleep."""
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                return fn(attempt)
            except Exception as e:
                if not self.is_transient(e):
                    raise
                delay = self.backoff(attempt)
                remaining = (None if self.deadline is None
                             else self.deadline - (time.monotonic() - t0))
                if attempt + 1 >= self.max_attempts or (
                        remaining is not None and remaining <= delay):
                    _m_exhausted.labels(policy=self.name).inc()
                    # instants auto-tag the current distributed trace
                    # context, so a request's trace shows WHICH retries
                    # it owned (telemetry off: one flag check)
                    telemetry.trace.instant("retry/exhausted",
                                            policy=self.name,
                                            attempts=attempt + 1,
                                            error=type(e).__name__)
                    raise
                _m_retries.labels(policy=self.name).inc()
                telemetry.trace.instant("retry", policy=self.name,
                                        attempt=attempt,
                                        error=type(e).__name__)
                if on_retry is not None:
                    on_retry(attempt, e)
                if delay > 0:
                    self._sleep(delay)
                attempt += 1


class BreakerOpen(ConnectionError):
    """Raised by :meth:`CircuitBreaker.call` when the circuit is open.
    Subclasses ConnectionError so the default RetryPolicy classification
    treats a short-circuited call as transient (retry later, elsewhere)."""

    def __init__(self, breaker: str, target: str):
        super().__init__(f"circuit {breaker!r} open for target {target!r}")
        self.breaker = breaker
        self.target = target


_STATE_NUM = {"closed": 0, "half_open": 1, "open": 2}


class _Target:
    __slots__ = ("state", "failures", "opened_at", "probes")

    def __init__(self):
        self.state = "closed"
        self.failures = 0      # consecutive failures while closed
        self.opened_at = 0.0
        self.probes = 0        # in-flight half-open probes


class CircuitBreaker:
    """Per-target closed/open/half-open circuit.

    ``failure_threshold`` CONSECUTIVE failures open the circuit for
    ``reset_timeout`` seconds, during which :meth:`allow` answers False
    (the caller skips the target — one cheap gauge read instead of a
    doomed network round-trip + timeout). After the window one probe
    (``half_open_max``) is let through: success closes the circuit,
    failure re-opens it for another window.

    Targets are independent (the fleet driver keys by worker index), and
    every live breaker is visible to ``GET /healthz`` via
    :meth:`snapshot_all`.
    """

    _instances: "weakref.WeakSet[CircuitBreaker]" = weakref.WeakSet()

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout: float = 1.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout = reset_timeout
        self.half_open_max = max(1, half_open_max)
        self._clock = clock
        self._targets: dict[str, _Target] = {}   # guarded-by: _lock
        self._lock = threading.Lock()
        CircuitBreaker._instances.add(self)

    def _get(self, target: str) -> _Target:   # requires-lock: _lock
        t = self._targets.get(target)
        if t is None:
            t = self._targets.setdefault(target, _Target())
        return t

    def _set_state(self, target: str, t: _Target, state: str):
        t.state = state
        _m_breaker_state.labels(breaker=self.name,
                                target=target).set(_STATE_NUM[state])

    def allow(self, target: str = "default") -> bool:
        with self._lock:
            t = self._get(target)
            if t.state == "closed":
                return True
            if t.state == "open":
                if self._clock() - t.opened_at < self.reset_timeout:
                    _m_breaker_short.labels(breaker=self.name,
                                            target=target).inc()
                    return False
                self._set_state(target, t, "half_open")
                t.probes = 0
            # half-open: admit up to half_open_max concurrent probes
            if t.probes < self.half_open_max:
                t.probes += 1
                return True
            _m_breaker_short.labels(breaker=self.name, target=target).inc()
            return False

    def record(self, target: str = "default", ok: bool = True):
        # the state transition is decided under the lock; log + tracer
        # emission happens AFTER release — log handlers do stream/file IO
        # and the tracer takes its own lock, and neither may stall every
        # thread contending this breaker (graftlint: lock-blocking-call)
        transition = None
        with self._lock:
            t = self._get(target)
            if ok:
                if t.state != "closed":
                    transition = "close"
                t.failures = 0
                t.probes = 0
                self._set_state(target, t, "closed")
            elif t.state == "half_open" or (
                    t.state == "closed"
                    and t.failures + 1 >= self.failure_threshold):
                t.opened_at = self._clock()
                t.failures = 0
                t.probes = 0
                if t.state != "open":
                    transition = "open"
                    _m_breaker_opens.labels(breaker=self.name,
                                            target=target).inc()
                self._set_state(target, t, "open")
            else:
                t.failures += 1
        if transition == "close":
            telemetry.trace.instant("breaker/close", breaker=self.name,
                                    target=target)
            log.info("breaker %s/%s: probe ok, closing circuit",
                     self.name, target)
        elif transition == "open":
            telemetry.trace.instant("breaker/open", breaker=self.name,
                                    target=target)
            log.warning("breaker %s/%s: opening circuit for %.2fs",
                        self.name, target, self.reset_timeout)

    def call(self, fn: Callable, target: str = "default"):
        """Run ``fn()`` through the circuit: short-circuit with
        :class:`BreakerOpen` when open, record the outcome otherwise."""
        if not self.allow(target):
            raise BreakerOpen(self.name, target)
        try:
            result = fn()
        except Exception:
            self.record(target, ok=False)
            raise
        self.record(target, ok=True)
        return result

    def state(self, target: str = "default") -> str:
        with self._lock:
            return self._get(target).state

    def reset(self, target: Optional[str] = None):
        """Force closed (a supervisor restoring a worker resets its
        circuit so the first poll isn't short-circuited)."""
        with self._lock:
            targets = ([target] if target is not None
                       else list(self._targets))
            for tg in targets:
                t = self._targets.get(tg)
                if t is not None:
                    t.failures = 0
                    t.probes = 0
                    self._set_state(tg, t, "closed")

    def snapshot(self) -> dict:
        with self._lock:
            return {tg: t.state for tg, t in sorted(self._targets.items())}

    @classmethod
    def snapshot_all(cls) -> dict:
        """{breaker_name: {target: state}} for every live breaker in this
        process — the ``GET /healthz`` breaker report."""
        out: dict = {}
        for b in list(cls._instances):
            snap = b.snapshot()
            if snap:
                out.setdefault(b.name, {}).update(snap)
        return out
