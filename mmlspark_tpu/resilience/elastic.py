"""Elastic multi-host training: heartbeats, death verdicts, re-mesh, resume.

PR 3's resilience machinery (RetryPolicy, faults, FleetSupervisor, step
checkpoints) protects *serving*; training still died with its first lost
host. This module extends the same model to ``TpuLearner.fit``: a fit that
loses a host **re-meshes over the survivors and resumes from the latest
consensus checkpoint**, losing zero committed steps — the fault-tolerant
distributed-training posture of the reference's distributed LightGBM
lineage (PAPER.md L5), rebuilt on XLA collectives, and the
barrier-execution recovery shape of JAMPI (arxiv 2007.01811: a failed
collective stage re-runs from its barrier, here the checkpoint).

Three pieces:

* :class:`HostHeartbeat` — one per host, a background thread writing
  ``hb_<host>.json`` (atomic write-then-rename, like checkpoints) into a
  directory on the job's shared storage every ``interval`` seconds, carrying
  the host's latest committed ``(epoch, step)``. A host that stops beating
  *is* the failure signal — a preempted VM cannot be asked.
* :class:`TrainSupervisor` — the :class:`~.supervisor.FleetSupervisor`
  sibling for training fleets. Probes heartbeat ages (fault site
  ``supervisor.heartbeat``), declares a host dead once its heartbeat is
  older than the ``grace`` window, and answers the restart-vs-shrink
  question: **shrink** while the survivors still satisfy ``min_hosts``,
  **restart** (give up in-job, let the launcher relaunch against the same
  checkpointDir) below it.
* :class:`ElasticFitCoordinator` — drives ``learner._fit_core`` in a
  recovery loop. Every optimizer step passes through
  :meth:`ElasticStepContext.check_step` (fault site ``elastic.step``;
  transient errors ride the trainer's existing retry-once policy); a death
  verdict on a mesh member raises :class:`HostLossError` out of the step
  loop, and the coordinator then re-meshes (fault site ``elastic.remesh``):
  rebuilds the device pool from the surviving hosts, re-creates the
  ``parallel/mesh`` mesh, re-places params, and re-enters the fit — which
  resumes from the ``(epoch, step)`` consensus checkpoint
  (``checkpointEverySteps`` format), so every step that reached a
  checkpoint survives the loss bit-exactly.

The loop also closes the other way — **in-job grow**: a relaunched host
writes its heartbeat with a ``joining`` flag; the supervisor turns
sustained freshness through the *rejoin grace* window into a **grow
verdict** (fault site ``supervisor.rejoin``), and the coordinator admits
the joiner at the next committed **checkpoint boundary**
(:class:`HostRejoinError` unwinds the step loop exactly like a loss,
pointed the other way), re-meshing over survivors + joiner with
``max_hosts`` capping the pool — replays only, no fleet restart. The
JAMPI barrier-execution shape again: the consensus checkpoint is the
barrier a gang-scheduled re-entry targets.

Single-process mode rehearses the full recovery path with *simulated*
hosts (contiguous device groups, ``mesh.host_device_groups``): killing a
group's heartbeat exercises verdict -> re-mesh -> resume exactly as a real
preemption would (and :meth:`ElasticFitCoordinator.relaunch_host` the
grow half), which is what the tier-1 chaos tests and
``bench.py --chaos-train`` drive. Multi-process mode runs the same
heartbeats and verdicts, but an in-job re-mesh is impossible once
``jax.distributed`` has lost a member — there the coordinator's job is to
fail FAST and cleanly (HostLossError instead of a hung collective), so the
launcher can relaunch the fleet smaller against the same checkpointDir;
the consensus-resume logic picks it up from the last committed step.

Env knobs: ``MMLSPARK_TPU_ELASTIC_GRACE`` (death-verdict window, seconds;
the ``elasticGraceSeconds`` param overrides), ``MMLSPARK_TPU_ELASTIC_HB``
(heartbeat write interval, default grace/4).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults
from .policy import default_transient

log = get_logger("resilience.elastic")

_m_host_losses = telemetry.registry.counter(
    "mmlspark_elastic_host_losses_total",
    "hosts declared dead by the train supervisor", labels=("host",))
_m_remeshes = telemetry.registry.counter(
    "mmlspark_elastic_remeshes_total",
    "fit recoveries that rebuilt the mesh over surviving hosts")
_m_attempt_failures = telemetry.registry.counter(
    "mmlspark_elastic_attempt_failures_total",
    "elastic fit attempts that ended in a classified-transient failure "
    "without a host verdict (retried on the same mesh)")
_m_recovery_seconds = telemetry.registry.histogram(
    "mmlspark_elastic_recovery_seconds",
    "host-loss detection -> first optimizer step committed on the "
    "re-meshed (or retried) fit")
_m_hosts_alive = telemetry.registry.gauge(
    "mmlspark_elastic_hosts_alive",
    "hosts currently alive in the elastic training fleet")
_m_steps_replayed = telemetry.registry.counter(
    "mmlspark_elastic_steps_replayed_total",
    "committed-but-unchekpointed steps re-run after a resume (the work a "
    "smaller checkpointEverySteps would have saved)")
_m_stragglers = telemetry.registry.counter(
    "mmlspark_elastic_stragglers_total",
    "hosts flagged anomalously slow by the rolling-MAD step-time "
    "detector (each flag episode counts once)", labels=("host",))
_m_rejoins = telemetry.registry.counter(
    "mmlspark_elastic_rejoins_total",
    "grow verdicts: relaunched hosts whose joining heartbeat stayed "
    "fresh through the rejoin grace window", labels=("host",))
_m_grows = telemetry.registry.counter(
    "mmlspark_elastic_grows_total",
    "fit recoveries that re-meshed the fleet LARGER (joiners admitted "
    "at a checkpoint boundary)")
_m_grow_recovery_seconds = telemetry.registry.histogram(
    "mmlspark_elastic_grow_recovery_seconds",
    "grow re-mesh start -> first optimizer step committed on the grown "
    "mesh (the cost of admitting a rejoined host)")
_m_heartbeat_errors = telemetry.registry.counter(
    "mmlspark_elastic_heartbeat_errors_total",
    "heartbeat writes that exhausted their retry budget (shared-FS "
    "trouble; the beacon thread stays alive and keeps trying)",
    labels=("host",))


class HostLossError(RuntimeError):
    """A mesh-member host was declared dead mid-fit. Deliberately NOT a
    ConnectionError: the per-step retry policy must not absorb it — the
    recovery is a re-mesh + checkpoint resume, not a redispatch."""

    def __init__(self, hosts):
        self.hosts = sorted(hosts)
        super().__init__(f"host(s) {', '.join(self.hosts)} declared dead "
                         f"mid-fit")


class HostRejoinError(RuntimeError):
    """A relaunched host earned a grow verdict and a checkpoint boundary
    has committed since: the step loop unwinds so the coordinator can
    re-mesh over survivors + joiner. NOT an error condition — it is the
    same unwind mechanism a host loss uses, pointed the other way (the
    fleet gets bigger). Deliberately not a ConnectionError: the per-step
    retry must not absorb it."""

    def __init__(self, hosts):
        self.hosts = sorted(hosts)
        super().__init__(f"host(s) {', '.join(self.hosts)} rejoining "
                         f"at checkpoint boundary")


class ElasticFleetLost(RuntimeError):
    """Survivors fell below ``min_hosts`` (or the failure budget ran out):
    in-job recovery is off the table; relaunch the fleet against the same
    checkpointDir to resume."""


def _grace_default() -> float:
    try:
        return float(os.environ.get("MMLSPARK_TPU_ELASTIC_GRACE", "") or 2.0)
    except ValueError:
        return 2.0


def _hb_interval_default(grace: float) -> float:
    try:
        v = os.environ.get("MMLSPARK_TPU_ELASTIC_HB", "")
        return float(v) if v else max(0.05, grace / 4.0)
    except ValueError:
        return max(0.05, grace / 4.0)


def heartbeat_dir(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, "heartbeats")


class HostHeartbeat:
    """Background liveness beacon for one host.

    Writes ``hb_<host>.json`` with ``{host, time, epoch, step}`` every
    ``interval`` seconds (write-then-rename: a torn read must never look
    like a dead host). ``beat(epoch, step)`` advances the progress the
    file carries; :meth:`kill` stops the thread WITHOUT a farewell write —
    the simulated-preemption switch chaos tests flip (a real preemption
    stops mid-air the same way).
    """

    def __init__(self, host_id: str, directory: str, interval: float,
                 joining: bool = False):
        from .policy import RetryPolicy
        self.host_id = host_id
        self.directory = directory
        self.interval = interval
        self._lock = threading.Lock()
        self._pos = (0, -1)          # guarded-by: _lock
        self._joining = joining      # guarded-by: _lock
        self._stop = threading.Event()
        # transient shared-FS hiccups must not silence the beacon — a
        # silent beacon IS a death verdict. Retry each write; exhaustion
        # is counted and survived (the next interval tries again).
        self._retry = RetryPolicy(name="elastic.heartbeat", max_attempts=3,
                                  base_delay=min(0.05, interval / 4),
                                  max_delay=max(0.05, interval / 2),
                                  retryable=lambda e: isinstance(
                                      e, (OSError, ValueError)))
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"heartbeat-{host_id}")

    @property
    def path(self) -> str:
        return os.path.join(self.directory, f"hb_{self.host_id}.json")

    def beat(self, epoch: int, step: int):
        with self._lock:
            self._pos = (epoch, step)

    def set_joining(self, joining: bool):
        """Flip the rejoin flag the next write carries. A relaunched host
        starts with ``joining=True``; the coordinator clears it once the
        host is admitted back into the mesh."""
        with self._lock:
            self._joining = joining

    def _write(self):
        with self._lock:
            (epoch, step), joining = self._pos, self._joining
        doc = {"host": self.host_id, "time": time.time(),
               "epoch": epoch, "step": step}
        if joining:
            doc["joining"] = True
        tmp = f"{self.path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)

    def _run(self):
        while not self._stop.is_set():
            try:
                self._retry.run(lambda _a: self._write())
            except Exception as e:   # exhausted: count, survive, retry
                _m_heartbeat_errors.labels(host=self.host_id).inc()
                log.warning("heartbeat %s write failed after retries: %s",
                            self.host_id, e)
            self._stop.wait(self.interval)

    def start(self) -> "HostHeartbeat":
        os.makedirs(self.directory, exist_ok=True)
        self._write()
        self._thread.start()
        return self

    def stop(self):
        """Clean shutdown (fit finished): final write then join, so a
        supervisor that outlives the fit doesn't read a stale file age."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2)

    def kill(self):
        """Simulated preemption: the beacon stops mid-air, no final write.
        The supervisor's grace window turns the silence into a verdict."""
        self._stop.set()


class TrainSupervisor:
    """Death-verdict loop over an elastic training fleet's heartbeats.

    The :class:`~.supervisor.FleetSupervisor` sibling: same tick/thread
    shape, but the subjects are training hosts (heartbeat files on shared
    storage) rather than serving workers (HTTP health probes), and the
    remedy is a re-mesh rather than a respawn — dead training hosts are
    *removed*, not restarted, because the collective program must shrink
    with them.

    ``probe(host_id) -> age_seconds | None`` is pluggable (tests inject
    clocks); the default reads the heartbeat file's ``time`` field. A host
    whose heartbeat is older than ``grace`` — or unreadable past the same
    window — is declared dead exactly once; verdicts are sticky (a zombie
    heartbeat resuming after its verdict stays dead: its devices left the
    mesh, rejoining means relaunching).
    """

    def __init__(self, host_ids, directory: str,
                 grace: Optional[float] = None,
                 min_hosts: int = 1,
                 probe: Optional[Callable] = None,
                 probe_interval: Optional[float] = None,
                 anomaly_detector=None,
                 rejoin_grace: Optional[float] = None):
        from ..telemetry.slo import StepTimeAnomalyDetector
        self.host_ids = list(host_ids)
        self.directory = directory
        self.grace = grace if grace is not None else _grace_default()
        self.min_hosts = max(1, min_hosts)
        #: how long a relaunched host's ``joining`` heartbeat must stay
        #: fresh before the GROW verdict lands (its own window, symmetric
        #: to the death grace: a flapping relauncher must not churn the
        #: mesh). Default: the death grace.
        self.rejoin_grace = (rejoin_grace if rejoin_grace is not None
                             else self.grace)
        self._probe = probe or self._probe_file
        self.probe_interval = (probe_interval if probe_interval is not None
                               else max(0.05, self.grace / 4.0))
        #: rolling-MAD step-time detector fed from heartbeat progress; a
        #: STRAGGLER verdict (consistently slow, still beating) is advisory
        #: — reported, never a death verdict (pass anomaly_detector=False
        #: to disable, or inject a configured detector)
        self.anomaly = (StepTimeAnomalyDetector()
                        if anomaly_detector is None
                        else (anomaly_detector or None))
        self._lock = threading.Lock()
        self._dead: set[str] = set()        # guarded-by: _lock
        self._joining: dict[str, float] = {}     # guarded-by: _lock
        self._join_seen: dict[str, float] = {}   # guarded-by: _lock
        self._progress: dict[str, tuple] = {}    # guarded-by: _lock
        self._flagged: set[str] = set()     # guarded-by: _lock
        self._started_at = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="train-supervisor")
        _m_hosts_alive.set(len(self.host_ids))

    # ---- probing ----
    def _read_doc(self, host_id: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.directory,
                                   f"hb_{host_id}.json"),
                      "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _probe_file(self, host_id: str) -> Optional[float]:
        """Heartbeat age in seconds; None when the file is missing or
        unreadable (counted against the host once the startup grace is
        spent — a host that never wrote at all is as dead as one that
        stopped)."""
        doc = self._read_doc(host_id)
        if doc is None:
            return None
        try:
            age = max(0.0, time.time() - float(doc["time"]))
        except (KeyError, TypeError, ValueError):
            return None
        self._note_progress(host_id, doc)
        return age

    def _note_progress(self, host_id: str, doc: dict):
        """Feed the anomaly detector from heartbeat progress: successive
        probes of the same epoch yield (wall delta / steps advanced) — a
        central seconds-per-step estimate that needs no new wire format."""
        if self.anomaly is None:
            return
        try:
            cur = (int(doc["epoch"]), int(doc["step"]), float(doc["time"]))
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            prev = self._progress.get(host_id)
            self._progress[host_id] = cur
        if prev is None:
            return
        pe, ps, pt = prev
        e, s, t = cur
        if e == pe and s > ps and t > pt:
            self.anomaly.observe(host_id, (t - pt) / (s - ps))

    def tick(self):
        """One verdict pass (public: deterministic tests drive it directly,
        the background thread calls it on ``probe_interval``)."""
        verdicts = []
        for host_id in self.host_ids:
            with self._lock:
                if host_id in self._dead:
                    continue
            faults.inject("supervisor.heartbeat")
            age = self._probe(host_id)
            if age is None:
                # missing file: only fatal once the fleet has had time to
                # write its first beats
                if time.monotonic() - self._started_at < self.grace:
                    continue
                verdicts.append((host_id, None))
            elif age > self.grace:
                verdicts.append((host_id, age))
        for host_id, age in verdicts:
            with self._lock:
                if host_id in self._dead:
                    continue
                self._dead.add(host_id)
                alive = len(self.host_ids) - len(self._dead)
            # verdict bookkeeping is IO (log/trace/metrics): after release
            _m_host_losses.labels(host=host_id).inc()
            _m_hosts_alive.set(alive)
            telemetry.trace.instant("elastic/host_loss", host=host_id,
                                    age=age)
            telemetry.flight.note("elastic/host_loss", host=host_id,
                                  age=age, alive=alive)
            log.warning(
                "host %s declared DEAD (heartbeat %s, grace %.2fs); "
                "%d host(s) remain", host_id,
                "missing" if age is None else f"{age:.2f}s old",
                self.grace, alive)
        self._grow_pass()
        self._straggler_pass()

    def _grow_pass(self):
        """GROW verdicts — the death pass's mirror. A dead host whose
        heartbeat file is beating again WITH the ``joining`` flag is a
        relaunch (not a zombie: sticky death still holds for flagless
        resurrections); once it has stayed fresh through ``rejoin_grace``
        the host earns a grow verdict the coordinator can admit at the
        next checkpoint boundary. Verdict bookkeeping decided under the
        lock; IO after release."""
        with self._lock:
            candidates = [h for h in self._dead if h not in self._joining]
        verdicts = []
        for host_id in candidates:
            faults.inject("supervisor.rejoin")
            doc = self._read_doc(host_id)
            fresh = (doc is not None and doc.get("joining")
                     and time.time() - float(doc.get("time", 0))
                     <= self.grace)
            now = time.monotonic()
            with self._lock:
                if not fresh:
                    # stale or flagless: the relaunch flapped (or was a
                    # zombie); restart its window
                    self._join_seen.pop(host_id, None)
                    continue
                t0 = self._join_seen.setdefault(host_id, now)
                if now - t0 < self.rejoin_grace:
                    continue
                self._join_seen.pop(host_id, None)
                self._joining[host_id] = now
            verdicts.append(host_id)
        for host_id in verdicts:
            _m_rejoins.labels(host=host_id).inc()
            telemetry.trace.instant("elastic/rejoin", host=host_id)
            telemetry.flight.note("elastic/rejoin", host=host_id)
            log.warning("host %s earned a GROW verdict (joining heartbeat "
                        "fresh through the %.2fs rejoin window); eligible "
                        "to re-enter the mesh at the next checkpoint "
                        "boundary", host_id, self.rejoin_grace)

    def joining_hosts(self) -> dict:
        """Hosts holding a grow verdict -> verdict time (monotonic). The
        coordinator admits them at the next checkpoint boundary."""
        with self._lock:
            return dict(self._joining)

    def admit(self, host_id: str):
        """The coordinator admitted a rejoined host back into the mesh:
        clear its death verdict and grow state so the death pass watches
        it again."""
        with self._lock:
            self._dead.discard(host_id)
            self._joining.pop(host_id, None)
            self._join_seen.pop(host_id, None)
            alive = len(self.host_ids) - len(self._dead)
        _m_hosts_alive.set(alive)

    def _straggler_pass(self):
        """Advisory anomaly verdicts: flag hosts the rolling-MAD detector
        calls stragglers (and unflag recovered ones so a relapse re-flags).
        Flag bookkeeping is decided under the lock; the IO (metrics,
        instants, flight notes, logs) happens after release."""
        if self.anomaly is None:
            return
        current = self.anomaly.stragglers()
        with self._lock:
            current -= self._dead
            newly = current - self._flagged
            self._flagged = current
        med = self.anomaly.host_medians() if newly else {}
        for host_id in sorted(newly):
            _m_stragglers.labels(host=host_id).inc()
            telemetry.trace.instant("elastic/straggler", host=host_id,
                                    median_s=med.get(host_id))
            telemetry.flight.note("elastic/straggler", host=host_id,
                                  median_s=med.get(host_id))
            log.warning("host %s flagged as STRAGGLER (median step "
                        "%.4fs vs fleet %s); still alive — advisory only",
                        host_id, med.get(host_id, float("nan")),
                        {h: round(v, 4) for h, v in med.items()})

    def straggler_hosts(self) -> set[str]:
        """Hosts currently flagged anomalously slow (advisory — they are
        alive and beating, just burning the step-time budget)."""
        with self._lock:
            return set(self._flagged)

    def dead_hosts(self) -> set[str]:
        with self._lock:
            return set(self._dead)

    def alive_hosts(self) -> list[str]:
        with self._lock:
            return [h for h in self.host_ids if h not in self._dead]

    def decision(self) -> str:
        """``"shrink"`` when the survivors can keep training in-job,
        ``"restart"`` when they cannot (relaunch against the same
        checkpointDir — consensus resume carries the run over)."""
        return ("shrink" if len(self.alive_hosts()) >= self.min_hosts
                else "restart")

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:   # a probe bug must not kill the loop
                log.warning("train-supervisor tick failed: %s", e)
            self._stop.wait(self.probe_interval)

    def clear_stale_heartbeats(self):
        """Remove ``hb_*.json`` ghosts from a PREVIOUS run (older than the
        grace window): without this a supervisor starting against a reused
        checkpointDir reads last week's heartbeat and declares an instant
        death (or an instant zombie) before the relaunched fleet writes
        its first beat. Fresh files — this run's — are untouched."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not (name.startswith("hb_") and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    stamp = float(json.load(f).get("time", 0))
                stale = time.time() - stamp > self.grace
            except (OSError, ValueError, TypeError):
                stale = True     # unreadable ghosts go too
            if stale:
                try:
                    os.remove(path)
                    log.info("cleared stale heartbeat %s from a previous "
                             "run", name)
                except OSError:
                    pass

    def start(self) -> "TrainSupervisor":
        self.clear_stale_heartbeats()
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


class ElasticStepContext:
    """The per-step hook the trainer's dispatch loop calls during an
    elastic fit. Cheap when nothing is wrong: one fault-site check and one
    set read per optimizer step."""

    def __init__(self, coordinator: "ElasticFitCoordinator"):
        self._coord = coordinator

    def check_step(self):
        """Runs inside the step dispatch, BEFORE the device work. An
        injected ``elastic.step`` fault is a ConnectionError — the
        trainer's retry-once policy absorbs singles, doubles escalate to
        the coordinator's transient classification. A death verdict on a
        mesh member raises :class:`HostLossError`; a grow verdict with a
        checkpoint boundary committed behind it raises
        :class:`HostRejoinError` (both non-transient: they skip the retry
        and unwind to the coordinator's re-mesh)."""
        faults.inject("elastic.step")
        dead = self._coord.dead_mesh_hosts()
        if dead:
            raise HostLossError(dead)
        grow = self._coord.pending_grow()
        if grow:
            raise HostRejoinError(grow)

    def step_committed(self, epoch: int, step: int):
        """The trainer reports each completed optimizer step: advances
        this process's heartbeat progress, closes any pending
        recovery-time measurement, and feeds the committed-step journal
        the chaos tests audit for gaps."""
        self._coord.note_step(epoch, step)

    def checkpoint_saved(self, epoch: int, step: Optional[int]):
        """A checkpoint COMMITTED (rename + manifest durable — on the
        async path this fires from the writer thread strictly after the
        commit, never at submit). Checkpoint boundaries are where grow
        re-meshes become eligible: a joiner admitted here replays ~zero
        steps."""
        self._coord.note_checkpoint(epoch, step)

    def resumed(self, pos, params_digest: Optional[str]):
        """The trainer reports the checkpoint position (or None for a
        fresh start) and a digest of the restored params — the bit-exact
        resume evidence."""
        self._coord.note_resume(pos, params_digest)

    # ---- in-memory boosting-state candidates (elastic GBDT fits) ----
    def save_snapshot(self, state):
        """The GBDT engine's per-iteration boosting-state candidate
        (newest wins): host-side arrays a re-meshed attempt resumes
        from. Pair with :meth:`checkpoint_saved` so grow boundaries work
        for boosted fits too."""
        self._coord.snapshot = state

    def latest_snapshot(self):
        return self._coord.snapshot


class ElasticFitCoordinator:
    """Drives a ``TpuLearner`` fit through host loss.

    ``fit(df)``: build the host groups, start heartbeats + the
    supervisor, then loop ``learner._fit_core(df, devices=pool,
    elastic_ctx=ctx)`` until it returns a model. A
    :class:`HostLossError` (or an exhausted-transient failure that a
    fresh verdict pass attributes to a dead host) triggers the re-mesh:
    survivors' devices become the new pool, and the next ``_fit_core``
    attempt resumes from the latest consensus checkpoint. Failures with
    *no* dead host burn the ``max_failures`` budget and retry on the same
    mesh — persistent infrastructure trouble must not loop forever.
    """

    def __init__(self, learner=None, n_hosts: int = 0,
                 min_hosts: int = 1,
                 grace: Optional[float] = None,
                 max_failures: int = 5,
                 heartbeat_interval: Optional[float] = None,
                 max_hosts: int = 0,
                 rejoin_grace: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None):
        ckdir = checkpoint_dir or (learner.getCheckpointDir()
                                   if learner is not None else "")
        if not ckdir:
            raise ValueError(
                "elastic fit requires checkpointDir: recovery is a resume "
                "from the consensus checkpoint — without one a host loss "
                "restarts from scratch, losing every committed step")
        self.learner = learner
        self.checkpoint_dir = ckdir
        self.grace = grace if grace is not None else _grace_default()
        self.min_hosts = max(1, min_hosts)
        self.max_failures = max(1, max_failures)
        self._hb_interval = (heartbeat_interval
                             if heartbeat_interval is not None
                             else _hb_interval_default(self.grace))
        from ..parallel import mesh as meshlib
        self.groups = dict(meshlib.host_device_groups(n_hosts))
        #: grow ceiling: the mesh never grows past this many hosts
        #: (0 = the launch fleet size)
        self.max_hosts = max_hosts or len(self.groups)
        self.hb_dir = heartbeat_dir(ckdir)
        self.heartbeats = {h: HostHeartbeat(h, self.hb_dir,
                                            self._hb_interval)
                           for h in self.groups}
        self.supervisor = TrainSupervisor(
            list(self.groups), self.hb_dir, grace=self.grace,
            min_hosts=self.min_hosts, rejoin_grace=rejoin_grace)
        self.attempts: list[dict] = []   # per-attempt journal (tests/bench)
        self.committed: list[tuple] = []   # (epoch, step) journal
        self.snapshot = None   # GBDT boosting-state candidate (newest wins)
        self._mesh_hosts: set[str] = set()
        self._pending_recovery_t0: Optional[float] = None
        self._recovery_kind = "loss"
        self._last_ckpt_pos: Optional[tuple] = None
        self._last_ckpt_t: Optional[float] = None

    # ---- state read by the step hook (fit thread) ----
    def dead_mesh_hosts(self) -> set[str]:
        return self.supervisor.dead_hosts() & self._mesh_hosts

    def pending_grow(self) -> set[str]:
        """Joiners eligible to enter at THIS step: they hold a grow
        verdict, a checkpoint boundary has committed since the verdict
        (so the re-entry replays ~zero steps), and the ``max_hosts``
        ceiling leaves room. Cheap when nobody is joining: one dict read
        under the supervisor lock."""
        join = self.supervisor.joining_hosts()
        if not join:
            return set()
        room = self.max_hosts - len(self._mesh_hosts)
        if room <= 0:
            return set()
        ckpt_t = self._last_ckpt_t
        eligible = sorted(h for h, t in join.items()
                          if h not in self._mesh_hosts
                          and ckpt_t is not None and ckpt_t >= t)
        return set(eligible[:room])

    def note_step(self, epoch: int, step: int):
        self.committed.append((epoch, step))
        for h in self._mesh_hosts:
            self.heartbeats[h].beat(epoch, step)
        if self._pending_recovery_t0 is not None:
            dt = time.monotonic() - self._pending_recovery_t0
            self._pending_recovery_t0 = None
            if self._recovery_kind == "grow":
                _m_grow_recovery_seconds.observe(dt)
                self.attempts[-1]["grow_recovery_s"] = dt
                log.info("elastic grow complete: first step committed "
                         "%.2fs after the grow re-mesh began", dt)
            else:
                _m_recovery_seconds.observe(dt)
                self.attempts[-1]["recovery_s"] = dt
                log.info("elastic recovery complete: first step committed "
                         "%.2fs after the failure", dt)

    def note_checkpoint(self, epoch: int, step: Optional[int]):
        """A checkpoint committed durably (rename + manifest). Marks the
        grow boundary: verdicts older than this instant become
        admissible."""
        self._last_ckpt_pos = (epoch, step)
        self._last_ckpt_t = time.monotonic()

    def note_resume(self, pos, params_digest):
        self._last_ckpt_pos = pos
        self.attempts[-1]["resume_pos"] = pos
        self.attempts[-1]["resume_digest"] = params_digest
        if pos is not None and self.committed:
            # steps the previous attempt committed past the checkpoint are
            # about to be re-run — the measurable cost of the ckpt interval
            e, s = pos
            replay = sum(1 for (ce, cs) in self.committed
                         if (ce, cs) > (e, -1 if s is None else s))
            if replay:
                _m_steps_replayed.inc(replay)

    # ---- the recovery loop ----
    def _pool(self) -> list:
        self._mesh_hosts = set(self.supervisor.alive_hosts())
        return [d for h in sorted(self._mesh_hosts)
                for d in self.groups[h]]

    def fit(self, df):
        """Drive ``learner.fit``'s core through the recovery loop."""
        return self.run(lambda devices, ctx: self.learner._fit_core(
            df, devices=devices, elastic_ctx=ctx))

    def fit_stream(self, batches_fn):
        """Drive ``learner.fitStream``'s core through the recovery loop:
        a host loss re-meshes and re-enters the stream (the epoch
        restarts — a generator cannot seek — with the checkpointed
        optimizer state kept)."""
        return self.run(lambda devices, ctx: self.learner._fit_stream_core(
            batches_fn, devices=devices, elastic_ctx=ctx))

    def relaunch_host(self, host_id: str) -> HostHeartbeat:
        """Simulated-preemption RELAUNCH (single-process failure domains:
        chaos tests, ``bench.py --chaos-train``): replace a killed host's
        beacon with a fresh one carrying the ``joining`` flag — exactly
        the heartbeat a real relaunched host process writes on boot. The
        supervisor turns its sustained freshness into a grow verdict."""
        if host_id not in self.groups:
            raise ValueError(f"unknown host {host_id!r}")
        old = self.heartbeats.get(host_id)
        if old is not None:
            old.kill()
        hb = HostHeartbeat(host_id, self.hb_dir, self._hb_interval,
                           joining=True)
        self.heartbeats[host_id] = hb
        hb.start()
        return hb

    def run(self, attempt_fn):
        """The recovery loop: ``attempt_fn(devices, ctx)`` until it
        returns. :class:`HostLossError` shrinks the mesh,
        :class:`HostRejoinError` grows it back (both re-enter from the
        consensus checkpoint); transient failures without a verdict burn
        the ``max_failures`` budget on the same mesh."""
        from ..parallel import mesh as meshlib
        if meshlib.effective_process_count() > 1:
            # real multi-process fleet: heartbeats + verdicts run (fast,
            # clean failure instead of a hung collective), but an in-job
            # re-mesh cannot outlive a jax.distributed member loss — the
            # launcher relaunches the fleet and consensus-resume
            # continues (growing back to full size counts as the grow)
            return self._run_multiprocess(attempt_fn)
        ctx = ElasticStepContext(self)
        for h in self.heartbeats.values():
            h.start()
        self.supervisor.start()
        failures = 0
        try:
            while True:
                pool = self._pool()
                self.attempts.append({"hosts": sorted(self._mesh_hosts),
                                      "devices": len(pool)})
                try:
                    with telemetry.trace.span("elastic/attempt",
                                              hosts=len(self._mesh_hosts),
                                              devices=len(pool)):
                        return attempt_fn(pool, ctx)
                except HostLossError as e:
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "loss"
                    self._remesh(e.hosts)
                except HostRejoinError as e:
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "grow"
                    self._grow(e.hosts)
                except Exception as e:
                    if not default_transient(e):
                        raise
                    # transient exhaustion with no verdict yet: force a
                    # probe pass — the failure may BE the dying host
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "loss"
                    self.supervisor.tick()
                    dead = self.dead_mesh_hosts()
                    if dead:
                        self._remesh(dead, cause=e)
                    else:
                        failures += 1
                        _m_attempt_failures.inc()
                        if failures >= self.max_failures:
                            raise ElasticFleetLost(
                                f"elastic fit failed {failures} times "
                                f"without a host verdict; last error: "
                                f"{e!r}") from e
                        log.warning(
                            "elastic fit attempt failed transiently (%r); "
                            "retrying from the latest checkpoint on the "
                            "same mesh (%d/%d)", e, failures,
                            self.max_failures)
        finally:
            self.supervisor.stop()
            for h in self.heartbeats.values():
                h.stop()

    def _grow(self, hosts):
        """Admit grow-verdict holders back into the mesh (capped by
        ``max_hosts``) and re-enter the fit: the next attempt's pool is
        survivors + joiners and resumes from the checkpoint boundary
        that armed the grow — replays only, no fleet restart."""
        faults.inject("elastic.remesh")
        admitted = []
        for h in sorted(hosts):
            if len(self.supervisor.alive_hosts()) >= self.max_hosts:
                log.warning("host %s holds a grow verdict but the fleet "
                            "is at elasticMaxHosts (%d); leaving it "
                            "parked", h, self.max_hosts)
                break
            self.supervisor.admit(h)
            hb = self.heartbeats.get(h)
            if hb is not None:
                hb.set_joining(False)
            admitted.append(h)
        if not admitted:
            return
        _m_grows.inc()
        telemetry.trace.instant("elastic/grow",
                                joined=",".join(admitted),
                                alive=len(self.supervisor.alive_hosts()))
        telemetry.flight.note("elastic/grow", joined=admitted)
        log.warning(
            "growing the mesh: host(s) %s rejoin at checkpoint %s; "
            "%d host(s) in the pool", admitted, self._last_ckpt_pos,
            len(self.supervisor.alive_hosts()))

    def _remesh(self, dead_hosts, cause=None):
        faults.inject("elastic.remesh")
        if self.supervisor.decision() == "restart":
            raise ElasticFleetLost(
                f"{len(self.supervisor.alive_hosts())} host(s) alive < "
                f"min_hosts ({self.min_hosts}); relaunch the fleet against "
                f"checkpointDir {self.learner.getCheckpointDir()!r} to "
                f"resume from the last committed step")
        _m_remeshes.inc()
        telemetry.trace.instant("elastic/remesh",
                                dead=",".join(sorted(dead_hosts)),
                                alive=len(self.supervisor.alive_hosts()))
        telemetry.flight.note("elastic/remesh", dead=sorted(dead_hosts))
        log.warning(
            "re-meshing after loss of %s: %d host(s) remain; resuming "
            "from the consensus checkpoint%s", sorted(dead_hosts),
            len(self.supervisor.alive_hosts()),
            f" (trigger: {cause!r})" if cause is not None else "")

    def _run_multiprocess(self, attempt_fn):
        import jax
        host_id = f"host{jax.process_index()}"
        hb = self.heartbeats.get(host_id)
        ctx = ElasticStepContext(self)
        self._mesh_hosts = set(self.groups)
        if hb is not None:
            hb.start()
        self.supervisor.start()
        try:
            self.attempts.append({"hosts": sorted(self.groups),
                                  "devices": len(jax.devices())})
            return attempt_fn(None, ctx)
        finally:
            self.supervisor.stop()
            if hb is not None:
                hb.stop()
