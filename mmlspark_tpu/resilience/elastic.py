"""Elastic multi-host training: heartbeats, death verdicts, re-mesh, resume.

PR 3's resilience machinery (RetryPolicy, faults, FleetSupervisor, step
checkpoints) protects *serving*; training still died with its first lost
host. This module extends the same model to ``TpuLearner.fit``: a fit that
loses a host **re-meshes over the survivors and resumes from the latest
consensus checkpoint**, losing zero committed steps — the fault-tolerant
distributed-training posture of the reference's distributed LightGBM
lineage (PAPER.md L5), rebuilt on XLA collectives, and the
barrier-execution recovery shape of JAMPI (arxiv 2007.01811: a failed
collective stage re-runs from its barrier, here the checkpoint).

Three pieces:

* :class:`HostHeartbeat` — one per host, a background thread writing
  ``hb_<host>.json`` (atomic write-then-rename, like checkpoints) into a
  directory on the job's shared storage every ``interval`` seconds, carrying
  the host's latest committed ``(epoch, step)``. A host that stops beating
  *is* the failure signal — a preempted VM cannot be asked.
* :class:`TrainSupervisor` — the :class:`~.supervisor.FleetSupervisor`
  sibling for training fleets. Probes heartbeat ages (fault site
  ``supervisor.heartbeat``), declares a host dead once its heartbeat is
  older than the ``grace`` window, and answers the restart-vs-shrink
  question: **shrink** while the survivors still satisfy ``min_hosts``,
  **restart** (give up in-job, let the launcher relaunch against the same
  checkpointDir) below it.
* :class:`ElasticFitCoordinator` — drives ``learner._fit_core`` in a
  recovery loop. Every optimizer step passes through
  :meth:`ElasticStepContext.check_step` (fault site ``elastic.step``;
  transient errors ride the trainer's existing retry-once policy); a death
  verdict on a mesh member raises :class:`HostLossError` out of the step
  loop, and the coordinator then re-meshes (fault site ``elastic.remesh``):
  rebuilds the device pool from the surviving hosts, re-creates the
  ``parallel/mesh`` mesh, re-places params, and re-enters the fit — which
  resumes from the ``(epoch, step)`` consensus checkpoint
  (``checkpointEverySteps`` format), so every step that reached a
  checkpoint survives the loss bit-exactly.

The loop also closes the other way — **in-job grow**: a relaunched host
writes its heartbeat with a ``joining`` flag; the supervisor turns
sustained freshness through the *rejoin grace* window into a **grow
verdict** (fault site ``supervisor.rejoin``), and the coordinator admits
the joiner at the next committed **checkpoint boundary**
(:class:`HostRejoinError` unwinds the step loop exactly like a loss,
pointed the other way), re-meshing over survivors + joiner with
``max_hosts`` capping the pool — replays only, no fleet restart. The
JAMPI barrier-execution shape again: the consensus checkpoint is the
barrier a gang-scheduled re-entry targets.

Single-process mode rehearses the full recovery path with *simulated*
hosts (contiguous device groups, ``mesh.host_device_groups``): killing a
group's heartbeat exercises verdict -> re-mesh -> resume exactly as a real
preemption would (and :meth:`ElasticFitCoordinator.relaunch_host` the
grow half), which is what the tier-1 chaos tests and
``bench.py --chaos-train`` drive. Multi-process mode runs the same
heartbeats and verdicts, but an in-job re-mesh is impossible once
``jax.distributed`` has lost a member — there the coordinator's job is to
fail FAST and cleanly (HostLossError instead of a hung collective), so the
launcher can relaunch the fleet smaller against the same checkpointDir;
the consensus-resume logic picks it up from the last committed step.

Beyond loss and grow, the fleet is **proactive**: heartbeat docs carry a
monotonic ``seq`` counter, so every freshness verdict compares
reader-observed seq advancement against the reader's own monotonic clock
(one skewed wall clock can neither kill a healthy host nor keep a ghost);
sustained straggler verdicts from the rolling-MAD detector are promoted
(``evict_after`` consecutive flags, ``min_hosts`` floor, never the
coordinator host) into an **evict** at the next committed checkpoint
boundary — the slow host is dropped *before* it fails, replays only, and
rejoins through the grow path once recovered. REAL multi-process fleets
re-enter the same fit through ``parallel/distributed``'s
RendezvousCoordinator: coordinator-service restart on the surviving
lowest-rank host, generation-stamped membership, barrier re-entry — a
kill -9'd process relaunches and joins the running fit instead of
forcing a full-size relaunch.

Env knobs: ``MMLSPARK_TPU_ELASTIC_GRACE`` (death-verdict window, seconds;
the ``elasticGraceSeconds`` param overrides), ``MMLSPARK_TPU_ELASTIC_HB``
(heartbeat write interval, default grace/4), ``MMLTPU_REJOIN_TIMEOUT``
(how long a below-quorum fleet waits for rejoining hosts).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults
from .policy import default_transient

log = get_logger("resilience.elastic")

_m_host_losses = telemetry.registry.counter(
    "mmlspark_elastic_host_losses_total",
    "hosts declared dead by the train supervisor", labels=("host",))
_m_remeshes = telemetry.registry.counter(
    "mmlspark_elastic_remeshes_total",
    "fit recoveries that rebuilt the mesh over surviving hosts")
_m_attempt_failures = telemetry.registry.counter(
    "mmlspark_elastic_attempt_failures_total",
    "elastic fit attempts that ended in a classified-transient failure "
    "without a host verdict (retried on the same mesh)")
_m_recovery_seconds = telemetry.registry.histogram(
    "mmlspark_elastic_recovery_seconds",
    "host-loss detection -> first optimizer step committed on the "
    "re-meshed (or retried) fit")
_m_hosts_alive = telemetry.registry.gauge(
    "mmlspark_elastic_hosts_alive",
    "hosts currently alive in the elastic training fleet")
_m_steps_replayed = telemetry.registry.counter(
    "mmlspark_elastic_steps_replayed_total",
    "committed-but-unchekpointed steps re-run after a resume (the work a "
    "smaller checkpointEverySteps would have saved)")
_m_stragglers = telemetry.registry.counter(
    "mmlspark_elastic_stragglers_total",
    "hosts flagged anomalously slow by the rolling-MAD step-time "
    "detector (each flag episode counts once)", labels=("host",))
_m_rejoins = telemetry.registry.counter(
    "mmlspark_elastic_rejoins_total",
    "grow verdicts: relaunched hosts whose joining heartbeat stayed "
    "fresh through the rejoin grace window", labels=("host",))
_m_grows = telemetry.registry.counter(
    "mmlspark_elastic_grows_total",
    "fit recoveries that re-meshed the fleet LARGER (joiners admitted "
    "at a checkpoint boundary)")
_m_grow_recovery_seconds = telemetry.registry.histogram(
    "mmlspark_elastic_grow_recovery_seconds",
    "grow re-mesh start -> first optimizer step committed on the grown "
    "mesh (the cost of admitting a rejoined host)")
_m_heartbeat_errors = telemetry.registry.counter(
    "mmlspark_elastic_heartbeat_errors_total",
    "heartbeat writes that exhausted their retry budget (shared-FS "
    "trouble; the beacon thread stays alive and keeps trying)",
    labels=("host",))
_m_evictions = telemetry.registry.counter(
    "mmlspark_elastic_evictions_total",
    "proactive straggler EVICTIONS: hosts dropped from the mesh at a "
    "checkpoint boundary after sustaining straggler verdicts for "
    "evict_after consecutive passes (alive but slow; eligible to "
    "rejoin through the grow path once recovered)", labels=("host",))


class HostLossError(RuntimeError):
    """A mesh-member host was declared dead mid-fit. Deliberately NOT a
    ConnectionError: the per-step retry policy must not absorb it — the
    recovery is a re-mesh + checkpoint resume, not a redispatch."""

    def __init__(self, hosts):
        self.hosts = sorted(hosts)
        super().__init__(f"host(s) {', '.join(self.hosts)} declared dead "
                         f"mid-fit")


class HostEvictError(RuntimeError):
    """A sustained-straggler host earned an EVICT verdict and a
    checkpoint boundary has committed since: the step loop unwinds so
    the coordinator can re-mesh WITHOUT the slow host — the same unwind
    mechanism a host loss uses, fired *before* the host fails instead of
    after. The evicted host stays alive; once it recovers it rejoins
    through the ordinary joining-heartbeat grow path. Deliberately not a
    ConnectionError: the per-step retry must not absorb it."""

    def __init__(self, hosts):
        self.hosts = sorted(hosts)
        super().__init__(f"host(s) {', '.join(self.hosts)} evicted as "
                         f"sustained stragglers at checkpoint boundary")


class RendezvousPending(RuntimeError):
    """Multi-process fleets: the leader committed a rendezvous proposal
    whose ``unwind_at`` boundary this process has now reached — unwind
    the step loop and join the new generation. The deterministic unwind
    point (every process raises after the SAME committed step) is what
    keeps a grow/evict re-mesh from stranding peers mid-collective."""

    def __init__(self, generation: int):
        self.generation = generation
        super().__init__(f"rendezvous generation {generation} pending")


class HostRejoinError(RuntimeError):
    """A relaunched host earned a grow verdict and a checkpoint boundary
    has committed since: the step loop unwinds so the coordinator can
    re-mesh over survivors + joiner. NOT an error condition — it is the
    same unwind mechanism a host loss uses, pointed the other way (the
    fleet gets bigger). Deliberately not a ConnectionError: the per-step
    retry must not absorb it."""

    def __init__(self, hosts):
        self.hosts = sorted(hosts)
        super().__init__(f"host(s) {', '.join(self.hosts)} rejoining "
                         f"at checkpoint boundary")


class ElasticFleetLost(RuntimeError):
    """Survivors fell below ``min_hosts`` (or the failure budget ran out):
    in-job recovery is off the table; relaunch the fleet against the same
    checkpointDir to resume."""


def _grace_default() -> float:
    try:
        return float(os.environ.get("MMLSPARK_TPU_ELASTIC_GRACE", "") or 2.0)
    except ValueError:
        return 2.0


def _hb_interval_default(grace: float) -> float:
    try:
        v = os.environ.get("MMLSPARK_TPU_ELASTIC_HB", "")
        return float(v) if v else max(0.05, grace / 4.0)
    except ValueError:
        return max(0.05, grace / 4.0)


def heartbeat_dir(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, "heartbeats")


# ---- fleet-health surface (GET /healthz) -----------------------------------
# The serving stack's /healthz shows breakers/queue/uptime; an operator
# watching an elastic fit could previously only see fleet state by
# scraping metrics. The active coordinator registers here and every
# /healthz payload (serving server + fleet workers) embeds the snapshot.

_fleet_lock = threading.Lock()
_fleet = None                        # guarded-by: _fleet_lock


def _register_fleet(coord):
    global _fleet
    with _fleet_lock:
        _fleet = coord


def _unregister_fleet(coord):
    global _fleet
    with _fleet_lock:
        if _fleet is coord:
            _fleet = None


def fleet_health():
    """The active elastic fleet's state for ``GET /healthz`` (None when
    no elastic fit is running in this process): hosts alive/dead, the
    straggler set, pending evict/grow verdicts, and the current
    rendezvous generation."""
    with _fleet_lock:
        coord = _fleet
    if coord is None:
        return None
    sup = coord.supervisor
    alive = sup.alive_hosts()
    return {
        "hosts_alive": len(alive),
        "alive": alive,
        "dead": sorted(sup.dead_hosts()),
        "stragglers": sorted(sup.straggler_hosts()),
        "pending_evict": sorted(sup.evict_verdicts()),
        "pending_grow": sorted(sup.joining_hosts()),
        "mesh_hosts": sorted(coord._mesh_hosts),
        "rendezvous_generation": (coord._rdzv.generation
                                  if coord._rdzv is not None else 0),
    }


class HostHeartbeat:
    """Background liveness beacon for one host.

    Writes ``hb_<host>.json`` with ``{host, seq, time, epoch, step}``
    every ``interval`` seconds (write-then-rename: a torn read must never
    look like a dead host). ``seq`` is a per-beacon monotonic counter —
    the freshness signal readers actually trust: a verdict compares
    *reader-observed seq advancement* against the reader's own monotonic
    clock, so one host with a skewed wall clock can neither be falsely
    declared dead nor kept alive as a ghost. ``time`` stays in the doc as
    informational metadata (and the same-writer deltas the straggler
    detector consumes, which no cross-host skew can distort).
    ``beat(epoch, step)`` advances the progress the file carries;
    :meth:`kill` stops the thread WITHOUT a farewell write — the
    simulated-preemption switch chaos tests flip (a real preemption stops
    mid-air the same way); :meth:`throttle` makes the carried progress
    advance only every k-th beat — the simulated-STRAGGLER switch (the
    host is alive and beating, just slow)."""

    def __init__(self, host_id: str, directory: str, interval: float,
                 joining: bool = False):
        from .policy import RetryPolicy
        self.host_id = host_id
        self.directory = directory
        self.interval = interval
        self._lock = threading.Lock()
        self._pos = (0, -1)          # guarded-by: _lock
        self._joining = joining      # guarded-by: _lock
        self._seq = 0                # guarded-by: _lock
        self._generation = 0         # guarded-by: _lock
        self._throttle = 1           # guarded-by: _lock
        self._beats = 0              # guarded-by: _lock
        self._stop = threading.Event()
        # transient shared-FS hiccups must not silence the beacon — a
        # silent beacon IS a death verdict. Retry each write; exhaustion
        # is counted and survived (the next interval tries again).
        self._retry = RetryPolicy(name="elastic.heartbeat", max_attempts=3,
                                  base_delay=min(0.05, interval / 4),
                                  max_delay=max(0.05, interval / 2),
                                  retryable=lambda e: isinstance(
                                      e, (OSError, ValueError)))
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"heartbeat-{host_id}")

    @property
    def path(self) -> str:
        return os.path.join(self.directory, f"hb_{self.host_id}.json")

    def beat(self, epoch: int, step: int):
        with self._lock:
            self._beats += 1
            if self._throttle <= 1:
                self._pos = (epoch, step)
            elif self._beats % self._throttle == 0:
                # simulated straggler: the carried position advances ONE
                # step per k real beats (never jumps to the true step),
                # so heartbeat-derived seconds-per-step reads k times the
                # fleet cadence — the signature of a genuinely slow host
                pe, ps = self._pos
                self._pos = (epoch, ps + 1 if epoch == pe else 0)

    def throttle(self, every: int):
        """Simulated straggler: carried progress advances only every
        ``every``-th :meth:`beat` (1 = healthy). The beacon keeps
        beating — a straggler is alive — but its seconds-per-step, as
        derived from heartbeat progress, multiplies by ``every``."""
        with self._lock:
            self._throttle = max(1, int(every))

    def set_joining(self, joining: bool):
        """Flip the rejoin flag and publish it IMMEDIATELY (best
        effort): a stale ``joining`` doc lingering for one beat interval
        after the host was admitted would read as a relaunch
        self-report and re-kill the freshly admitted member."""
        with self._lock:
            self._joining = joining
        try:
            self._write()
        except OSError:
            pass    # the beacon thread retries within one interval

    def set_generation(self, generation: int):
        """Stamp the rendezvous generation this host currently belongs
        to into its heartbeat (multi-process fleets): operators and the
        supervisor can see which incarnation each host last joined."""
        with self._lock:
            self._generation = int(generation)

    def _write(self):
        with self._lock:
            self._seq += 1
            (epoch, step), joining = self._pos, self._joining
            seq, generation = self._seq, self._generation
        doc = {"host": self.host_id, "seq": seq, "time": time.time(),
               "epoch": epoch, "step": step}
        if generation:
            doc["generation"] = generation
        if joining:
            doc["joining"] = True
        # unique tmp per writer thread: set_joining publishes from the
        # caller's thread while the beacon thread keeps beating.
        # No fsync before the rename ON PURPOSE: a heartbeat needs READ
        # atomicity (rename gives it), not crash durability — a host
        # that crashes SHOULD look dead, and an fsync per beat would
        # hammer the shared filesystem the beacon must never stall on.
        tmp = f"{self.path}.tmp.{threading.get_ident()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        # graftlint: disable=protocol-rename-before-fsync
        os.replace(tmp, self.path)

    def _run(self):
        while not self._stop.is_set():
            try:
                self._retry.run(lambda _a: self._write())
            except Exception as e:   # exhausted: count, survive, retry
                _m_heartbeat_errors.labels(host=self.host_id).inc()
                log.warning("heartbeat %s write failed after retries: %s",
                            self.host_id, e)
            self._stop.wait(self.interval)

    def start(self) -> "HostHeartbeat":
        os.makedirs(self.directory, exist_ok=True)
        self._write()
        self._thread.start()
        return self

    def stop(self):
        """Clean shutdown (fit finished): final write then join, so a
        supervisor that outlives the fit doesn't read a stale file age."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2)

    def kill(self):
        """Simulated preemption: the beacon stops mid-air, no final write.
        The supervisor's grace window turns the silence into a verdict."""
        self._stop.set()


class TrainSupervisor:
    """Death-verdict loop over an elastic training fleet's heartbeats.

    The :class:`~.supervisor.FleetSupervisor` sibling: same tick/thread
    shape, but the subjects are training hosts (heartbeat files on shared
    storage) rather than serving workers (HTTP health probes), and the
    remedy is a re-mesh rather than a respawn — dead training hosts are
    *removed*, not restarted, because the collective program must shrink
    with them.

    ``probe(host_id) -> age_seconds | None`` is pluggable (tests inject
    clocks); the default reads the heartbeat file's ``time`` field. A host
    whose heartbeat is older than ``grace`` — or unreadable past the same
    window — is declared dead exactly once; verdicts are sticky (a zombie
    heartbeat resuming after its verdict stays dead: its devices left the
    mesh, rejoining means relaunching).
    """

    def __init__(self, host_ids, directory: str,
                 grace: Optional[float] = None,
                 min_hosts: int = 1,
                 probe: Optional[Callable] = None,
                 probe_interval: Optional[float] = None,
                 anomaly_detector=None,
                 rejoin_grace: Optional[float] = None,
                 evict_after: int = 0,
                 self_host: Optional[str] = None):
        from ..telemetry.slo import StepTimeAnomalyDetector
        self.host_ids = list(host_ids)
        self.directory = directory
        #: this process's own host id on a REAL fleet (None in the
        #: single-process simulation where every host is "us"): a
        #: running process is self-evidently alive, so the death pass
        #: skips it — its own heartbeat doc lagging (fs hiccup, stale
        #: joining flag from its rejoin) must not produce a self-verdict
        self.self_host = self_host
        self.grace = grace if grace is not None else _grace_default()
        self.min_hosts = max(1, min_hosts)
        #: consecutive straggler-flagged passes that promote the advisory
        #: verdict into an EVICT verdict (0 = advisory only, never evict)
        self.evict_after = max(0, int(evict_after))
        #: how long a relaunched host's ``joining`` heartbeat must stay
        #: fresh before the GROW verdict lands (its own window, symmetric
        #: to the death grace: a flapping relauncher must not churn the
        #: mesh). Default: the death grace.
        self.rejoin_grace = (rejoin_grace if rejoin_grace is not None
                             else self.grace)
        self._probe = probe or self._probe_file
        self.probe_interval = (probe_interval if probe_interval is not None
                               else max(0.05, self.grace / 4.0))
        #: rolling-MAD step-time detector fed from heartbeat progress; a
        #: STRAGGLER verdict (consistently slow, still beating) is advisory
        #: — reported, never a death verdict (pass anomaly_detector=False
        #: to disable, or inject a configured detector)
        self.anomaly = (StepTimeAnomalyDetector()
                        if anomaly_detector is None
                        else (anomaly_detector or None))
        self._lock = threading.Lock()
        self._dead: set[str] = set()        # guarded-by: _lock
        self._joining: dict[str, float] = {}     # guarded-by: _lock
        self._join_seen: dict[str, float] = {}   # guarded-by: _lock
        self._progress: dict[str, tuple] = {}    # guarded-by: _lock
        self._flagged: set[str] = set()     # guarded-by: _lock
        # reader-observed freshness: host -> (last seq, monotonic instant
        # the reader first saw it). Death and grow verdicts compare seq
        # ADVANCEMENT against the reader's monotonic clock — writer
        # wall-clock skew cannot fake either direction.
        self._fresh: dict[str, tuple] = {}       # guarded-by: _lock
        self._join_fresh: dict[str, tuple] = {}  # guarded-by: _lock
        self._streak: dict[str, int] = {}        # guarded-by: _lock
        self._evict: dict[str, float] = {}       # guarded-by: _lock
        self._started_at = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="train-supervisor")
        _m_hosts_alive.set(len(self.host_ids))

    # ---- probing ----
    def _read_doc(self, host_id: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.directory,
                                   f"hb_{host_id}.json"),
                      "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _doc_age(self, host_id: str, doc: dict,
                 table: dict) -> Optional[float]:
        """Reader-side freshness of one heartbeat doc: seconds since the
        doc's ``seq`` last ADVANCED, measured on the reader's monotonic
        clock (``table`` is the per-verdict-kind observation map). Docs
        written before the seq field existed fall back to the writer's
        wall time — legacy behavior, skew and all."""
        seq = doc.get("seq")
        if not isinstance(seq, int):
            try:
                return max(0.0, time.time() - float(doc["time"]))
            except (KeyError, TypeError, ValueError):
                return None
        now = time.monotonic()
        with self._lock:
            prev = table.get(host_id)
            if prev is None or prev[0] != seq:
                table[host_id] = (seq, now)
                return 0.0
            return now - prev[1]

    def _probe_file(self, host_id: str) -> Optional[float]:
        """Heartbeat age in seconds; None when the file is missing or
        unreadable (counted against the host once the startup grace is
        spent — a host that never wrote at all is as dead as one that
        stopped)."""
        doc = self._read_doc(host_id)
        if doc is None:
            return None
        age = self._doc_age(host_id, doc, self._fresh)
        if age is None:
            return None
        # an in-mesh host writing a JOINING heartbeat is a fresh process
        # self-reporting a restart (killed + relaunched inside the grace
        # window): its old membership — devices, collectives — is gone,
        # so the beating file must still produce a death verdict; the
        # grow path then readmits the new incarnation
        if doc.get("joining"):
            return float("inf")
        self._note_progress(host_id, doc)
        return age

    def _note_progress(self, host_id: str, doc: dict):
        """Feed the anomaly detector from heartbeat progress: successive
        probes of the same epoch yield (wall delta / steps advanced) — a
        central seconds-per-step estimate that needs no new wire format."""
        if self.anomaly is None:
            return
        try:
            cur = (int(doc["epoch"]), int(doc["step"]), float(doc["time"]))
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            prev = self._progress.get(host_id)
            self._progress[host_id] = cur
        if prev is None:
            return
        pe, ps, pt = prev
        e, s, t = cur
        if e == pe and s > ps and t > pt:
            self.anomaly.observe(host_id, (t - pt) / (s - ps))

    def tick(self):
        """One verdict pass (public: deterministic tests drive it directly,
        the background thread calls it on ``probe_interval``)."""
        verdicts = []
        for host_id in self.host_ids:
            if host_id == self.self_host:
                continue
            with self._lock:
                if host_id in self._dead:
                    continue
            faults.inject("supervisor.heartbeat")
            age = self._probe(host_id)
            if age is None:
                # missing file: only fatal once the fleet has had time to
                # write its first beats
                if time.monotonic() - self._started_at < self.grace:
                    continue
                verdicts.append((host_id, None))
            elif age > self.grace:
                verdicts.append((host_id, age))
        for host_id, age in verdicts:
            with self._lock:
                if host_id in self._dead:
                    continue
                self._dead.add(host_id)
                alive = len(self.host_ids) - len(self._dead)
            # verdict bookkeeping is IO (log/trace/metrics): after release
            _m_host_losses.labels(host=host_id).inc()
            _m_hosts_alive.set(alive)
            telemetry.trace.instant("elastic/host_loss", host=host_id,
                                    age=age)
            telemetry.flight.note("elastic/host_loss", host=host_id,
                                  age=age, alive=alive)
            log.warning(
                "host %s declared DEAD (heartbeat %s, grace %.2fs); "
                "%d host(s) remain", host_id,
                "missing" if age is None else f"{age:.2f}s old",
                self.grace, alive)
        self._grow_pass()
        self._straggler_pass()

    def _grow_pass(self):
        """GROW verdicts — the death pass's mirror. A dead host whose
        heartbeat file is beating again WITH the ``joining`` flag is a
        relaunch (not a zombie: sticky death still holds for flagless
        resurrections); once it has stayed fresh through ``rejoin_grace``
        the host earns a grow verdict the coordinator can admit at the
        next checkpoint boundary. Verdict bookkeeping decided under the
        lock; IO after release."""
        with self._lock:
            candidates = [h for h in self._dead if h not in self._joining]
        verdicts = []
        for host_id in candidates:
            faults.inject("supervisor.rejoin")
            doc = self._read_doc(host_id)
            age = (self._doc_age(host_id, doc, self._join_fresh)
                   if doc is not None and doc.get("joining") else None)
            fresh = age is not None and age <= self.grace
            now = time.monotonic()
            with self._lock:
                if not fresh:
                    # stale or flagless: the relaunch flapped (or was a
                    # zombie); restart its window
                    self._join_seen.pop(host_id, None)
                    continue
                t0 = self._join_seen.setdefault(host_id, now)
                if now - t0 < self.rejoin_grace:
                    continue
                self._join_seen.pop(host_id, None)
                self._joining[host_id] = now
            verdicts.append(host_id)
        for host_id in verdicts:
            _m_rejoins.labels(host=host_id).inc()
            telemetry.trace.instant("elastic/rejoin", host=host_id)
            telemetry.flight.note("elastic/rejoin", host=host_id)
            log.warning("host %s earned a GROW verdict (joining heartbeat "
                        "fresh through the %.2fs rejoin window); eligible "
                        "to re-enter the mesh at the next checkpoint "
                        "boundary", host_id, self.rejoin_grace)

    def joining_hosts(self) -> dict:
        """Hosts holding a grow verdict -> verdict time (monotonic). The
        coordinator admits them at the next checkpoint boundary."""
        with self._lock:
            return dict(self._joining)

    def admit(self, host_id: str):
        """The coordinator admitted a rejoined host back into the mesh:
        clear its death verdict and grow state so the death pass watches
        it again."""
        with self._lock:
            self._dead.discard(host_id)
            self._joining.pop(host_id, None)
            self._join_seen.pop(host_id, None)
            self._join_fresh.pop(host_id, None)
            self._evict.pop(host_id, None)
            self._streak.pop(host_id, None)
            # re-baseline freshness: the readmitted host gets a full
            # grace window from its next observed beat
            self._fresh.pop(host_id, None)
            alive = len(self.host_ids) - len(self._dead)
        _m_hosts_alive.set(alive)

    def _straggler_pass(self):
        """Anomaly verdicts: flag hosts the rolling-MAD detector calls
        stragglers (and unflag recovered ones so a relapse re-flags).
        With ``evict_after`` > 0, a host flagged for that many
        CONSECUTIVE passes is promoted from advisory to an **EVICT
        verdict** — subject to the floors: the survivors after the evict
        must still satisfy ``min_hosts``, and the coordinator host
        (lowest-ranked alive — it owns checkpoints and rendezvous
        proposals) is never evicted. The verdict is consumed by the fit
        coordinator at the next committed checkpoint boundary. Flag
        bookkeeping is decided under the lock; the IO (metrics,
        instants, flight notes, logs) happens after release."""
        if self.anomaly is None:
            return
        current = self.anomaly.stragglers()
        evict_verdicts = []
        with self._lock:
            current -= self._dead
            newly = current - self._flagged
            self._flagged = current
            alive = [h for h in self.host_ids if h not in self._dead]
            now = time.monotonic()
            for h in list(self._streak):
                if h not in current:
                    self._streak.pop(h)
            for h in sorted(current):
                self._streak[h] = self._streak.get(h, 0) + 1
                if (self.evict_after > 0 and h not in self._evict
                        and self._streak[h] >= self.evict_after
                        and alive and h != min(alive)
                        and len(alive) - len(self._evict) - 1
                        >= self.min_hosts):
                    self._evict[h] = now
                    evict_verdicts.append(h)
        med = (self.anomaly.host_medians()
               if (newly or evict_verdicts) else {})
        for host_id in sorted(newly):
            _m_stragglers.labels(host=host_id).inc()
            telemetry.trace.instant("elastic/straggler", host=host_id,
                                    median_s=med.get(host_id))
            telemetry.flight.note("elastic/straggler", host=host_id,
                                  median_s=med.get(host_id))
            log.warning("host %s flagged as STRAGGLER (median step "
                        "%.4fs vs fleet %s); still alive — advisory only",
                        host_id, med.get(host_id, float("nan")),
                        {h: round(v, 4) for h, v in med.items()})
        for host_id in evict_verdicts:
            telemetry.trace.instant("elastic/evict", host=host_id,
                                    stage="verdict",
                                    median_s=med.get(host_id))
            telemetry.flight.note("elastic/evict", host=host_id,
                                  stage="verdict")
            log.warning(
                "host %s earned an EVICT verdict (straggler for %d "
                "consecutive passes, median step %.4fs); dropped at the "
                "next committed checkpoint boundary", host_id,
                self.evict_after, med.get(host_id, float("nan")))

    def evict_verdicts(self) -> dict:
        """Hosts holding an evict verdict -> verdict time (monotonic).
        The coordinator consumes them at the next committed checkpoint
        boundary through the same unwind path as a host loss."""
        with self._lock:
            return dict(self._evict)

    def mark_evicted(self, host_id: str):
        """The coordinator dropped an evicted host from the mesh: record
        the (sticky) death verdict and clear its straggler state — its
        samples are stale the moment it leaves the mesh, and a held flag
        would block the rejoin it is entitled to once recovered."""
        with self._lock:
            self._dead.add(host_id)
            self._evict.pop(host_id, None)
            self._streak.pop(host_id, None)
            self._flagged.discard(host_id)
            alive = len(self.host_ids) - len(self._dead)
        if self.anomaly is not None:
            self.anomaly.forget(host_id)
        _m_evictions.labels(host=host_id).inc()
        _m_hosts_alive.set(alive)

    def straggler_hosts(self) -> set[str]:
        """Hosts currently flagged anomalously slow (advisory — they are
        alive and beating, just burning the step-time budget)."""
        with self._lock:
            return set(self._flagged)

    def dead_hosts(self) -> set[str]:
        with self._lock:
            return set(self._dead)

    def alive_hosts(self) -> list[str]:
        with self._lock:
            return [h for h in self.host_ids if h not in self._dead]

    def decision(self) -> str:
        """``"shrink"`` when the survivors can keep training in-job,
        ``"restart"`` when they cannot (relaunch against the same
        checkpointDir — consensus resume carries the run over)."""
        return ("shrink" if len(self.alive_hosts()) >= self.min_hosts
                else "restart")

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:   # a probe bug must not kill the loop
                log.warning("train-supervisor tick failed: %s", e)
            self._stop.wait(self.probe_interval)

    def clear_stale_heartbeats(self):
        """Remove ``hb_*.json`` ghosts from a PREVIOUS run (not modified
        within the grace window): without this a supervisor starting
        against a reused checkpointDir reads last week's heartbeat and
        declares an instant death (or an instant zombie) before the
        relaunched fleet writes its first beat. Staleness is judged by
        the file's mtime — the filesystem's clock, not the dead writer's
        wall clock, so a ghost written by a skewed host still clears.
        Fresh files — this run's — are untouched."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not (name.startswith("hb_") and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            try:
                stale = time.time() - os.path.getmtime(path) > self.grace
            except OSError:
                stale = True     # unreadable ghosts go too
            if stale:
                try:
                    os.remove(path)
                    log.info("cleared stale heartbeat %s from a previous "
                             "run", name)
                except OSError:
                    pass

    def start(self) -> "TrainSupervisor":
        self.clear_stale_heartbeats()
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


class ElasticStepContext:
    """The per-step hook the trainer's dispatch loop calls during an
    elastic fit. Cheap when nothing is wrong: one fault-site check and one
    set read per optimizer step."""

    def __init__(self, coordinator: "ElasticFitCoordinator"):
        self._coord = coordinator

    def check_step(self):
        """Runs inside the step dispatch, BEFORE the device work. An
        injected ``elastic.step`` fault is a ConnectionError — the
        trainer's retry-once policy absorbs singles, doubles escalate to
        the coordinator's transient classification. A death verdict on a
        mesh member raises :class:`HostLossError`; a grow verdict with a
        checkpoint boundary committed behind it raises
        :class:`HostRejoinError`; a sustained-straggler evict verdict
        with a boundary behind it raises :class:`HostEvictError` (all
        non-transient: they skip the retry and unwind to the
        coordinator's re-mesh)."""
        faults.inject("elastic.step")
        dead = self._coord.dead_mesh_hosts()
        if dead:
            raise HostLossError(dead)
        if self._coord._multiproc:
            # grow/evict re-meshes in a REAL fleet must unwind every
            # process at the same step — they go through the leader's
            # rendezvous proposal (check_rendezvous), never a unilateral
            # raise here; only a dead mesh member (collectives already
            # broken) justifies unwinding alone
            return
        grow = self._coord.pending_grow()
        if grow:
            raise HostRejoinError(grow)
        evict = self._coord.pending_evict()
        if evict:
            raise HostEvictError(evict)

    def step_committed(self, epoch: int, step: int):
        """The trainer reports each completed optimizer step: advances
        this process's heartbeat progress, closes any pending
        recovery-time measurement, and feeds the committed-step journal
        the chaos tests audit for gaps. Multi-process fleets also poll
        the rendezvous doc here — the deterministic unwind point: every
        process raises :class:`RendezvousPending` after the SAME
        committed step, so a grow/evict re-mesh never strands a peer
        mid-collective."""
        self._coord.note_step(epoch, step)
        self._coord.check_rendezvous(epoch, step)

    def checkpoint_saved(self, epoch: int, step: Optional[int]):
        """A checkpoint COMMITTED (rename + manifest durable — on the
        async path this fires from the writer thread strictly after the
        commit, never at submit). Checkpoint boundaries are where grow
        re-meshes become eligible: a joiner admitted here replays ~zero
        steps."""
        self._coord.note_checkpoint(epoch, step)

    def resumed(self, pos, params_digest: Optional[str]):
        """The trainer reports the checkpoint position (or None for a
        fresh start) and a digest of the restored params — the bit-exact
        resume evidence."""
        self._coord.note_resume(pos, params_digest)

    # ---- in-memory boosting-state candidates (elastic GBDT fits) ----
    def save_snapshot(self, state):
        """The GBDT engine's per-iteration boosting-state candidate
        (newest wins): host-side arrays a re-meshed attempt resumes
        from. Pair with :meth:`checkpoint_saved` so grow boundaries work
        for boosted fits too."""
        self._coord.snapshot = state

    def latest_snapshot(self):
        return self._coord.snapshot


class ElasticFitCoordinator:
    """Drives a ``TpuLearner`` fit through host loss.

    ``fit(df)``: build the host groups, start heartbeats + the
    supervisor, then loop ``learner._fit_core(df, devices=pool,
    elastic_ctx=ctx)`` until it returns a model. A
    :class:`HostLossError` (or an exhausted-transient failure that a
    fresh verdict pass attributes to a dead host) triggers the re-mesh:
    survivors' devices become the new pool, and the next ``_fit_core``
    attempt resumes from the latest consensus checkpoint. Failures with
    *no* dead host burn the ``max_failures`` budget and retry on the same
    mesh — persistent infrastructure trouble must not loop forever.
    """

    def __init__(self, learner=None, n_hosts: int = 0,
                 min_hosts: int = 1,
                 grace: Optional[float] = None,
                 max_failures: int = 5,
                 heartbeat_interval: Optional[float] = None,
                 max_hosts: int = 0,
                 rejoin_grace: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 evict_after: int = 0):
        ckdir = checkpoint_dir or (learner.getCheckpointDir()
                                   if learner is not None else "")
        if not ckdir:
            raise ValueError(
                "elastic fit requires checkpointDir: recovery is a resume "
                "from the consensus checkpoint — without one a host loss "
                "restarts from scratch, losing every committed step")
        self.learner = learner
        self.checkpoint_dir = ckdir
        self.grace = grace if grace is not None else _grace_default()
        self.min_hosts = max(1, min_hosts)
        self.max_failures = max(1, max_failures)
        self._hb_interval = (heartbeat_interval
                             if heartbeat_interval is not None
                             else _hb_interval_default(self.grace))
        from ..parallel import distributed as dist
        from ..parallel import mesh as meshlib
        self._rdzv = dist.rendezvous_coordinator()
        if self._rdzv is not None:
            # rendezvous-armed multi-process fleet: membership is the
            # LAUNCH fleet (stable host ids = launch ranks), whatever
            # the current incarnation's size — a dropped host stays on
            # the watch list so its rejoin can be seen
            n_env = int(os.environ.get(dist.ENV_NUM_PROCESSES, "0") or 0)
            hosts = sorted(set(self._rdzv.ranks)
                           | {f"host{i}" for i in range(n_env)}
                           | {self._rdzv.host_id})
            self.groups = {h: [] for h in hosts}
        else:
            self.groups = dict(meshlib.host_device_groups(n_hosts))
        #: grow ceiling: the mesh never grows past this many hosts
        #: (0 = the launch fleet size)
        self.max_hosts = max_hosts or len(self.groups)
        self.hb_dir = heartbeat_dir(ckdir)
        self.heartbeats = {h: HostHeartbeat(h, self.hb_dir,
                                            self._hb_interval)
                           for h in self.groups}
        self.supervisor = TrainSupervisor(
            list(self.groups), self.hb_dir, grace=self.grace,
            min_hosts=self.min_hosts, rejoin_grace=rejoin_grace,
            evict_after=evict_after,
            self_host=(self._rdzv.host_id if self._rdzv is not None
                       else None))
        self.attempts: list[dict] = []   # per-attempt journal (tests/bench)
        self.committed: list[tuple] = []   # (epoch, step) journal
        self.snapshot = None   # GBDT boosting-state candidate (newest wins)
        self._mesh_hosts: set[str] = set()
        self._multiproc = False
        self._pending_recovery_t0: Optional[float] = None
        self._recovery_kind = "loss"
        self._last_ckpt_pos: Optional[tuple] = None
        self._last_ckpt_t: Optional[float] = None
        self._rdzv_cache: tuple = (0.0, 0.0, None)  # (checked, mtime, doc)

    # ---- state read by the step hook (fit thread) ----
    def dead_mesh_hosts(self) -> set[str]:
        return self.supervisor.dead_hosts() & self._mesh_hosts

    def pending_grow(self) -> set[str]:
        """Joiners eligible to enter at THIS step: they hold a grow
        verdict, a checkpoint boundary has committed since the verdict
        (so the re-entry replays ~zero steps), and the ``max_hosts``
        ceiling leaves room. Cheap when nobody is joining: one dict read
        under the supervisor lock."""
        join = self.supervisor.joining_hosts()
        if not join:
            return set()
        room = self.max_hosts - len(self._mesh_hosts)
        if room <= 0:
            return set()
        ckpt_t = self._last_ckpt_t
        eligible = sorted(h for h, t in join.items()
                          if h not in self._mesh_hosts
                          and ckpt_t is not None and ckpt_t >= t)
        return set(eligible[:room])

    def pending_evict(self) -> set[str]:
        """Sustained-straggler evict verdicts eligible to fire at THIS
        step: a checkpoint boundary has committed since the verdict (so
        the unwind replays ~zero steps) and dropping them keeps the mesh
        at or above ``min_hosts``. Cheap when nobody is flagged: one
        dict read under the supervisor lock."""
        ev = self.supervisor.evict_verdicts()
        if not ev:
            return set()
        ckpt_t = self._last_ckpt_t
        if ckpt_t is None:
            return set()
        eligible = sorted(h for h, t in ev.items()
                          if h in self._mesh_hosts and ckpt_t >= t)
        room = len(self._mesh_hosts) - self.min_hosts
        return set(eligible[:max(0, room)])

    # ---- multi-process rendezvous polling (step hook, fit thread) ----
    def _read_rdzv_doc(self) -> Optional[dict]:
        """The current rendezvous doc, mtime-cached and stat-throttled:
        one os.stat per step at most, one re-read per actual change."""
        rdzv = self._rdzv
        if rdzv is None:
            return None
        checked, mtime, doc = self._rdzv_cache
        now = time.monotonic()
        if now - checked < 0.05:
            return doc
        try:
            cur = os.path.getmtime(rdzv.path)
        except OSError:
            self._rdzv_cache = (now, 0.0, None)
            return None
        if cur != mtime:
            doc = rdzv.read()
        self._rdzv_cache = (now, cur, doc)
        return doc

    def _is_leader(self) -> bool:
        """Lease-aware: the fresh leaseholder leads; an expired/absent
        lease falls back to the lowest-rank mesh host (who takes the
        lease over at propose time)."""
        return bool(self._mesh_hosts) and self._rdzv.host_id \
            == self._rdzv.elect_leader(self._mesh_hosts)

    def check_rendezvous(self, epoch: int, step: int):
        """Multi-process fleets only (single-process fits no-op): the
        deterministic membership-change machinery that rides the
        committed-step sequence. The LEADER promotes boundary-armed
        grow/evict verdicts into a rendezvous proposal whose
        ``unwind_at`` names a step a checkpoint-interval ahead; EVERY
        process (leader included) polls the doc each committed step and
        raises :class:`RendezvousPending` once it commits that step —
        identical unwind points fleet-wide, nobody stranded
        mid-collective."""
        if not self._multiproc or self._rdzv is None:
            return
        rdzv = self._rdzv
        doc = self._read_rdzv_doc()
        if (doc is None or doc["generation"] <= rdzv.generation) \
                and self._is_leader():
            # hold leadership while the fit runs: a renewed lease keeps
            # followers from taking over between membership changes
            rdzv.lease.maybe_renew()
            grow = self.pending_grow()
            evict = self.pending_evict()
            if grow or evict:
                members = sorted((self._mesh_hosts - evict) | grow)
                margin = 1
                if self.learner is not None:
                    margin = max(
                        1, self.learner.getCheckpointEverySteps() or 1)
                doc = rdzv.propose(members,
                                   unwind_at=(epoch, step + margin))
                self._rdzv_cache = (0.0, 0.0, None)
        if doc is not None and doc["generation"] > rdzv.generation:
            ua = doc.get("unwind_at")
            if ua is None or (epoch, step) >= (int(ua[0]), int(ua[1])):
                raise RendezvousPending(doc["generation"])

    def note_step(self, epoch: int, step: int):
        self.committed.append((epoch, step))
        for h in self._mesh_hosts:
            hb = self.heartbeats.get(h)
            # only beacons whose thread runs in THIS process (all of
            # them single-process; just our own on a real fleet)
            if hb is not None and hb._thread.is_alive():
                hb.beat(epoch, step)
        if self._pending_recovery_t0 is not None:
            dt = time.monotonic() - self._pending_recovery_t0
            self._pending_recovery_t0 = None
            if self._recovery_kind == "grow":
                _m_grow_recovery_seconds.observe(dt)
                self.attempts[-1]["grow_recovery_s"] = dt
                log.info("elastic grow complete: first step committed "
                         "%.2fs after the grow re-mesh began", dt)
            elif self._recovery_kind == "evict":
                _m_recovery_seconds.observe(dt)
                self.attempts[-1]["evict_recovery_s"] = dt
                log.info("elastic evict complete: first step committed "
                         "%.2fs after the straggler was dropped", dt)
            else:
                _m_recovery_seconds.observe(dt)
                self.attempts[-1]["recovery_s"] = dt
                log.info("elastic recovery complete: first step committed "
                         "%.2fs after the failure", dt)

    def note_checkpoint(self, epoch: int, step: Optional[int]):
        """A checkpoint committed durably (rename + manifest). Marks the
        grow boundary: verdicts older than this instant become
        admissible."""
        self._last_ckpt_pos = (epoch, step)
        self._last_ckpt_t = time.monotonic()

    def note_resume(self, pos, params_digest):
        self._last_ckpt_pos = pos
        self.attempts[-1]["resume_pos"] = pos
        self.attempts[-1]["resume_digest"] = params_digest
        if pos is not None and self.committed:
            # steps the previous attempt committed past the checkpoint are
            # about to be re-run — the measurable cost of the ckpt interval
            e, s = pos
            replay = sum(1 for (ce, cs) in self.committed
                         if (ce, cs) > (e, -1 if s is None else s))
            if replay:
                _m_steps_replayed.inc(replay)

    # ---- the recovery loop ----
    def _pool(self) -> list:
        self._mesh_hosts = set(self.supervisor.alive_hosts())
        return [d for h in sorted(self._mesh_hosts)
                for d in self.groups[h]]

    def fit(self, df):
        """Drive ``learner.fit``'s core through the recovery loop."""
        return self.run(lambda devices, ctx: self.learner._fit_core(
            df, devices=devices, elastic_ctx=ctx))

    def fit_stream(self, batches_fn):
        """Drive ``learner.fitStream``'s core through the recovery loop:
        a host loss re-meshes and re-enters the stream (the epoch
        restarts — a generator cannot seek — with the checkpointed
        optimizer state kept)."""
        return self.run(lambda devices, ctx: self.learner._fit_stream_core(
            batches_fn, devices=devices, elastic_ctx=ctx))

    def relaunch_host(self, host_id: str) -> HostHeartbeat:
        """Simulated-preemption RELAUNCH (single-process failure domains:
        chaos tests, ``bench.py --chaos-train``): replace a killed host's
        beacon with a fresh one carrying the ``joining`` flag — exactly
        the heartbeat a real relaunched host process writes on boot. The
        supervisor turns its sustained freshness into a grow verdict."""
        if host_id not in self.groups:
            raise ValueError(f"unknown host {host_id!r}")
        old = self.heartbeats.get(host_id)
        if old is not None:
            old.kill()
        hb = HostHeartbeat(host_id, self.hb_dir, self._hb_interval,
                           joining=True)
        self.heartbeats[host_id] = hb
        hb.start()
        return hb

    def run(self, attempt_fn):
        """The recovery loop: ``attempt_fn(devices, ctx)`` until it
        returns. :class:`HostLossError` shrinks the mesh,
        :class:`HostRejoinError` grows it back,
        :class:`HostEvictError` drops a sustained straggler *before* it
        fails (all re-enter from the consensus checkpoint); transient
        failures without a verdict burn the ``max_failures`` budget on
        the same mesh."""
        from ..parallel import mesh as meshlib
        if meshlib.effective_process_count() > 1 or self._rdzv is not None:
            # real multi-process fleet. With a RendezvousCoordinator
            # armed (distributed.elastic_initialize) the fleet re-enters
            # the SAME fit through coordinator-service restart + barrier
            # re-entry; without one it keeps the fixed-fleet posture:
            # fast, clean failure instead of a hung collective, and the
            # launcher relaunches at full size against the checkpointDir
            return self._run_multiprocess(attempt_fn)
        ctx = ElasticStepContext(self)
        for h in self.heartbeats.values():
            h.start()
        self.supervisor.start()
        _register_fleet(self)
        failures = 0
        try:
            while True:
                pool = self._pool()
                self.attempts.append({"hosts": sorted(self._mesh_hosts),
                                      "devices": len(pool)})
                try:
                    with telemetry.trace.span("elastic/attempt",
                                              hosts=len(self._mesh_hosts),
                                              devices=len(pool)):
                        return attempt_fn(pool, ctx)
                except HostLossError as e:
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "loss"
                    self._remesh(e.hosts)
                except HostRejoinError as e:
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "grow"
                    self._grow(e.hosts)
                except HostEvictError as e:
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "evict"
                    self._evict(e.hosts)
                except Exception as e:
                    if not default_transient(e):
                        raise
                    # transient exhaustion with no verdict yet: force a
                    # probe pass — the failure may BE the dying host
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "loss"
                    self.supervisor.tick()
                    dead = self.dead_mesh_hosts()
                    if dead:
                        self._remesh(dead, cause=e)
                    else:
                        failures += 1
                        _m_attempt_failures.inc()
                        if failures >= self.max_failures:
                            raise ElasticFleetLost(
                                f"elastic fit failed {failures} times "
                                f"without a host verdict; last error: "
                                f"{e!r}") from e
                        log.warning(
                            "elastic fit attempt failed transiently (%r); "
                            "retrying from the latest checkpoint on the "
                            "same mesh (%d/%d)", e, failures,
                            self.max_failures)
        finally:
            _unregister_fleet(self)
            self.supervisor.stop()
            for h in self.heartbeats.values():
                h.stop()

    def _grow(self, hosts):
        """Admit grow-verdict holders back into the mesh (capped by
        ``max_hosts``) and re-enter the fit: the next attempt's pool is
        survivors + joiners and resumes from the checkpoint boundary
        that armed the grow — replays only, no fleet restart."""
        faults.inject("elastic.remesh")
        admitted = []
        for h in sorted(hosts):
            if len(self.supervisor.alive_hosts()) >= self.max_hosts:
                log.warning("host %s holds a grow verdict but the fleet "
                            "is at elasticMaxHosts (%d); leaving it "
                            "parked", h, self.max_hosts)
                break
            self.supervisor.admit(h)
            hb = self.heartbeats.get(h)
            if hb is not None:
                hb.set_joining(False)
            admitted.append(h)
        if not admitted:
            return
        _m_grows.inc()
        telemetry.trace.instant("elastic/grow",
                                joined=",".join(admitted),
                                alive=len(self.supervisor.alive_hosts()))
        telemetry.flight.note("elastic/grow", joined=admitted)
        log.warning(
            "growing the mesh: host(s) %s rejoin at checkpoint %s; "
            "%d host(s) in the pool", admitted, self._last_ckpt_pos,
            len(self.supervisor.alive_hosts()))

    def _evict(self, hosts):
        """Drop sustained-straggler hosts from the mesh at a committed
        checkpoint boundary — the loss unwind path fired *before* the
        failure. The floors are re-checked at consumption time (a death
        verdict may have landed since the evict verdict): survivors must
        satisfy ``min_hosts`` and the coordinator host (lowest alive) is
        never evicted. The evicted host stays alive and rejoins through
        the joining-heartbeat grow path once it recovers."""
        faults.inject("elastic.evict")
        victims = []
        for h in sorted(hosts):
            alive = set(self.supervisor.alive_hosts())
            if h not in alive or h not in self._mesh_hosts:
                continue
            if len(alive) - 1 < self.min_hosts:
                log.warning("host %s holds an evict verdict but dropping "
                            "it would leave %d < min_hosts (%d); leaving "
                            "it in the mesh", h, len(alive) - 1,
                            self.min_hosts)
                continue
            if h == min(alive):
                log.warning("host %s holds an evict verdict but is the "
                            "coordinator host; never evicted", h)
                continue
            self.supervisor.mark_evicted(h)
            victims.append(h)
        if not victims:
            return
        _m_remeshes.inc()
        telemetry.trace.instant("elastic/evict",
                                evicted=",".join(victims), stage="remesh",
                                alive=len(self.supervisor.alive_hosts()))
        telemetry.flight.note("elastic/evict", evicted=victims,
                              stage="remesh")
        log.warning(
            "evicting straggler host(s) %s at checkpoint %s: %d host(s) "
            "remain; resuming from the consensus checkpoint — the "
            "evicted host rejoins via the grow path once recovered",
            victims, self._last_ckpt_pos,
            len(self.supervisor.alive_hosts()))

    def _remesh(self, dead_hosts, cause=None):
        faults.inject("elastic.remesh")
        if self.supervisor.decision() == "restart":
            raise ElasticFleetLost(
                f"{len(self.supervisor.alive_hosts())} host(s) alive < "
                f"min_hosts ({self.min_hosts}); relaunch the fleet against "
                f"checkpointDir {self.learner.getCheckpointDir()!r} to "
                f"resume from the last committed step")
        _m_remeshes.inc()
        telemetry.trace.instant("elastic/remesh",
                                dead=",".join(sorted(dead_hosts)),
                                alive=len(self.supervisor.alive_hosts()))
        telemetry.flight.note("elastic/remesh", dead=sorted(dead_hosts))
        log.warning(
            "re-meshing after loss of %s: %d host(s) remain; resuming "
            "from the consensus checkpoint%s", sorted(dead_hosts),
            len(self.supervisor.alive_hosts()),
            f" (trigger: {cause!r})" if cause is not None else "")

    def _run_multiprocess(self, attempt_fn):
        import jax
        ctx = ElasticStepContext(self)
        if self._rdzv is None:
            # fixed-fleet posture (no elastic_initialize): detection +
            # fail-fast; the launcher relaunches at full size and the
            # consensus resume carries the run over
            from ..parallel import mesh as meshlib
            host_id = meshlib.stable_host_id()
            hb = self.heartbeats.get(host_id)
            self._mesh_hosts = set(self.groups)
            if hb is not None:
                hb.start()
            self.supervisor.start()
            _register_fleet(self)
            try:
                self.attempts.append({"hosts": sorted(self.groups),
                                      "devices": len(jax.devices())})
                return attempt_fn(None, ctx)
            finally:
                _unregister_fleet(self)
                self.supervisor.stop()
                if hb is not None:
                    hb.stop()
        # ---- rendezvous-armed elastic fleet ----
        self._multiproc = True
        rdzv = self._rdzv
        host_id = rdzv.host_id
        hb = rdzv.heartbeat
        if hb is not None:
            # reuse the PROCESS-LEVEL beacon elastic_initialize started:
            # it has been proving liveness since before this fit and
            # must keep doing so across re-rendezvous gaps (tighten its
            # cadence to the fit's grace if needed)
            hb.interval = min(hb.interval, self._hb_interval)
            self.heartbeats[host_id] = hb
        else:
            hb = self.heartbeats.get(host_id)
            if hb is None:
                hb = self.heartbeats[host_id] = HostHeartbeat(
                    host_id, self.hb_dir, self._hb_interval)
            hb.start()
        hb.set_generation(rdzv.generation)
        self.supervisor.start()
        _register_fleet(self)
        failures = 0
        try:
            while True:
                self._mesh_hosts = set(rdzv.ranks) or {host_id}
                self.attempts.append({"hosts": sorted(self._mesh_hosts),
                                      "devices": len(jax.devices()),
                                      "generation": rdzv.generation})
                with telemetry.trace.span("elastic/attempt",
                                          hosts=len(self._mesh_hosts),
                                          generation=rdzv.generation):
                    kind, val = self._attempt_in_thread(attempt_fn, ctx)
                if kind == "ok":
                    return val
                e = val
                if isinstance(e, RendezvousPending):
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "grow"
                    self._rendezvous_cycle(hb)
                elif isinstance(e, (HostLossError, HostEvictError)):
                    self._pending_recovery_t0 = time.monotonic()
                    self._recovery_kind = "loss"
                    self._rendezvous_cycle(hb, dead=set(e.hosts))
                else:
                    # a failed collective (XlaRuntimeError from a gloo
                    # op on a dead peer — NOT a ConnectionError) is how
                    # a peer death usually surfaces here: force a
                    # verdict pass BEFORE deciding the error is fatal
                    self.supervisor.tick()
                    dead = self.dead_mesh_hosts()
                    doc = rdzv.read()
                    xla_err = type(e).__name__ == "XlaRuntimeError"
                    if dead or (doc is not None
                                and doc["generation"] > rdzv.generation):
                        self._pending_recovery_t0 = time.monotonic()
                        self._recovery_kind = "loss"
                        self._rendezvous_cycle(hb, dead=dead)
                    elif not default_transient(e) and not xla_err:
                        raise e
                    else:
                        failures += 1
                        _m_attempt_failures.inc()
                        if failures >= self.max_failures:
                            raise ElasticFleetLost(
                                f"elastic fit failed {failures} times "
                                f"without a host verdict; last error: "
                                f"{e!r}") from e
                        if xla_err:
                            # a failed/timed-out collective with no
                            # verdict: the gloo state is desynced (a
                            # peer re-rendezvoused or aborted) — a
                            # FRESH generation (new KV store, new
                            # contexts) is the recovery
                            log.warning(
                                "collective failed without a verdict "
                                "(%r); minting a fresh generation "
                                "(%d/%d)", e, failures,
                                self.max_failures)
                            self._pending_recovery_t0 = time.monotonic()
                            self._recovery_kind = "loss"
                            self._rendezvous_cycle(hb)
                        else:
                            log.warning(
                                "elastic fit attempt failed transiently "
                                "(%r); retrying from the latest "
                                "checkpoint (%d/%d)", e, failures,
                                self.max_failures)
        finally:
            _unregister_fleet(self)
            if self.learner is not None:
                self.learner._active_fit_thread = None
            self.supervisor.stop()
            if hb is not rdzv.heartbeat:
                hb.stop()   # the process-level beacon outlives the fit

    def _attempt_in_thread(self, attempt_fn, ctx):
        """Run one fit attempt on a WATCHED worker thread. XLA's CPU
        collectives block for up to 30 minutes when a peer dies mid-op,
        and the dispatch is synchronous — a fit thread pinned inside a
        dead collective could otherwise hold the whole fleet for that
        long. The watchdog sees the (background-thread) heartbeat
        verdict or a newer rendezvous doc, gives the attempt a short
        grace to unwind CLEANLY (check_step raising, or the collective
        surfacing its error), and otherwise FAILS FAST with
        :class:`ElasticFleetLost`: a thread pinned inside the dead
        incarnation cannot be safely abandoned in-process (it would
        unstick into — and poison — the next generation's runtime), so
        the clean recovery is a process relaunch, which re-enters the
        SAME rendezvous lineage (generation + 1) and consensus-resumes.
        In-job re-rendezvous is reserved for attempts that unwound
        cleanly — the deterministic grow/evict boundaries and surfaced
        collective errors."""
        rdzv = self._rdzv
        result: dict = {}
        done = threading.Event()

        def body():
            try:
                result["value"] = attempt_fn(None, ctx)
            except BaseException as e:   # delivered to the main loop
                result["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=body, daemon=True,
                             name="elastic-attempt")
        if self.learner is not None:
            self.learner._active_fit_thread = t
        t.start()
        poll = min(0.1, max(0.02, self._hb_interval))
        while not done.wait(poll):
            dead = self.dead_mesh_hosts()
            doc = rdzv.read()
            newer = (doc is not None
                     and doc["generation"] > rdzv.generation)
            if not (dead or newer):
                continue
            # verdict landed: the attempt should unwind via check_step
            # within a step or two — unless it is pinned in C++
            if done.wait(max(1.0, 2 * self.grace)):
                break
            why = (f"dead: {sorted(dead)}" if dead
                   else f"generation {doc['generation']} pending")
            log.warning("fit attempt pinned inside a dead collective "
                        "(%s); failing fast — relaunch this process to "
                        "rejoin the rendezvous lineage", why)
            raise ElasticFleetLost(
                f"fit attempt pinned inside a dead collective ({why}); "
                f"XLA's collective timeout is ~30 minutes, so this "
                f"process fails fast instead. Relaunch it against "
                f"checkpointDir {self.checkpoint_dir!r}: it will rejoin "
                f"the rendezvous lineage (generation "
                f"{rdzv.generation} + 1) and resume from the last "
                f"committed step")
        if "error" in result:
            return "error", result["error"]
        return "ok", result.get("value")

    def _rendezvous_cycle(self, hb, dead=frozenset()):
        """One membership change on a REAL fleet: agree on the next
        generation's members, tear down the dead incarnation, restart
        the coordination service on the surviving lowest-rank host, and
        barrier back in. Retries with exponential backoff; exhaustion
        falls back to relaunch-at-full-size (ElasticFleetLost, the
        pre-rendezvous posture)."""
        from ..parallel import distributed as dist
        rdzv = self._rdzv
        host_id = rdzv.host_id
        backoff = 0.2
        last_err = None
        doc = None
        for attempt in range(self.max_failures):
            try:
                doc = rdzv.read()
                if not (doc is not None
                        and doc["generation"] > rdzv.generation
                        and host_id in doc.get("ranks", {})):
                    doc = self._negotiate_generation(hb, dead)
                rdzv.join(doc)
                break
            except (dist.RendezvousError, ConnectionError, OSError) as e:
                last_err = e
                log.warning("re-rendezvous attempt %d/%d failed (%s); "
                            "backing off %.1fs", attempt + 1,
                            self.max_failures, e, backoff)
                time.sleep(backoff)
                backoff = min(5.0, backoff * 2)
        else:
            raise ElasticFleetLost(
                f"re-rendezvous failed {self.max_failures} times (last: "
                f"{last_err!r}); relaunch the fleet at full size against "
                f"checkpointDir {self.checkpoint_dir!r} to resume from "
                f"the last committed step") from last_err
        # joined: reconcile verdict state with the new membership
        grew = len(doc["ranks"]) > len(self._mesh_hosts)
        for h in doc["ranks"]:
            if h in self.supervisor.dead_hosts():
                self.supervisor.admit(h)
        hb.set_joining(False)
        hb.set_generation(rdzv.generation)
        self._mesh_hosts = set(doc["ranks"])
        self._rdzv_cache = (0.0, 0.0, None)
        if grew:
            _m_grows.inc()
        else:
            _m_remeshes.inc()
        telemetry.trace.instant("elastic/remesh" if not grew
                                else "elastic/grow",
                                generation=rdzv.generation,
                                alive=len(self._mesh_hosts))
        log.warning("re-rendezvoused into generation %d with %d host(s) "
                    "%s", rdzv.generation, len(doc["ranks"]),
                    sorted(doc["ranks"]))

    def _negotiate_generation(self, hb, dead):
        """Decide the next generation's membership and either propose it
        (leader) or await it (everyone else). Below ``min_hosts`` the
        fleet WAITS for joining heartbeats to restore quorum — a killed
        process that relaunches re-enters the same fit instead of
        forcing a full-size relaunch."""
        from ..parallel import distributed as dist
        rdzv = self._rdzv
        host_id = rdzv.host_id
        deadline = time.monotonic() + float(os.environ.get(
            dist.ENV_REJOIN_TIMEOUT, dist.DEFAULT_REJOIN_TIMEOUT))
        while True:
            self.supervisor.tick()
            alive = set(self.supervisor.alive_hosts()) - set(dead)
            joiners = set(self.supervisor.joining_hosts())
            # a dead-verdict host whose heartbeat is FRESH and stamped
            # with the current (or newer) generation is a live member we
            # mis-verdicted across a rendezvous gap — it cannot earn a
            # grow verdict (its beacon is flagless), so recognize it
            # here or the fleet deadlocks waiting for a joiner that
            # already joined
            for h in self.supervisor.dead_hosts():
                if h in dead or h in joiners:
                    continue
                d = self.supervisor._read_doc(h)
                if (d is not None
                        and int(d.get("generation") or 0)
                        >= rdzv.generation):
                    age = self.supervisor._doc_age(
                        h, d, self.supervisor._join_fresh)
                    if age is not None and age <= self.grace:
                        joiners.add(h)
            members = sorted(alive)
            for h in sorted(joiners - alive):
                if len(members) < self.max_hosts:
                    members.append(h)
            members = sorted(members)
            if host_id not in members:
                # evicted (or mis-verdicted): park as a joiner until a
                # future generation readmits us
                hb.set_joining(True)
                return rdzv.await_membership(rdzv.generation + 1)
            if len(members) >= self.min_hosts:
                # lease-aware election: the fresh leaseholder proposes;
                # an expired lease is taken over by the lowest-rank
                # fresh member (members only contains fresh hosts)
                if host_id == rdzv.elect_leader(members, max_age=0.0):
                    return rdzv.propose(members)
                # follower: wait as long as the leader might (it may be
                # holding for quorum before proposing)
                return rdzv.await_membership(
                    rdzv.generation + 1,
                    timeout=max(5.0, deadline - time.monotonic()))
            if time.monotonic() >= deadline:
                raise ElasticFleetLost(
                    f"{len(members)} host(s) alive < min_hosts "
                    f"({self.min_hosts}) and no rejoin within the "
                    f"window; relaunch the fleet against checkpointDir "
                    f"{self.checkpoint_dir!r} to resume")
            log.warning("fleet below min_hosts (%d alive, need %d); "
                        "waiting for joining heartbeats",
                        len(members), self.min_hosts)
            time.sleep(max(0.1, self.supervisor.probe_interval))
