"""A k8s-operator-shaped reconciler loop for the serving fleet.

The orchestration adapter ROADMAP item 2 calls for, simulated in-tree
the way multi-host fleets already are: one loop owns **desired vs
observed** worker state and converges the difference every tick, the
way a Deployment controller converges replicas (PAPERS.md arxiv
1810.08744 motivates the one-control-plane-over-many-engines shape).

* **Desired** is a replica count — written by the operator or by the
  :class:`~.autoscale.ServingAutoscaler`'s grow/shrink verdicts
  (``set_desired``); clamped to ``[min_workers, max_workers]``.
* **Observed** is the fleet's live capacity: workers that are alive and
  not draining.
* **Converge** each tick:

  1. *heal* — the embedded :class:`~.supervisor.FleetSupervisor` tick
     (health probes, exponential-backoff respawn): a kill -9'd worker is
     relaunched **into the same slot** — same ports, same
     ``extra_argv`` (``--bundle`` included, so the fresh incarnation
     answers warm; ``--timeseries`` included, so a federated fleet's
     respawned worker keeps feeding the driver's
     :class:`~..telemetry.federation.FleetScraper` — its counters
     restart at zero and the merge absorbs the reset) — the serving
     fleet's "same rendezvous lineage";
  2. *drain progress* — draining workers are retired the moment
     :meth:`~...io.http.fleet.ProcessHTTPSource.drainComplete` holds
     (nothing in flight anywhere: zero loss by construction), or
     force-retired past ``drain_timeout`` / on mid-drain death (their
     clients died with them);
  3. *scale up* — capacity below desired spawns workers through the
     same respawn machinery (chaos site ``fleet.spawn``), preferring
     retired slots (a shrink followed by a grow resurrects the same
     lineage) before appending fresh ones;
  4. *scale down* — capacity above desired begins a graceful drain of
     the highest-index workers (chaos site ``fleet.drain`` inside the
     control round-trip): they shed new requests, finish what they
     admitted, then exit. The fleet parks nothing.

:meth:`state` is the ``reconciler`` section of the driver's fleet-level
``/healthz`` doc (:func:`fleet_doc`)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults
from .supervisor import FleetSupervisor

log = get_logger("resilience.reconciler")

_m_desired = telemetry.registry.gauge(
    "mmlspark_autoscale_desired_workers",
    "serving replicas the control plane wants (autoscaler verdicts / "
    "operator set_desired, clamped to the min/max floors)")
_m_observed = telemetry.registry.gauge(
    "mmlspark_autoscale_observed_workers",
    "serving replicas actually providing capacity (alive, not draining)")
_m_spawns = telemetry.registry.counter(
    "mmlspark_autoscale_spawns",
    "workers spawned by the reconciler converging desired > observed")
_m_spawn_failures = telemetry.registry.counter(
    "mmlspark_autoscale_spawn_failures",
    "reconciler spawn attempts that failed (retried next tick)")
_m_drains = telemetry.registry.counter(
    "mmlspark_autoscale_drains",
    "graceful drains begun by the reconciler converging desired < "
    "observed")


def default_spawn_factory(host: str = "127.0.0.1",
                          max_queue_depth: int = 0,
                          extra_argv: tuple = ()) -> Callable:
    """The subprocess spawn/respawn callable: ``(wi, old) -> _Worker``.
    With ``old`` it rebinds the old incarnation's ports and serving
    flags (the supervisor-respawn contract — same lineage); without, it
    spawns a fresh worker on kernel-assigned ports. ``extra_argv``
    (e.g. ``("--bundle", dir)``) makes every spawned worker come up
    warm from the AOT bundle."""
    def spawn(wi: int, old):
        from ..io.http.fleet import _Worker
        if old is not None:
            try:
                old.kill()   # reap; no-op for never-spawned handles
            except Exception:
                pass
            return _Worker(old.host, old.port, old.control, spawn=True,
                           extra_argv=getattr(old, "extra_argv", ())
                           or tuple(extra_argv))
        return _Worker(host, 0, 0, spawn=True,
                       max_queue_depth=max_queue_depth,
                       extra_argv=tuple(extra_argv))
    return spawn


class FleetReconciler:
    """Desired-vs-observed convergence over a ``ProcessHTTPSource``.

    ``spawn(wi, old_or_None) -> worker`` is the single worker factory —
    shared with the embedded supervisor's respawn, so healing and
    scaling produce identical incarnations (in-process chaos tests
    substitute WorkerServer factories). ``supervise=False`` skips the
    embedded supervisor (a caller that already runs one)."""

    def __init__(self, source, replicas: int,
                 spawn: Optional[Callable] = None,
                 min_workers: int = 1, max_workers: int = 8,
                 interval: float = 0.25, drain_timeout: float = 10.0,
                 supervise: bool = True,
                 probe_interval: float = 0.25,
                 extra_argv: tuple = ()):
        if not 1 <= min_workers <= max_workers:
            raise ValueError(f"need 1 <= min_workers <= max_workers, got "
                             f"({min_workers}, {max_workers})")
        self.source = source
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.interval = float(interval)
        self.drain_timeout = float(drain_timeout)
        self.spawn = spawn or default_spawn_factory(extra_argv=extra_argv)
        self.supervisor = (FleetSupervisor(source,
                                           probe_interval=probe_interval,
                                           respawn=self.spawn)
                           if supervise else None)
        self._desired = self._clamp(replicas)
        # tick() runs on the daemon thread while state() serves healthz
        # request threads (and deterministic tests drive tick directly)
        self._lock = threading.RLock()
        self._drain_started: dict[int, float] = {}      # guarded-by: _lock
        self._last_error: Optional[str] = None          # guarded-by: _lock
        self._converged_at: Optional[float] = None      # guarded-by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-reconciler")
        _m_desired.set(self._desired)

    def _clamp(self, n: int) -> int:
        return max(self.min_workers, min(self.max_workers, int(n)))

    # ---- the control-plane write surface ----
    @property
    def desired(self) -> int:
        return self._desired

    def set_desired(self, replicas: int) -> int:
        """Write the desired replica count (the autoscaler's verdict
        sink); the loop converges toward it. Returns the clamped value."""
        n = self._clamp(replicas)
        if n != self._desired:
            log.info("desired replicas %d -> %d", self._desired, n)
        self._desired = n
        _m_desired.set(n)
        return n

    # ---- observation ----
    def capacity_slots(self) -> list:
        """Indices of workers currently providing capacity."""
        return [wi for wi, w in enumerate(self.source.workers)
                if w.alive and not w.draining and not w.retired]

    def observed(self) -> int:
        return len(self.capacity_slots())

    def converged(self) -> bool:
        with self._lock:
            return (self.observed() == self._desired
                    and not self._drain_started)

    # ---- convergence ----
    # requires-lock: _lock
    def _spawn_into(self, wi: Optional[int], now: float) -> bool:
        """One spawn attempt (``wi`` = retired/dead slot to resurrect,
        None = append a fresh worker). Failures are counted and retried
        next tick — a flapping spawn path must not kill the loop."""
        try:
            faults.inject("fleet.spawn")
            if wi is not None:
                nw = self.spawn(wi, self.source.workers[wi])
                self.source.restoreWorker(wi, worker=nw,
                                          resurrected=False)
            else:
                nw = self.spawn(len(self.source.workers), None)
                wi = self.source.addWorker(nw)
            _m_spawns.inc()
            telemetry.trace.instant("fleet/spawn", worker=wi,
                                    port=nw.port)
            telemetry.flight.note("fleet/spawn", worker=wi, port=nw.port)
            self._last_error = None
            return True
        except Exception as e:
            _m_spawn_failures.inc()
            self._last_error = f"spawn: {e}"
            # _lock serializes whole reconcile passes BY DESIGN (the
            # spawn itself blocks under it); rare failure logging under
            # it is inherent  # graftlint: disable=lock-blocking-call
            log.warning("reconciler spawn failed (retried next tick): %s",
                        e)
            return False

    def tick(self, now: Optional[float] = None):
        """One reconcile pass (public: deterministic tests drive it
        directly instead of sleeping against the thread)."""
        now = time.monotonic() if now is None else now
        if self.supervisor is not None:
            self.supervisor.tick()
        with self._lock:
            self._tick_locked(now)

    # requires-lock: _lock
    def _tick_locked(self, now: float):
        # 1. progress draining workers toward retirement
        for wi, w in enumerate(list(self.source.workers)):
            if not w.draining:
                self._drain_started.pop(wi, None)
                continue
            started = self._drain_started.setdefault(wi, now)
            done = False
            if not w.alive:
                done = True        # died mid-drain: its clients are gone
            else:
                try:
                    done = self.source.drainComplete(wi)
                except Exception as e:
                    self._last_error = f"drain probe: {e}"
            if done or now - started >= self.drain_timeout:
                if not done:
                    # rare drain-timeout path under the by-design
                    # whole-tick lock
                    # graftlint: disable=lock-blocking-call
                    log.warning("worker %d force-retired after %.1fs "
                                "drain timeout", wi, self.drain_timeout)
                self.source.retireWorker(wi)
                self._drain_started.pop(wi, None)
        # 2. converge capacity toward desired
        capacity = self.capacity_slots()
        desired = self._desired
        # dead non-retired slots are the supervisor's backoff-governed
        # healing in progress: count them as pending capacity, or a
        # grow verdict during a heal would overshoot and then drain
        healing = sum(1 for w in self.source.workers
                      if not w.alive and not w.retired and not w.draining)
        if len(capacity) + healing < desired:
            # prefer resurrecting retired slots (same lineage) over
            # appending new ones
            free = [wi for wi, w in enumerate(self.source.workers)
                    if w.retired]
            for _ in range(desired - len(capacity) - healing):
                slot = free.pop(0) if free else None
                if not self._spawn_into(slot, now):
                    break           # retry the rest next tick
        elif len(capacity) > desired:
            for wi in sorted(capacity, reverse=True)[
                    :len(capacity) - desired]:
                self.source.beginDrain(wi)
                if self.source.workers[wi].draining:
                    self._drain_started[wi] = now
                    _m_drains.inc()
        observed = self.observed()
        _m_observed.set(observed)
        if observed == desired and not self._drain_started:
            if self._converged_at is None:
                self._converged_at = now
        else:
            self._converged_at = None

    def state(self) -> dict:
        """The ``reconciler`` section of the fleet-level healthz doc."""
        with self._lock:
            return {"desired": self._desired,
                    "observed": self.observed(),
                    "min_workers": self.min_workers,
                    "max_workers": self.max_workers,
                    "draining": sorted(self._drain_started),
                    "retired": [wi for wi, w in
                                enumerate(self.source.workers)
                                if w.retired],
                    "converged": self.converged(),
                    "last_error": self._last_error}

    # ---- lifecycle ----
    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:   # a converge bug must not kill the loop
                log.warning("reconciler tick failed: %s", e)
            self._stop.wait(self.interval)

    def start(self) -> "FleetReconciler":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
