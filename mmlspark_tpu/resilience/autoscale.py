"""SLO-burn-driven autoscaling verdicts for the serving fleet.

Elasticity used to stop at training (`resilience/elastic.py`): the
serving fleet could *heal* (supervisor restarts) but not *scale*, so a
burst it could absorb by growing was shed as 503s instead. This module
closes ROADMAP item 2 the way production TPU serving does it (PAPERS.md
arxiv 2605.25645): the :class:`~..telemetry.slo.SLOEngine`'s burn
verdicts on latency/goodput objectives ARE the scaling signal.

:class:`ServingAutoscaler` evaluates the engine each tick and turns
sustained pressure into verdicts written to the
:class:`~.reconciler.FleetReconciler`'s desired replica count. The
engine's sampler decides WHOSE latency burns the budget: under fleet
federation (``serve_autoscaled(federate=True)``) it is the merged
:class:`~..telemetry.federation.FederatedSampler`, so a latency breach
that exists only inside worker processes — invisible to every
driver-side series — still burns, grows the fleet, and sheds with a
burn-derived Retry-After at every worker door:

* **GROW** — a watched objective (by default every ``latency`` /
  ``goodput`` objective) in **breach** continuously for ``grow_window``
  seconds adds one replica. The reconciler spawns it through the
  supervisor respawn machinery; with ``--bundle`` workers it comes up
  warm (zero live-traffic compiles).
* **SHRINK** — every watched objective **ok** AND driver-observed load
  under ``idle_rows_per_worker`` rows/s/worker continuously for
  ``shrink_window`` seconds removes one replica, by graceful drain
  (the worker stops admitting, finishes its in-flight exchanges, then
  exits; nothing is parked).
* **Hysteresis** — the windows are separate (grow fast, shrink slow)
  and every verdict opens a ``cooldown`` during which NO verdict fires:
  a burn that recovers inside the cooldown produces nothing, and a
  square-wave load can force at most one transition per cooldown
  window. ``min_workers``/``max_workers`` floor and cap the fleet.

Verdicts pass chaos site ``autoscale.verdict`` — an injected fault
skips (and counts) that tick's verdict without killing the loop; the
pressure trackers keep accumulating, so the verdict fires next tick.

``tick(now=...)`` is deterministic (tests drive it with the same
synthetic clock they tick the sampler with); :meth:`start` runs it on a
daemon thread. :meth:`state` is the ``autoscale`` section of the
fleet-level ``/healthz`` doc.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Iterable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults

log = get_logger("resilience.autoscale")

_m_verdicts = telemetry.registry.counter(
    "mmlspark_autoscale_verdicts",
    "grow/shrink verdicts applied to the desired replica count",
    labels=("verdict",))
_m_verdicts_skipped = telemetry.registry.counter(
    "mmlspark_autoscale_verdicts_skipped",
    "verdicts skipped by an injected fault at autoscale.verdict "
    "(re-issued on a later tick while the pressure persists)")
_m_load = telemetry.registry.gauge(
    "mmlspark_autoscale_load_rows_per_worker",
    "driver-observed arrival rate per capacity worker (the SHRINK "
    "side's idle signal)")
_m_state = telemetry.registry.gauge(
    "mmlspark_autoscale_state",
    "autoscaler pressure: 0 steady, 1 grow pressure accumulating, "
    "-1 shrink pressure accumulating, 2 in post-verdict cooldown")


class ServingAutoscaler:
    """Burn verdicts -> desired replicas, with hysteresis.

    ``slo`` is a started-or-not :class:`SLOEngine` (the autoscaler calls
    ``evaluate(now)`` itself each tick — don't also ``start()`` the
    engine); ``reconciler`` receives ``set_desired`` writes.
    ``objectives`` restricts the watched set by name (default: every
    ``latency`` / ``goodput`` objective). ``load_fn() -> rows/s`` totals
    fleet arrivals for the idle signal; the default derives it from the
    source's offset-log advancement between ticks."""

    def __init__(self, slo, reconciler, *,
                 grow_window: float = 1.0, shrink_window: float = 10.0,
                 cooldown: float = 5.0,
                 idle_rows_per_worker: float = 1.0,
                 objectives: Optional[Iterable[str]] = None,
                 load_fn: Optional[Callable[[], float]] = None,
                 interval: float = 0.5):
        if grow_window <= 0 or shrink_window <= 0 or cooldown < 0:
            raise ValueError("windows must be > 0 and cooldown >= 0")
        self.slo = slo
        self.reconciler = reconciler
        self.grow_window = float(grow_window)
        self.shrink_window = float(shrink_window)
        self.cooldown = float(cooldown)
        self.idle_rows_per_worker = float(idle_rows_per_worker)
        names = {o.name for o in slo.objectives}
        if objectives is not None:
            objectives = list(objectives)
            unknown = [n for n in objectives if n not in names]
            if unknown:
                raise ValueError(f"autoscaler watches unknown "
                                 f"objective(s) {unknown} (engine has "
                                 f"{sorted(names)})")
            self.objectives = objectives
        else:
            self.objectives = [o.name for o in slo.objectives
                               if o.kind in ("latency", "goodput")]
        if not self.objectives:
            raise ValueError("no latency/goodput objectives to scale on "
                             "(pass objectives=[...] explicitly)")
        self.load_fn = load_fn
        self.interval = float(interval)
        # tick() runs on the daemon thread while state() serves healthz
        # request threads (and deterministic tests drive tick directly):
        # every mutable verdict field below is guarded by _lock
        self._lock = threading.RLock()
        self._breach_since: Optional[float] = None      # guarded-by: _lock
        self._idle_since: Optional[float] = None        # guarded-by: _lock
        self._cooldown_until = 0.0                      # guarded-by: _lock
        self._last_verdict: Optional[str] = None        # guarded-by: _lock
        self._last_offset: Optional[tuple[float, int]] = None  # guarded-by: _lock
        self._load = None                               # guarded-by: _lock
        self._now = 0.0                                 # guarded-by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-autoscaler")

    # ------------------------------------------------------------- signals
    # requires-lock: _lock
    def _observe_load(self, now: float) -> Optional[float]:
        """Rows/s per capacity worker since the last tick (None until
        two observations exist)."""
        if self.load_fn is not None:
            total = float(self.load_fn())
        else:
            src = self.reconciler.source
            offset = int(src._offset)
            prev = self._last_offset
            self._last_offset = (now, offset)
            if prev is None or now <= prev[0]:
                return None
            total = max(0, offset - prev[1]) / (now - prev[0])
        per = total / max(1, self.reconciler.observed())
        _m_load.set(per)
        return per

    # ------------------------------------------------------------ verdicts
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation pass; returns the applied verdict (``"grow"``
        / ``"shrink"`` / None). ``now`` drives BOTH the SLO evaluation
        and the hysteresis clocks, so tests replay scenarios exactly."""
        t = time.time() if now is None else float(now)
        with self._lock:
            return self._tick_locked(t)

    # requires-lock: _lock
    def _tick_locked(self, t: float) -> Optional[str]:
        self._now = t
        state = self.slo.evaluate(now=t)
        watched = {n: state[n] for n in self.objectives if n in state}
        breach = any(r["state"] == "breach" for r in watched.values())
        calm = all(r["state"] == "ok" for r in watched.values())
        self._load = load = self._observe_load(t)
        desired = self.reconciler.desired
        # pressure accumulation (tracked even through cooldown: a burn
        # that OUTLIVES the cooldown fires the moment it ends, but one
        # that recovers inside it leaves no trace)
        if breach and desired < self.reconciler.max_workers:
            if self._breach_since is None:
                self._breach_since = t
        else:
            self._breach_since = None
        idle = (calm and load is not None
                and load < self.idle_rows_per_worker)
        if idle and desired > self.reconciler.min_workers:
            if self._idle_since is None:
                self._idle_since = t
        else:
            self._idle_since = None
        verdict = None
        if t >= self._cooldown_until:
            if (self._breach_since is not None
                    and t - self._breach_since >= self.grow_window):
                verdict = "grow"
            elif (self._idle_since is not None
                    and t - self._idle_since >= self.shrink_window):
                verdict = "shrink"
        _m_state.set(2 if t < self._cooldown_until
                     else 1 if self._breach_since is not None
                     else -1 if self._idle_since is not None else 0)
        if verdict is None:
            return None
        try:
            faults.inject("autoscale.verdict")
        except Exception:
            # the verdict is skipped, not lost: pressure keeps
            # accumulating and the next clean tick re-issues it
            _m_verdicts_skipped.inc()
            return None
        new = desired + (1 if verdict == "grow" else -1)
        applied = self.reconciler.set_desired(new)
        self._cooldown_until = t + self.cooldown
        self._breach_since = None
        self._idle_since = None
        self._last_verdict = verdict
        _m_verdicts.labels(verdict=verdict).inc()
        burns = {n: r["burn_fast"] for n, r in watched.items()}
        if verdict == "grow":
            telemetry.trace.instant("autoscale/grow", desired=applied,
                                    load_per_worker=load)
        else:
            telemetry.trace.instant("autoscale/shrink", desired=applied,
                                    load_per_worker=load)
        telemetry.flight.note(f"autoscale/{verdict}", desired=applied,
                              burns={k: (v if isinstance(v, (int, float))
                                         and math.isfinite(v) else "inf")
                                     for k, v in burns.items()})
        # _lock serializes whole ticks against healthz readers BY
        # DESIGN; a verdict fires at most once per cooldown window, so
        # logging under it is inherent, not a contention bug
        # graftlint: disable=lock-blocking-call
        log.warning("autoscale %s verdict: desired -> %d (burns %s, "
                    "load/worker %s)", verdict, applied, burns,
                    None if load is None else round(load, 2))
        return verdict

    def state(self) -> dict:
        """The ``autoscale`` section of the fleet-level healthz doc.
        Durations are measured against the LAST tick's clock, so
        synthetic-clock tests read consistent numbers."""
        with self._lock:
            return self._state_locked()

    # requires-lock: _lock
    def _state_locked(self) -> dict:
        now = self._now or time.time()
        return {"desired": self.reconciler.desired,
                "objectives": list(self.objectives),
                "grow_window_s": self.grow_window,
                "shrink_window_s": self.shrink_window,
                "cooldown_s": self.cooldown,
                "cooldown_remaining_s": round(
                    max(0.0, self._cooldown_until - now), 3),
                "breach_for_s": (None if self._breach_since is None
                                 else round(now - self._breach_since, 3)),
                "idle_for_s": (None if self._idle_since is None
                               else round(now - self._idle_since, 3)),
                "load_rows_per_worker": self._load,
                "last_verdict": self._last_verdict}

    # ----------------------------------------------------------- lifecycle
    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # a verdict bug must not kill the loop
                log.warning("autoscaler tick failed: %s", e)
            self._stop.wait(self.interval)

    def start(self) -> "ServingAutoscaler":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
