"""Deterministic, env-gated fault injection.

Chaos engineering needs faults that are (a) OFF by default with no
measurable overhead, (b) seeded so a failing CI run replays exactly, and
(c) injected at NAMED sites inside the real code paths rather than via
monkeypatching, so the recovery path exercised is the one production runs.

Spec grammar (``MMLSPARK_TPU_FAULTS`` or :func:`configure`)::

    site:kind:rate[:arg[:arg2]] [; site:kind:rate...]

    fleet.poll:error:0.1                 10% of driver poll round-trips raise
    dataplane.put:delay:0.05:0.02        5% of device puts sleep 20ms
    trainer.step:error:1.0:5             every step faults AFTER 5 clean calls
    serving.transform:error:1.0:0:1      fault the first call only (budget 1)

Kinds:

* ``error`` — raise :class:`InjectedFault` (a ConnectionError subclass, so
  the shared RetryPolicy classifies it transient). Optional args:
  ``after`` (skip the first N calls — arms a mid-run kill) and ``budget``
  (max injections — fail-once-then-recover scenarios).
* ``delay`` — sleep ``arg`` seconds (default 10ms): latency injection for
  tail-latency and timeout testing.

Each (site, fault) pair draws from its own ``random.Random`` seeded from
``seed ^ crc32(site)`` (``MMLSPARK_TPU_FAULTS_SEED``, default 0), so sites
are independent and the whole run is reproducible. Injection sites call
:func:`inject` — one function call + module-bool check when disabled.

Registered sites (see docs/reliability.md): ``fleet.poll``,
``fleet.respond``, ``fleet.transform``, ``fleet.spawn``,
``fleet.drain``, ``serving.transform``,
``serving.batch``, ``serving.bundle_load``,
``http.request``, ``http.debug``, ``powerbi.post``, ``dataplane.put``,
``dataplane.allgather``, ``trainer.step``, ``supervisor.probe``,
``supervisor.heartbeat``, ``supervisor.rejoin``, ``elastic.step``,
``elastic.remesh``, ``elastic.evict``, ``autoscale.verdict``,
``distributed.rendezvous``, ``distributed.lease``, ``ckpt.write``,
``ckpt.rename``, ``ckpt.shard``, ``downloader.fetch``,
``codegen.write``, ``federation.scrape``, ``federation.merge``,
``automl.trial``, ``automl.promote``, ``automl.report``.
"""

from __future__ import annotations

import threading
import time
import zlib
from random import Random
from typing import Optional

from .. import telemetry
from ..core.utils import get_logger

log = get_logger("resilience.faults")

_m_injected = telemetry.registry.counter(
    "mmlspark_faults_injected_total",
    "faults injected by site and kind", labels=("site", "kind"))

KINDS = ("error", "delay")

#: the canonical injection-site registry. graftlint's ``fault-site``
#: consistency rule keeps this tuple in lockstep with the actual
#: ``faults.inject(...)`` call sites across the tree, and
#: :func:`configure` warns when a chaos spec names a site not listed
#: here — a typo'd site would otherwise inject nothing, silently.
SITES = ("fleet.poll", "fleet.respond", "fleet.transform",
         "fleet.spawn", "fleet.drain",
         "serving.transform", "serving.batch", "serving.bundle_load",
         "http.request", "http.debug",
         "powerbi.post", "dataplane.put", "dataplane.allgather",
         "trainer.step", "supervisor.probe", "supervisor.heartbeat",
         "supervisor.rejoin", "elastic.step", "elastic.remesh",
         "elastic.evict", "autoscale.verdict",
         "distributed.rendezvous", "distributed.lease", "ckpt.write",
         "ckpt.rename", "ckpt.shard", "downloader.fetch",
         "codegen.write", "federation.scrape", "federation.merge",
         "automl.trial", "automl.promote", "automl.report")


class InjectedFault(ConnectionError):
    """The error kind's exception. ConnectionError subclass: transient
    under the default RetryPolicy classification, so injected faults
    exercise the same recovery path a real network blip would."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at site {site!r}")
        self.site = site


class _Fault:
    __slots__ = ("site", "kind", "rate", "delay", "after", "budget",
                 "rng", "lock", "calls", "injected")

    def __init__(self, site: str, kind: str, rate: float, args: list,
                 seed: int):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} for site "
                             f"{site!r} (kinds: {KINDS})")
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fault rate for {site!r} must be in [0, 1], "
                             f"got {rate}")
        self.site = site
        self.kind = kind
        self.rate = rate
        self.delay = float(args[0]) if kind == "delay" and args else 0.01
        self.after = int(float(args[0])) if kind == "error" and args else 0
        self.budget = (int(float(args[1]))
                       if kind == "error" and len(args) > 1 else None)
        self.rng = Random(seed ^ zlib.crc32(site.encode()))
        self.lock = threading.Lock()
        self.calls = 0
        self.injected = 0


_plans: dict[str, list[_Fault]] = {}
_active = False


def parse(spec: str) -> list[tuple[str, str, float, list]]:
    """Parse the fault-spec grammar; raises ValueError on malformed specs
    (a typo'd chaos config must fail loudly, not silently inject nothing)."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 3:
            raise ValueError(
                f"malformed fault spec {part!r}: need site:kind:rate[:arg]")
        site, kind, rate = fields[0].strip(), fields[1].strip(), fields[2]
        out.append((site, kind, float(rate), fields[3:]))
    return out


def configure(spec: str, seed: Optional[int] = None) -> int:
    """Install a fault plan (replacing any previous one); returns the
    number of faults armed. ``seed=None`` reads
    ``MMLSPARK_TPU_FAULTS_SEED`` (default 0)."""
    global _active
    if seed is None:
        from ..core.env import fault_seed
        seed = fault_seed()
    plans: dict[str, list[_Fault]] = {}
    for site, kind, rate, args in parse(spec):
        if site not in SITES:
            # warn, don't raise: tests arm ad-hoc sites, but a typo'd
            # production chaos spec must at least say so in the log
            log.warning("fault spec names unregistered site %r "
                        "(registered: %s)", site, ", ".join(SITES))
        plans.setdefault(site, []).append(_Fault(site, kind, rate, args,
                                                 seed))
    _plans.clear()
    _plans.update(plans)
    _active = bool(_plans)
    n = sum(len(v) for v in _plans.values())
    if n:
        log.warning("fault injection ARMED: %d fault(s) at sites %s "
                    "(seed %d)", n, sorted(_plans), seed)
    return n


def clear():
    """Disarm all faults; :func:`inject` returns to its no-op fast path."""
    global _active
    _plans.clear()
    _active = False


def active() -> bool:
    return _active


def snapshot() -> dict:
    """{site: [{kind, rate, calls, injected}]} — test/bench introspection."""
    return {site: [{"kind": f.kind, "rate": f.rate, "calls": f.calls,
                    "injected": f.injected} for f in fs]
            for site, fs in sorted(_plans.items())}


def inject(site: str):
    """The injection site hook. Disabled (the default): one module-bool
    check and return. Armed: draw from the site's seeded RNG; raise
    :class:`InjectedFault` or sleep per the plan."""
    if not _active:
        return
    faults = _plans.get(site)
    if not faults:
        return
    for f in faults:
        with f.lock:
            f.calls += 1
            if f.kind == "error" and f.calls <= f.after:
                continue
            if f.budget is not None and f.injected >= f.budget:
                continue
            hit = f.rate >= 1.0 or f.rng.random() < f.rate
            if hit:
                f.injected += 1
        if hit:
            _m_injected.labels(site=site, kind=f.kind).inc()
            # fault markers land in the span trace (tagged with the
            # owning request's context when one is active) AND the
            # flight-recorder ring — a chaos-run artifact shows exactly
            # which injections preceded the failure
            telemetry.trace.instant("fault/injected", site=site,
                                    kind=f.kind)
            if f.kind == "delay":
                time.sleep(f.delay)
            else:
                raise InjectedFault(site)


def _init_from_env():
    from ..core.env import fault_spec
    spec = fault_spec()
    if spec:
        configure(spec)


_init_from_env()
