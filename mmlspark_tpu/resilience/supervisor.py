"""Fleet supervision: health probing, worker restart, row redispatch.

The serving fleet (io/http/fleet.py) contains failure DETECTION — a poll
or reply round-trip that fails marks the worker dead after a failed health
check — but the seed had no RECOVERY: a dead worker stayed dead forever,
its uncommitted rows were stranded, and `killWorker` existed purely as a
failure-injection hook with nothing on the other side.

:class:`FleetSupervisor` closes the loop. A background thread ticks every
``probe_interval`` seconds:

* **live workers** are probed (``GET /healthz`` on the control port); a
  probe failure confirmed by the worker's own dead-verdict
  (``probably_dead``) marks it dead through
  ``source.markWorkerDead`` — which parks its uncommitted rows and
  undelivered replies instead of dropping them;
* **dead workers** are recovered, with exponential backoff between
  attempts:

  - *resurrection*: the process is still running and answers its health
    check (the death verdict was spurious — a timeout blip, an injected
    probe fault). ``source.restoreWorker(..., resurrected=True)`` returns
    the parked rows to the offset log and re-buffers the parked replies:
    the worker's in-flight exchanges are still alive, so its blocked
    clients get their replies instead of hanging until reply_timeout;
  - *restart*: the process is gone. ``respawn`` launches a fresh worker on
    the SAME ports (clients' retries hit the same URL);
    ``source.restoreWorker(..., resurrected=False)`` drops the parked
    state — the old incarnation's client sockets died with it — and
    counts it;

* finally the tick flushes the source, so parked/retried replies are
  delivered promptly even when no new batch is flowing.

``respawn(worker_index, old_worker) -> new_worker`` is pluggable: the
default respawns the worker subprocess; in-process chaos tests substitute
a factory building a fresh in-process WorkerServer.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults

log = get_logger("resilience.supervisor")

_m_probes = telemetry.registry.counter(
    "mmlspark_supervisor_probes_total", "worker health probes issued")
_m_probe_failures = telemetry.registry.counter(
    "mmlspark_supervisor_probe_failures_total",
    "failed worker health probes, by worker index", labels=("worker",))
_m_restarts = telemetry.registry.counter(
    "mmlspark_supervisor_worker_restarts_total",
    "dead workers replaced with a fresh process", labels=("worker",))
_m_resurrections = telemetry.registry.counter(
    "mmlspark_supervisor_worker_resurrections_total",
    "workers restored after a spurious death verdict", labels=("worker",))
_m_restart_failures = telemetry.registry.counter(
    "mmlspark_supervisor_restart_failures_total",
    "respawn attempts that themselves failed", labels=("worker",))


def _default_respawn(wi: int, old):
    """Respawn the worker subprocess on the old incarnation's ports (the
    server sockets use SO_REUSEADDR, so the rebind succeeds immediately
    and client retries land on the same URL). ``extra_argv`` is
    preserved, so a federated worker's ``--timeseries`` flag survives
    the restart — the fresh incarnation's cumulative series restart at
    zero, a monotonic reset the driver's FederatedSampler absorbs."""
    from ..io.http.fleet import _Worker
    try:
        old.kill()   # reap the zombie; no-op for already-waited procs
    except Exception:
        pass
    return _Worker(old.host, old.port, old.control, spawn=True,
                   extra_argv=getattr(old, "extra_argv", ()))


class _Recovery:
    __slots__ = ("next_try", "backoff", "restarts")

    def __init__(self, base: float):
        self.next_try = 0.0
        self.backoff = base
        self.restarts = 0


class FleetSupervisor:
    """Self-healing loop over a ``ProcessHTTPSource``-shaped fleet.

    ``source`` must expose ``workers`` (handles with ``alive``, ``host``,
    ``control``, ``proc``, ``probably_dead()``), ``markWorkerDead(i)``,
    ``restoreWorker(i, worker=None, resurrected=False)`` and ``flush()``.
    ``max_restarts`` bounds restarts PER WORKER (0 = unbounded); a worker
    over its budget is left dead and logged once.
    """

    def __init__(self, source, probe_interval: float = 0.25,
                 probe_timeout: float = 1.0,
                 restart_backoff: float = 0.2,
                 max_restart_backoff: float = 5.0,
                 max_restarts: int = 0,
                 respawn: Optional[Callable] = None):
        self.source = source
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.restart_backoff = restart_backoff
        self.max_restart_backoff = max_restart_backoff
        self.max_restarts = max_restarts
        self.respawn = respawn or _default_respawn
        # tick() runs on this supervisor's own daemon thread AND on the
        # FleetReconciler's (reconciler.tick calls supervisor.tick), so
        # recovery bookkeeping must serialize on a lock
        self._lock = threading.RLock()
        self._recovery: dict[int, _Recovery] = {}       # guarded-by: _lock
        self._gave_up: set[int] = set()                 # guarded-by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-supervisor")

    # ---- probing ----
    def _healthy(self, w) -> bool:
        """One control-plane health round-trip. /healthz with a /health
        fallback keeps the probe compatible with pre-resilience workers."""
        _m_probes.inc()
        try:
            faults.inject("supervisor.probe")
            for path in ("/healthz", "/health"):
                try:
                    with urllib.request.urlopen(
                            f"http://{w.host}:{w.control}{path}",
                            timeout=self.probe_timeout) as r:
                        if r.status == 200:
                            return True
                except urllib.error.HTTPError:
                    continue   # 404: try the fallback path
            return False
        except Exception:
            return False

    def _process_exited(self, w) -> bool:
        return w.proc is not None and w.proc.poll() is not None

    # ---- recovery ----
    # requires-lock: _lock
    def _recover(self, wi: int, w, now: float):
        rec = self._recovery.setdefault(
            wi, _Recovery(self.restart_backoff))
        if now < rec.next_try:
            return
        if not self._process_exited(w) and self._healthy(w):
            # spurious death verdict: the process is alive and answering —
            # restore it and redispatch its parked rows/replies
            self.source.restoreWorker(wi, resurrected=True)
            _m_resurrections.labels(worker=str(wi)).inc()
            telemetry.flight.note("supervisor/resurrect", worker=wi)
            # _lock serializes whole supervision passes BY DESIGN (two
            # tick threads: our own and the reconciler's); rare
            # recovery-path logging under it is inherent
            # graftlint: disable=lock-blocking-call
            log.warning("worker %d resurrected (death verdict was "
                        "spurious); parked rows redispatched", wi)
            self._recovery.pop(wi, None)
            return
        if self.max_restarts and rec.restarts >= self.max_restarts:
            if wi not in self._gave_up:
                self._gave_up.add(wi)
                # logged once per worker ever
                # graftlint: disable=lock-blocking-call
                log.error("worker %d: restart budget (%d) exhausted; "
                          "leaving it dead", wi, self.max_restarts)
            return
        rec.restarts += 1
        rec.next_try = now + rec.backoff
        rec.backoff = min(self.max_restart_backoff, rec.backoff * 2)
        try:
            nw = self.respawn(wi, w)
        except Exception as e:
            _m_restart_failures.labels(worker=str(wi)).inc()
            # backoff-governed failure path under the by-design
            # whole-tick lock  # graftlint: disable=lock-blocking-call
            log.warning("worker %d respawn attempt %d failed (next in "
                        "%.2fs): %s", wi, rec.restarts, rec.backoff, e)
            return
        self.source.restoreWorker(wi, worker=nw, resurrected=False)
        _m_restarts.labels(worker=str(wi)).inc()
        telemetry.flight.note("supervisor/restart", worker=wi,
                              attempt=rec.restarts, port=nw.port)
        # restart is already a whole-process spawn under this lock;
        # the log line is noise by comparison
        # graftlint: disable=lock-blocking-call
        log.warning("worker %d restarted (attempt %d) on port %d",
                    wi, rec.restarts, nw.port)
        self._recovery.pop(wi, None)
        self._gave_up.discard(wi)

    def tick(self):
        """One supervision pass (public: deterministic tests drive it
        directly instead of sleeping against the thread)."""
        now = time.monotonic()
        with self._lock:
            for wi, w in enumerate(list(self.source.workers)):
                # draining / retired workers belong to the reconciler's
                # scale-down lifecycle: healing one would respawn
                # capacity the autoscaler just decided to shed
                if getattr(w, "retired", False) or getattr(w, "draining",
                                                           False):
                    continue
                if getattr(w, "alive", False):
                    if self._process_exited(w) or (
                            not self._healthy(w) and w.probably_dead()):
                        _m_probe_failures.labels(worker=str(wi)).inc()
                        telemetry.flight.note("supervisor/death_verdict",
                                              worker=wi)
                        self.source.markWorkerDead(
                            wi, reason="supervisor probe")
                else:
                    self._recover(wi, w, now)
        # deliver parked / retry-buffered replies even when no new batch
        # is flowing through the serving loop
        try:
            self.source.flush()
        except Exception as e:
            log.warning("supervisor flush failed: %s", e)

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:   # a probe bug must not kill the loop
                log.warning("supervisor tick failed: %s", e)
            self._stop.wait(self.probe_interval)

    def start(self) -> "FleetSupervisor":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
