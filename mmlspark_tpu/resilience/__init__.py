"""Resilience subsystem: unified retry/backoff/circuit-breaking policies,
deterministic fault injection, and serving-fleet supervision.

The ROADMAP north star is a serving system for "heavy traffic from millions
of users"; at that scale transient failure is the steady state, not the
exception. Before this package every network/IO call site hand-rolled its
own recovery (or had none): the fleet driver dropped undeliverable replies,
the PowerBI writer retried on a fixed interval, the trainer checkpointed
only at epoch boundaries, and nothing restarted a dead serving worker.

Three pillars, adopted across io/http, io/powerbi, parallel/dataplane and
the trainer:

  * :mod:`policy`     — :class:`RetryPolicy` (exponential backoff, full
                        jitter, deadline budget, transient-vs-fatal error
                        classification) and :class:`CircuitBreaker`
                        (closed/open/half-open, per-target);
  * :mod:`faults`     — seeded, env-gated fault injection
                        (``MMLSPARK_TPU_FAULTS``) with named sites threaded
                        through the serving/data/training paths, so every
                        recovery path is testable on CPU in CI;
  * :mod:`supervisor` — :class:`FleetSupervisor`: health probing, automatic
                        worker restart with backoff, and redispatch of a
                        dead worker's in-flight rows;
  * :mod:`elastic`    — elastic training: :class:`TrainSupervisor`
                        (heartbeat-file death verdicts with a grace
                        window, restart-vs-shrink decisions) and
                        :class:`ElasticFitCoordinator` (re-mesh over
                        surviving hosts + consensus-checkpoint resume —
                        a fit survives a preempted host);
  * :mod:`autoscale`  — :class:`ServingAutoscaler`: the SLO engine's
                        burn verdicts drive serving-fleet GROW, sustained
                        idle drives SHRINK, with hysteresis windows +
                        cooldown and min/max floors;
  * :mod:`reconciler` — :class:`FleetReconciler`: the k8s-operator-shaped
                        loop converging desired vs observed workers
                        (heal into the same lineage, spawn warm from
                        bundles, graceful drain on scale-down).

Everything reports through :mod:`mmlspark_tpu.telemetry` (retry counters,
breaker-state gauges, injected-fault counters, restart counters); see
docs/reliability.md.
"""

from __future__ import annotations

from . import ckpt, faults
from .autoscale import ServingAutoscaler
from .ckpt import AsyncCheckpointWriter
from .elastic import (ElasticFitCoordinator, ElasticFleetLost,
                      HostHeartbeat, HostLossError, HostRejoinError,
                      TrainSupervisor)
from .policy import BreakerOpen, CircuitBreaker, RetryPolicy
from .reconciler import FleetReconciler
from .supervisor import FleetSupervisor

__all__ = ["faults", "ckpt", "BreakerOpen", "CircuitBreaker",
           "RetryPolicy", "FleetSupervisor", "TrainSupervisor",
           "ElasticFitCoordinator", "ElasticFleetLost", "HostHeartbeat",
           "HostLossError", "HostRejoinError", "AsyncCheckpointWriter",
           "ServingAutoscaler", "FleetReconciler"]
