"""Async non-blocking checkpoints with a torn-write-proof commit protocol.

PR 3's step-interval checkpoints made long fits preemption-tolerant, but
every save stalls the step loop while msgpack hits disk — so
``checkpointEverySteps`` stays large and a host loss replays a large
window. This module splits the save into the two halves that actually
have different costs:

* **snapshot** (caller, synchronous): ``jax.device_get`` the training
  state into pinned host arrays — cheap, and REQUIRED to be synchronous
  because the very next optimizer step donates those device buffers;
* **serialize + publish** (background thread): msgpack the host tree and
  run the commit protocol below, overlapped with the next steps.

The queue is bounded at depth 1 with **newest-wins coalescing**: when the
step loop outruns the disk, intermediate snapshots are dropped (counted on
``mmlspark_ckpt_coalesced_total``) rather than back-pressuring the fit —
a checkpoint's only job is to bound the replay window, and the newest one
bounds it best.  :meth:`AsyncCheckpointWriter.wait` is the barrier the
trainer takes at epoch end and fit exit, so an epoch boundary or a fit
return never races its own pending write.

Commit protocol (shared by the synchronous path — ``publish()``):

1. write ``<path>.tmp.<pid>`` (fault site ``ckpt.write``), flush + fsync;
2. ``os.replace`` tmp -> final (fault site ``ckpt.rename``) — atomic, so
   a *partial* file can never carry the final name;
3. commit ``manifest.json`` LAST (its own write-then-fsync-then-rename),
   recording the file's size + sha256.

A crash anywhere in 1-3 therefore leaves either no file, or a complete
file that is **not in the manifest** — and resume treats "exists but
unverified" exactly like "corrupt": skip it, warn, count it on
``mmlspark_ckpt_corrupt_total``, and fall back to the previous
checkpoint.  The consensus candidate is always a manifest-verified file.
Both ordering invariants — fsync strictly before the publishing rename,
manifest strictly after every payload/shard write — are enforced
statically by graftlint's ``protocol-rename-before-fsync`` /
``protocol-manifest-order`` rules (docs/static-analysis.md), so a
refactor that reorders them fails CI instead of waiting for a power
cut.

**Sharded checkpoints** extend the same protocol to models too big for
one host's msgpack: the training state (flattened to ``path -> leaf``)
is split into N byte-balanced shards, each committed as its own
``<stem>.shard_<i>.msgpack`` file (fault site ``ckpt.shard``, same
tmp-write + fsync + rename discipline, NO per-shard manifest entry), a
small **head** file under the canonical ``ckpt_E[_sS].msgpack`` name
records the shard list, and the manifest — still committed LAST, by the
coordinator, after every shard is verified present with size + sha256 —
becomes the multi-shard commit record (the head's manifest entry carries
a ``shards`` map). Resume reads the head, then every shard (content-
hashed against the manifest), and reassembles the tree; shard count is
recorded in the manifest, so an N-shard checkpoint restores onto any
mesh size. **A torn shard disqualifies the whole candidate**: verify()
fails the head, the resume falls back to the previous committed
checkpoint, and the skip is counted.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Callable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults

log = get_logger("resilience.ckpt")

_m_write_seconds = telemetry.registry.histogram(
    "mmlspark_ckpt_write_seconds",
    "background serialize + write + fsync + rename + manifest-commit time "
    "per published checkpoint")
_m_coalesced = telemetry.registry.counter(
    "mmlspark_ckpt_coalesced_total",
    "checkpoint snapshots dropped by newest-wins coalescing (the step "
    "loop outran the disk; the newest snapshot bounds the replay window "
    "best, so nothing durable is lost)")
_m_corrupt = telemetry.registry.counter(
    "mmlspark_ckpt_corrupt_total",
    "checkpoint files skipped at resume because they were partial, "
    "corrupt, or not committed to the manifest (each skip falls back to "
    "the previous checkpoint)")
_m_wait_seconds = telemetry.registry.histogram(
    "mmlspark_ckpt_wait_seconds",
    "time the fit actually blocked on the async-checkpoint barrier "
    "(epoch end / fit exit); ~0 when the disk keeps up")
_m_shards_written = telemetry.registry.counter(
    "mmlspark_ckpt_shards_written_total",
    "checkpoint shard files committed (tmp-write + fsync + rename; the "
    "head + manifest commit follows once every shard landed)")

MANIFEST = "manifest.json"


class CorruptCheckpoint(RuntimeError):
    """A checkpoint file failed content verification (manifest digest
    mismatch or undecodable payload). Resume catches it and falls back to
    the previous checkpoint."""


def note_corrupt(name: str, reason: str):
    """Count + trace one corrupt-checkpoint sighting (callers that decode
    the payload themselves — e.g. a msgpack parse failure on a
    pre-manifest file — report through here so the counter stays the one
    place to alert on)."""
    _m_corrupt.inc()
    telemetry.trace.instant("ckpt/corrupt", file=name, reason=reason)
    log.warning("checkpoint %s is corrupt (%s) — falling back to the "
                "previous checkpoint", name, reason)


def manifest_path(directory: str) -> str:
    return os.path.join(directory, MANIFEST)


def load_manifest(directory: str) -> Optional[dict]:
    """The committed manifest's ``files`` map, or None when the directory
    predates manifests (every file passes verification then — old
    checkpoint dirs stay resumable)."""
    try:
        with open(manifest_path(directory), "r", encoding="utf-8") as f:
            doc = json.load(f)
        return dict(doc.get("files", {}))
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        # an unreadable manifest must not brick the resume outright: warn
        # and fall back to manifest-less verification
        log.warning("checkpoint manifest %s unreadable; skipping "
                    "verification", manifest_path(directory))
        return None


def _commit_manifest(directory: str, files: dict):
    """Write-then-fsync-then-rename the manifest — the LAST step of the
    commit protocol, so its presence implies every listed file landed."""
    path = manifest_path(directory)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "files": files}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish(path: str, data: bytes, extra: Optional[dict] = None):
    """Commit one checkpoint file: tmp write + fsync (site ``ckpt.write``),
    atomic rename (site ``ckpt.rename``), manifest entry committed last.
    ``extra`` merges additional JSON-able keys into the manifest entry —
    e.g. the trainer's fused-fit ``featurize_digest``, which resume uses
    to reject candidates written under a different featurize plan."""
    directory, name = os.path.split(path)
    t0 = time.perf_counter()
    with telemetry.trace.span("ckpt/write", file=name, bytes=len(data)):
        faults.inject("ckpt.write")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        faults.inject("ckpt.rename")
        os.replace(tmp, path)
        files = load_manifest(directory) or {}
        files[name] = {"size": len(data),
                       "sha256": hashlib.sha256(data).hexdigest(),
                       **(extra or {})}
        _commit_manifest(directory, files)
    _m_write_seconds.observe(time.perf_counter() - t0)


def verify(directory: str, name: str) -> bool:
    """Is ``name`` a legitimate consensus candidate? True when the
    directory has no manifest (pre-manifest checkpoints), or when the
    manifest lists the file with a matching on-disk size — and, for a
    sharded checkpoint, every shard the head's manifest entry records is
    present with its committed size. A file the manifest doesn't know,
    a size that disagrees, or ANY torn/missing shard disqualifies the
    whole candidate: count it and skip it."""
    files = load_manifest(directory)
    if files is None:
        return True
    entry = files.get(name)
    try:
        size = os.path.getsize(os.path.join(directory, name))
    except OSError:
        return False
    if entry is None or int(entry.get("size", -1)) != size:
        _m_corrupt.inc()
        telemetry.trace.instant("ckpt/corrupt", file=name,
                                reason="unlisted" if entry is None
                                else "size")
        log.warning(
            "checkpoint %s is %s — skipping it as a resume candidate "
            "(falling back to the previous checkpoint)", name,
            "not committed to the manifest (torn write?)" if entry is None
            else f"{size} bytes but the manifest recorded "
                 f"{entry.get('size')}")
        return False
    for sname, sentry in (entry.get("shards") or {}).items():
        try:
            ssize = os.path.getsize(os.path.join(directory, sname))
        except OSError:
            ssize = -1
        if int(sentry.get("size", -1)) != ssize:
            _m_corrupt.inc()
            telemetry.trace.instant("ckpt/corrupt", file=sname,
                                    reason="shard")
            log.warning(
                "checkpoint %s shard %s is %s — the torn shard "
                "disqualifies the whole candidate (falling back to the "
                "previous checkpoint)", name, sname,
                "missing" if ssize < 0
                else f"{ssize} bytes vs {sentry.get('size')} committed")
            return False
    return True


# ---- sharded checkpoints ---------------------------------------------------

def shard_name(name: str, index: int) -> str:
    """``ckpt_E[_sS].msgpack`` -> ``ckpt_E[_sS].shard_<i>.msgpack``. The
    shard suffix keeps the stem non-numeric, so shard files are never
    mistaken for standalone resume candidates by the trainer's
    checkpoint-name parser."""
    stem = name[:-len(".msgpack")] if name.endswith(".msgpack") else name
    return f"{stem}.shard_{index}.msgpack"


def write_shard(path: str, data: bytes):
    """Commit ONE shard file: tmp write + fsync (fault site
    ``ckpt.shard``) then atomic rename. Deliberately no manifest entry —
    a shard only becomes part of a durable checkpoint when the
    coordinator's head + manifest commit (``commit_sharded``) lands
    after verifying every shard."""
    name = os.path.basename(path)
    with telemetry.trace.span("ckpt/write", file=name, bytes=len(data)):
        faults.inject("ckpt.shard")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    _m_shards_written.inc()


def head_payload(shard_names) -> bytes:
    """The head file's bytes: a tiny JSON document naming the shards.
    Committed under the canonical checkpoint name so the existing
    candidate discovery finds sharded checkpoints unchanged."""
    return json.dumps({"sharded": {"version": 1,
                                   "shards": list(shard_names)}},
                      sort_keys=True).encode("utf-8")


def parse_head(data: bytes):
    """The shard list when ``data`` is a sharded-checkpoint head, else
    None (a regular msgpack checkpoint)."""
    if not data.startswith(b'{"sharded"'):
        return None
    try:
        return list(json.loads(data.decode("utf-8"))["sharded"]["shards"])
    except (ValueError, KeyError, TypeError):
        return None


def await_shards(directory: str, names, timeout: float = 60.0) -> bool:
    """Coordinator-side barrier before the head + manifest commit: every
    shard file must be present (rename is atomic, so presence implies a
    complete, fsynced write). Multi-host sharded saves call this on the
    coordinator while peers publish their own shards to shared storage."""
    deadline = time.monotonic() + timeout
    while True:
        missing = [n for n in names
                   if not os.path.exists(os.path.join(directory, n))]
        if not missing:
            return True
        if time.monotonic() >= deadline:
            log.warning("sharded checkpoint commit timed out waiting for "
                        "shard(s) %s", missing)
            return False
        time.sleep(0.02)


def commit_sharded(path: str, shard_names,
                   extra: Optional[dict] = None) -> None:
    """The coordinator's LAST step of a sharded save: verify every shard
    on disk (size + sha256 recorded into the manifest), publish the head
    under the canonical name, then commit the manifest whose head entry
    carries the ``shards`` map (plus any ``extra`` keys — see
    :func:`publish`). Raises OSError when a shard vanished —
    the save fails loudly rather than committing a torn record."""
    directory, name = os.path.split(path)
    shards = {}
    for sname in shard_names:
        with open(os.path.join(directory, sname), "rb") as f:
            blob = f.read()
        shards[sname] = {"size": len(blob),
                         "sha256": hashlib.sha256(blob).hexdigest()}
    data = head_payload(shard_names)
    with telemetry.trace.span("ckpt/write", file=name, bytes=len(data),
                              shards=len(shards)):
        faults.inject("ckpt.write")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        faults.inject("ckpt.rename")
        os.replace(tmp, path)
        files = load_manifest(directory) or {}
        files[name] = {"size": len(data),
                       "sha256": hashlib.sha256(data).hexdigest(),
                       "shards": shards,
                       **(extra or {})}
        _commit_manifest(directory, files)


def publish_sharded(path: str, shard_payloads,
                    extra: Optional[dict] = None) -> None:
    """Single-writer sharded commit (single-process fits, simulated
    hosts): write every shard, then run the coordinator's head +
    manifest commit. One host's failure domain, N files — the layout is
    identical to the multi-host case, so resume code has one path."""
    t0 = time.perf_counter()
    names = []
    for i, data in enumerate(shard_payloads):
        sname = shard_name(os.path.basename(path), i)
        write_shard(os.path.join(os.path.dirname(path), sname), data)
        names.append(sname)
    commit_sharded(path, names, extra=extra)
    _m_write_seconds.observe(time.perf_counter() - t0)


def read_shards(directory: str, shard_names) -> list:
    """Read + content-verify every shard of a committed checkpoint.
    Raises :class:`CorruptCheckpoint` on a digest mismatch — resume
    falls back to the previous candidate."""
    blobs = []
    for sname in shard_names:
        try:
            with open(os.path.join(directory, sname), "rb") as f:
                blob = f.read()
        except OSError as e:
            note_corrupt(sname, f"shard unreadable: {e}")
            raise CorruptCheckpoint(sname) from e
        if not verify_bytes(directory, sname, blob):
            raise CorruptCheckpoint(sname)
        blobs.append(blob)
    return blobs


_EMPTY = "__mmlspark_empty_dict__"


def flatten_state(nested, _prefix=()) -> dict:
    """Flatten a flax state dict into ``{"a/b/c": leaf}`` (empty dicts
    kept via a sentinel so the round trip is exact) — the unit sharded
    checkpoints partition."""
    out = {}
    if isinstance(nested, dict):
        if not nested:
            out["/".join(_prefix)] = _EMPTY
        for k, v in nested.items():
            out.update(flatten_state(v, _prefix + (str(k),)))
        return out
    out["/".join(_prefix)] = nested
    return out


def unflatten_state(flat: dict):
    """Inverse of :func:`flatten_state`."""
    nested: dict = {}
    for key in sorted(flat):
        val = flat[key]
        parts = key.split("/")
        d = nested
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = {} if (isinstance(val, str) and val == _EMPTY) \
            else val
    return nested


def partition_leaves(sizes, n_shards: int) -> list:
    """Contiguous partition of ``len(sizes)`` leaves into ``n_shards``
    byte-balanced groups (greedy cut at the running-total boundaries).
    Deterministic given (sizes, n_shards) — every host computes the
    identical split, so host i can serialize shard i alone."""
    n_shards = max(1, min(int(n_shards), max(1, len(sizes))))
    total = float(sum(sizes)) or 1.0
    bounds = []
    acc = 0.0
    cut = 1
    for i, s in enumerate(sizes):
        acc += s
        while cut < n_shards and acc >= total * cut / n_shards:
            bounds.append(i + 1)
            cut += 1
    starts = [0] + bounds
    ends = bounds + [len(sizes)]
    return [list(range(a, b)) for a, b in zip(starts, ends)]


def _manifest_entry(files: dict, name: str) -> Optional[dict]:
    """The manifest record for ``name``: a top-level file entry, or a
    shard entry found under some head's ``shards`` map."""
    entry = files.get(name)
    if entry is not None:
        return entry
    for head in files.values():
        sentry = (head.get("shards") or {}).get(name)
        if sentry is not None:
            return sentry
    return None


def verify_bytes(directory: str, name: str, data: bytes) -> bool:
    """Content check at restore time: the read bytes must hash to the
    manifest's digest (bit-rot / concurrent-truncation defense beyond the
    size check). Shard files resolve their digest through the head's
    ``shards`` map."""
    files = load_manifest(directory)
    if files is None:
        return True      # unverifiable dirs already passed verify()
    entry = _manifest_entry(files, name)
    if entry is None:
        return True
    digest = entry.get("sha256")
    if digest and hashlib.sha256(data).hexdigest() != digest:
        _m_corrupt.inc()
        telemetry.trace.instant("ckpt/corrupt", file=name, reason="sha256")
        log.warning("checkpoint %s content does not match its manifest "
                    "digest — skipping it", name)
        return False
    return True


def prune(directory: str, names) -> None:
    """Remove checkpoint files AND their manifest entries (one manifest
    commit for the batch). A sharded checkpoint's head takes its shard
    files with it. Missing files are fine — another process may have
    pruned first on shared storage."""
    names = [n for n in names]
    if not names:
        return
    files = load_manifest(directory)
    for n in list(names):
        entry = (files or {}).get(n) or {}
        names.extend((entry.get("shards") or {}).keys())
    for n in names:
        try:
            os.remove(os.path.join(directory, n))
        except OSError:
            pass
    if files:
        kept = {k: v for k, v in files.items() if k not in set(names)}
        if len(kept) != len(files):
            try:
                _commit_manifest(directory, kept)
            except OSError as e:
                log.warning("manifest prune failed (kept stale entries, "
                            "harmless): %s", e)


class AsyncCheckpointWriter:
    """Depth-1, newest-wins background checkpoint publisher.

    ``submit(path, payload_fn, on_commit)`` enqueues one checkpoint whose
    bytes are produced by ``payload_fn()`` ON THE WRITER THREAD (that's
    where the msgpack serialization cost goes); a submit that finds a
    not-yet-started entry replaces it (newest-wins — the superseded
    snapshot's ``on_commit`` never fires, mirroring that it never became
    durable). ``on_commit`` runs on the writer thread strictly AFTER the
    rename + manifest commit — the elastic journal's
    ``checkpoint_saved`` hook rides it, so a grow re-mesh can only target
    checkpoints that are actually on disk.

    A write error is remembered and re-raised at the next :meth:`submit`
    or :meth:`wait` (the step loop must learn its durability story broke,
    not train on thinking it has checkpoints it doesn't).
    """

    def __init__(self, name: str = "ckpt"):
        self._cond = threading.Condition()
        self._pending: Optional[tuple] = None  # guarded-by: _cond
        self._in_flight = False                # guarded-by: _cond
        self._error: Optional[BaseException] = None  # guarded-by: _cond
        self._closed = False                   # guarded-by: _cond
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ckpt-writer-{name}")
        self._thread.start()

    def submit(self, path: str, payload_fn: Callable[[], bytes],
               on_commit: Optional[Callable[[], None]] = None,
               publish_fn: Optional[Callable] = None):
        """``publish_fn(path, payload)`` overrides the single-file
        :func:`publish` commit — sharded saves pass their own commit
        (per-rank shard write, coordinator head + manifest)."""
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            coalesced = self._pending is not None
            self._pending = (path, payload_fn, on_commit, publish_fn)
            self._cond.notify_all()
        if coalesced:
            _m_coalesced.inc()
            log.info("checkpoint %s coalesced away by a newer snapshot",
                     os.path.basename(path))

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Barrier: block until no checkpoint is pending or in flight.
        Returns False on timeout. Re-raises a writer-thread error."""
        t0 = time.perf_counter()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending is not None or self._in_flight:
                remain = (None if deadline is None
                          else deadline - time.monotonic())
                if remain is not None and remain <= 0:
                    return False
                self._cond.wait(remain if remain is not None else 0.5)
            if self._error is not None:
                err, self._error = self._error, None
                raise err
        _m_wait_seconds.observe(time.perf_counter() - t0)
        return True

    def close(self):
        """Flush and stop. Swallows nothing: a pending error surfaces."""
        try:
            self.wait()
        finally:
            with self._cond:
                self._closed = True
                self._cond.notify_all()
            if self._thread.is_alive():
                self._thread.join(timeout=5)

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait(0.5)
                if self._pending is None and self._closed:
                    return
                entry, self._pending = self._pending, None
                self._in_flight = True
            # serialize + IO happen OUTSIDE the lock: submit() stays a
            # dict swap while a write is in flight
            path, payload_fn, on_commit, publish_fn = entry
            try:
                (publish_fn or publish)(path, payload_fn())
                if on_commit is not None:
                    on_commit()
            except BaseException as e:
                log.warning("async checkpoint %s failed: %s",
                            os.path.basename(path), e)
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._in_flight = False
                    self._cond.notify_all()
