"""Async non-blocking checkpoints with a torn-write-proof commit protocol.

PR 3's step-interval checkpoints made long fits preemption-tolerant, but
every save stalls the step loop while msgpack hits disk — so
``checkpointEverySteps`` stays large and a host loss replays a large
window. This module splits the save into the two halves that actually
have different costs:

* **snapshot** (caller, synchronous): ``jax.device_get`` the training
  state into pinned host arrays — cheap, and REQUIRED to be synchronous
  because the very next optimizer step donates those device buffers;
* **serialize + publish** (background thread): msgpack the host tree and
  run the commit protocol below, overlapped with the next steps.

The queue is bounded at depth 1 with **newest-wins coalescing**: when the
step loop outruns the disk, intermediate snapshots are dropped (counted on
``mmlspark_ckpt_coalesced_total``) rather than back-pressuring the fit —
a checkpoint's only job is to bound the replay window, and the newest one
bounds it best.  :meth:`AsyncCheckpointWriter.wait` is the barrier the
trainer takes at epoch end and fit exit, so an epoch boundary or a fit
return never races its own pending write.

Commit protocol (shared by the synchronous path — ``publish()``):

1. write ``<path>.tmp.<pid>`` (fault site ``ckpt.write``), flush + fsync;
2. ``os.replace`` tmp -> final (fault site ``ckpt.rename``) — atomic, so
   a *partial* file can never carry the final name;
3. commit ``manifest.json`` LAST (its own write-then-fsync-then-rename),
   recording the file's size + sha256.

A crash anywhere in 1-3 therefore leaves either no file, or a complete
file that is **not in the manifest** — and resume treats "exists but
unverified" exactly like "corrupt": skip it, warn, count it on
``mmlspark_ckpt_corrupt_total``, and fall back to the previous
checkpoint.  The consensus candidate is always a manifest-verified file.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Callable, Optional

from .. import telemetry
from ..core.utils import get_logger
from . import faults

log = get_logger("resilience.ckpt")

_m_write_seconds = telemetry.registry.histogram(
    "mmlspark_ckpt_write_seconds",
    "background serialize + write + fsync + rename + manifest-commit time "
    "per published checkpoint")
_m_coalesced = telemetry.registry.counter(
    "mmlspark_ckpt_coalesced_total",
    "checkpoint snapshots dropped by newest-wins coalescing (the step "
    "loop outran the disk; the newest snapshot bounds the replay window "
    "best, so nothing durable is lost)")
_m_corrupt = telemetry.registry.counter(
    "mmlspark_ckpt_corrupt_total",
    "checkpoint files skipped at resume because they were partial, "
    "corrupt, or not committed to the manifest (each skip falls back to "
    "the previous checkpoint)")
_m_wait_seconds = telemetry.registry.histogram(
    "mmlspark_ckpt_wait_seconds",
    "time the fit actually blocked on the async-checkpoint barrier "
    "(epoch end / fit exit); ~0 when the disk keeps up")

MANIFEST = "manifest.json"


class CorruptCheckpoint(RuntimeError):
    """A checkpoint file failed content verification (manifest digest
    mismatch or undecodable payload). Resume catches it and falls back to
    the previous checkpoint."""


def note_corrupt(name: str, reason: str):
    """Count + trace one corrupt-checkpoint sighting (callers that decode
    the payload themselves — e.g. a msgpack parse failure on a
    pre-manifest file — report through here so the counter stays the one
    place to alert on)."""
    _m_corrupt.inc()
    telemetry.trace.instant("ckpt/corrupt", file=name, reason=reason)
    log.warning("checkpoint %s is corrupt (%s) — falling back to the "
                "previous checkpoint", name, reason)


def manifest_path(directory: str) -> str:
    return os.path.join(directory, MANIFEST)


def load_manifest(directory: str) -> Optional[dict]:
    """The committed manifest's ``files`` map, or None when the directory
    predates manifests (every file passes verification then — old
    checkpoint dirs stay resumable)."""
    try:
        with open(manifest_path(directory), "r", encoding="utf-8") as f:
            doc = json.load(f)
        return dict(doc.get("files", {}))
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        # an unreadable manifest must not brick the resume outright: warn
        # and fall back to manifest-less verification
        log.warning("checkpoint manifest %s unreadable; skipping "
                    "verification", manifest_path(directory))
        return None


def _commit_manifest(directory: str, files: dict):
    """Write-then-fsync-then-rename the manifest — the LAST step of the
    commit protocol, so its presence implies every listed file landed."""
    path = manifest_path(directory)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "files": files}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish(path: str, data: bytes):
    """Commit one checkpoint file: tmp write + fsync (site ``ckpt.write``),
    atomic rename (site ``ckpt.rename``), manifest entry committed last."""
    directory, name = os.path.split(path)
    t0 = time.perf_counter()
    with telemetry.trace.span("ckpt/write", file=name, bytes=len(data)):
        faults.inject("ckpt.write")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        faults.inject("ckpt.rename")
        os.replace(tmp, path)
        files = load_manifest(directory) or {}
        files[name] = {"size": len(data),
                       "sha256": hashlib.sha256(data).hexdigest()}
        _commit_manifest(directory, files)
    _m_write_seconds.observe(time.perf_counter() - t0)


def verify(directory: str, name: str) -> bool:
    """Is ``name`` a legitimate consensus candidate? True when the
    directory has no manifest (pre-manifest checkpoints), or when the
    manifest lists the file with a matching on-disk size. A file the
    manifest doesn't know, or whose size disagrees, is a torn/uncommitted
    write: count it and skip it."""
    files = load_manifest(directory)
    if files is None:
        return True
    entry = files.get(name)
    try:
        size = os.path.getsize(os.path.join(directory, name))
    except OSError:
        return False
    if entry is None or int(entry.get("size", -1)) != size:
        _m_corrupt.inc()
        telemetry.trace.instant("ckpt/corrupt", file=name,
                                reason="unlisted" if entry is None
                                else "size")
        log.warning(
            "checkpoint %s is %s — skipping it as a resume candidate "
            "(falling back to the previous checkpoint)", name,
            "not committed to the manifest (torn write?)" if entry is None
            else f"{size} bytes but the manifest recorded "
                 f"{entry.get('size')}")
        return False
    return True


def verify_bytes(directory: str, name: str, data: bytes) -> bool:
    """Content check at restore time: the read bytes must hash to the
    manifest's digest (bit-rot / concurrent-truncation defense beyond the
    size check)."""
    files = load_manifest(directory)
    if files is None or name not in files:
        return True      # unverifiable dirs already passed verify()
    digest = files[name].get("sha256")
    if digest and hashlib.sha256(data).hexdigest() != digest:
        _m_corrupt.inc()
        telemetry.trace.instant("ckpt/corrupt", file=name, reason="sha256")
        log.warning("checkpoint %s content does not match its manifest "
                    "digest — skipping it", name)
        return False
    return True


def prune(directory: str, names) -> None:
    """Remove checkpoint files AND their manifest entries (one manifest
    commit for the batch). Missing files are fine — another process may
    have pruned first on shared storage."""
    names = [n for n in names]
    if not names:
        return
    for n in names:
        try:
            os.remove(os.path.join(directory, n))
        except OSError:
            pass
    files = load_manifest(directory)
    if files:
        kept = {k: v for k, v in files.items() if k not in set(names)}
        if len(kept) != len(files):
            try:
                _commit_manifest(directory, kept)
            except OSError as e:
                log.warning("manifest prune failed (kept stale entries, "
                            "harmless): %s", e)


class AsyncCheckpointWriter:
    """Depth-1, newest-wins background checkpoint publisher.

    ``submit(path, payload_fn, on_commit)`` enqueues one checkpoint whose
    bytes are produced by ``payload_fn()`` ON THE WRITER THREAD (that's
    where the msgpack serialization cost goes); a submit that finds a
    not-yet-started entry replaces it (newest-wins — the superseded
    snapshot's ``on_commit`` never fires, mirroring that it never became
    durable). ``on_commit`` runs on the writer thread strictly AFTER the
    rename + manifest commit — the elastic journal's
    ``checkpoint_saved`` hook rides it, so a grow re-mesh can only target
    checkpoints that are actually on disk.

    A write error is remembered and re-raised at the next :meth:`submit`
    or :meth:`wait` (the step loop must learn its durability story broke,
    not train on thinking it has checkpoints it doesn't).
    """

    def __init__(self, name: str = "ckpt"):
        self._cond = threading.Condition()
        self._pending: Optional[tuple] = None  # guarded-by: _cond
        self._in_flight = False                # guarded-by: _cond
        self._error: Optional[BaseException] = None  # guarded-by: _cond
        self._closed = False                   # guarded-by: _cond
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ckpt-writer-{name}")
        self._thread.start()

    def submit(self, path: str, payload_fn: Callable[[], bytes],
               on_commit: Optional[Callable[[], None]] = None):
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            coalesced = self._pending is not None
            self._pending = (path, payload_fn, on_commit)
            self._cond.notify_all()
        if coalesced:
            _m_coalesced.inc()
            log.info("checkpoint %s coalesced away by a newer snapshot",
                     os.path.basename(path))

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Barrier: block until no checkpoint is pending or in flight.
        Returns False on timeout. Re-raises a writer-thread error."""
        t0 = time.perf_counter()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending is not None or self._in_flight:
                remain = (None if deadline is None
                          else deadline - time.monotonic())
                if remain is not None and remain <= 0:
                    return False
                self._cond.wait(remain if remain is not None else 0.5)
            if self._error is not None:
                err, self._error = self._error, None
                raise err
        _m_wait_seconds.observe(time.perf_counter() - t0)
        return True

    def close(self):
        """Flush and stop. Swallows nothing: a pending error surfaces."""
        try:
            self.wait()
        finally:
            with self._cond:
                self._closed = True
                self._cond.notify_all()
            if self._thread.is_alive():
                self._thread.join(timeout=5)

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait(0.5)
                if self._pending is None and self._closed:
                    return
                entry, self._pending = self._pending, None
                self._in_flight = True
            # serialize + IO happen OUTSIDE the lock: submit() stays a
            # dict swap while a write is in flight
            path, payload_fn, on_commit = entry
            try:
                publish(path, payload_fn())
                if on_commit is not None:
                    on_commit()
            except BaseException as e:
                log.warning("async checkpoint %s failed: %s",
                            os.path.basename(path), e)
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._in_flight = False
                    self._cond.notify_all()
