"""Sequence / context parallelism: ring attention, Ulysses, blockwise attention.

The reference has NO long-context story (SURVEY.md §5: "Long-context /
sequence parallelism: absent" — its only sequence model is a pre-trained
BiLSTM evaluated via CNTKModel, notebook 304, and sequence length never
exceeds one host). This module designs it in from the start, TPU-first, so
the attention path scales past single-chip HBM:

  * ``blockwise_attention`` — single-device memory-efficient attention:
    online-softmax over KV blocks via ``lax.scan`` (FlashAttention recurrence)
    so the (T, T) score matrix is never materialized. O(T) memory in sequence
    length instead of O(T^2).
  * ``ring_attention`` — context parallelism over a mesh axis: Q/K/V are
    sequence-sharded; KV shards rotate around the ICI ring via
    ``lax.ppermute`` while each device accumulates online-softmax partial
    results for its resident queries. Compute overlaps the neighbor exchange;
    memory per chip stays O(T / sp).
  * ``ulysses_attention`` — all-to-all sequence parallelism: two
    ``lax.all_to_all`` collectives re-shard (seq-sharded, all heads) ->
    (head-sharded, full seq), run dense local attention per head group, and
    re-shard back. Cheaper than ring when head count >= sp and ICI all-to-all
    bandwidth is plentiful.
  * ``make_sp_attention`` — wraps either collective form in ``shard_map`` over
    a named mesh axis, yielding a plain ``(q, k, v) -> o`` callable usable
    inside any flax module under ``jit``.

All collective math runs in float32 for the softmax statistics with bfloat16
matmul inputs (MXU-native). Shapes are static; the scan carries are
fixed-shape — everything XLA needs to pipeline DMA against compute.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import axis_size, shard_map

NEG_INF = -1e30


def _attend_block(q, k, v, qpos, kpos, causal: bool, scale: float,
                  kv_valid_below=None):
    """One (Q-resident, KV-block) attention step: returns (out_unnorm, m, l).

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); qpos: (Tq,), kpos: (Tk,) global
    positions for causal masking; kv_valid_below masks padded KV rows
    (kpos >= bound). Scores in float32.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = qpos[:, None] >= kpos[None, :]          # (Tq, Tk)
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    if kv_valid_below is not None:
        scores = jnp.where((kpos < kv_valid_below)[None, None, None, :],
                           scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                        # (B, H, Tq)
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)  # all-masked row -> 0
    l = jnp.sum(p, axis=-1)                             # (B, H, Tq)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out, m, l


def _online_merge(acc, m_acc, l_acc, out, m, l):
    """Merge a new block's (out, m, l) into the running (acc, m_acc, l_acc)
    via the numerically-stable online-softmax recurrence."""
    m_new = jnp.maximum(m_acc, m)
    corr_old = jnp.exp(m_acc - m_new)
    corr_new = jnp.exp(m - m_new)
    corr_old = jnp.where(m_acc <= NEG_INF / 2, 0.0, corr_old)
    corr_new = jnp.where(m <= NEG_INF / 2, 0.0, corr_new)
    l_new = l_acc * corr_old + l * corr_new
    acc_new = (acc * corr_old[..., None].transpose(0, 2, 1, 3)
               + out * corr_new[..., None].transpose(0, 2, 1, 3))
    return acc_new, m_new, l_new


def _finalize(acc, l):
    """acc: (B, Tq, H, D) unnormalized, l: (B, H, Tq) -> normalized output."""
    denom = l[..., None].transpose(0, 2, 1, 3)          # (B, Tq, H, 1)
    return acc / jnp.maximum(denom, 1e-30)


def blockwise_attention(q, k, v, block_size: int = 512,
                        causal: bool = False,
                        scale: Optional[float] = None):
    """Memory-efficient single-device attention (FlashAttention recurrence).

    q/k/v: (B, T, H, D). Scans over KV blocks with an online softmax so peak
    memory is O(B*H*Tq*block) instead of O(B*H*Tq*Tk). This is also the local
    kernel both SP forms call per shard.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    block_size = min(block_size, Tk)
    if Tk % block_size != 0:         # pad KV to a block multiple, mask pads
        pad = block_size - Tk % block_size
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = k.shape[1] // block_size
    qpos = jnp.arange(Tq)
    kb = k.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)

    def step(carry, blk):
        acc, m_acc, l_acc = carry
        k_i, v_i, i = blk
        kpos = i * block_size + jnp.arange(block_size)
        out, m, l = _attend_block(q, k_i, v_i, qpos, kpos, causal=causal,
                                  scale=scale, kv_valid_below=Tk)
        return _online_merge(acc, m_acc, l_acc, out, m, l), None

    init = (jnp.zeros((B, Tq, H, D), jnp.float32),
            jnp.full((B, H, Tq), NEG_INF, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32))
    (acc, m_acc, l_acc), _ = lax.scan(step, init,
                                      (kb, vb, jnp.arange(n_blocks)))
    return _finalize(acc, l_acc).astype(q.dtype)


# --------------------------------------------------------------- ring

def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Context-parallel attention over a mesh axis (call inside shard_map).

    Per device: q/k/v are the LOCAL sequence shard (B, T/sp, H, D). KV shards
    rotate around the ring with ``lax.ppermute`` (neighbor-only traffic —
    rides ICI links); each device folds every visiting KV block into its
    queries' online softmax. Global positions derived from the axis index
    keep causal masking exact across shards.

    Design: Ring Attention (Liu et al.) re-expressed as an XLA-collective
    scan — no NCCL/MPI analog needed (the reference's only rings are the
    LightGBM socket ring TrainUtils.scala:141 and the MPI ring
    CommandBuilders.scala:241, both CPU-side; here the ring IS the compute).
    """
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    qpos = idx * Tq + jnp.arange(Tq)
    perm = [(i, (i - 1) % sp) for i in range(sp)]   # shard s visits blocks
                                                    # s, s+1, ... (mod sp)

    # fold the resident block first, then sp-1 exchange+fold rounds — the
    # last round must not pay a ppermute whose result nobody reads
    out0, m0, l0 = _attend_block(q, k, v, qpos, idx * Tk + jnp.arange(Tk),
                                 causal=causal, scale=scale)
    acc0, macc0, lacc0 = _online_merge(
        jnp.zeros((B, Tq, H, D), jnp.float32),
        jnp.full((B, H, Tq), NEG_INF, jnp.float32),
        jnp.zeros((B, H, Tq), jnp.float32), out0, m0, l0)

    def step(carry, s):
        acc, m_acc, l_acc, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        src = (idx + s) % sp                        # owner of the block we hold
        kpos = src * Tk + jnp.arange(Tk)
        out, m, l = _attend_block(q, k_cur, v_cur, qpos, kpos,
                                  causal=causal, scale=scale)
        acc, m_acc, l_acc = _online_merge(acc, m_acc, l_acc, out, m, l)
        return (acc, m_acc, l_acc, k_cur, v_cur), None

    if sp > 1:
        (acc, m_acc, l_acc, _, _), _ = lax.scan(
            step, (acc0, macc0, lacc0, k, v), jnp.arange(1, sp))
    else:
        acc, l_acc = acc0, lacc0
    return _finalize(acc, l_acc).astype(q.dtype)


# --------------------------------------------------------------- ulysses

def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None,
                      block_size: int = 512):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses form), inside
    shard_map. Inputs are sequence-sharded (B, T/sp, H, D) with full heads;
    two ``lax.all_to_all`` re-shard to (B, T, H/sp, D) — full sequence,
    head-sharded — where dense local attention runs, then back. Requires
    H % sp == 0."""
    sp = axis_size(axis_name)
    H = q.shape[2]
    if H % sp != 0:
        raise ValueError(f"ulysses needs heads ({H}) divisible by sp ({sp})")
    # (B, T/sp, H, D) -> (B, T, H/sp, D): split heads, concat sequence
    def fwd(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
    def bwd(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
    qg, kg, vg = fwd(q), fwd(k), fwd(v)
    out = blockwise_attention(qg, kg, vg, block_size=block_size,
                              causal=causal, scale=scale)
    return bwd(out)


# --------------------------------------------------------------- shard_map

def make_sp_attention(mesh: Mesh, axis_name: str = "seq",
                      mode: str = "ring", causal: bool = False,
                      batch_axis: Optional[str] = "data"):
    """Build a plain ``(q, k, v) -> o`` attention callable that is sequence-
    parallel over ``axis_name`` (and batch-parallel over ``batch_axis`` when
    present in the mesh). Usable directly inside flax modules under jit —
    shard_map handles the collective placement; XLA overlaps the ppermutes
    with the per-block einsums.

    Inputs/outputs are GLOBAL (B, T, H, D); the sequence dim is sharded over
    ``axis_name`` inside."""
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}")
    b = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    spec = P(b, axis_name, None, None)

    if mode == "ring":
        local = functools.partial(ring_attention, axis_name=axis_name,
                                  causal=causal)
    elif mode == "ulysses":
        local = functools.partial(ulysses_attention, axis_name=axis_name,
                                  causal=causal)
    else:
        raise ValueError(f"unknown sp mode {mode!r} (ring|ulysses)")

    def attn(q, k, v):
        return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check=False)(q, k, v)
    return attn


def plain_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Dense reference attention (for tests and tiny sequences)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
