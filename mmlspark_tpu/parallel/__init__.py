from . import mesh
from .mesh import (batch_sharding, create_mesh, pad_batch_to_devices,
                   replicated, shard_batch, shard_params_tp)

__all__ = ["mesh", "create_mesh", "batch_sharding", "replicated",
           "shard_batch", "pad_batch_to_devices", "shard_params_tp"]
