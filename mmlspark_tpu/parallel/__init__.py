from . import (dataplane, distributed, mesh, pipeline_parallel, prefetch,
               sequence)
from .dataplane import ShardedDataFrame, shard_paths
from .mesh import (batch_sharding, create_mesh, make_mesh,
                   pad_batch_to_devices, replicated, shard_batch,
                   shard_params_tp)
from .pipeline_parallel import (pipeline_apply, shard_pipeline_params,
                                stack_stage_params)
from .prefetch import DevicePrefetcher, prefetched

__all__ = ["mesh", "sequence", "distributed", "pipeline_parallel",
           "dataplane", "prefetch", "ShardedDataFrame", "shard_paths",
           "create_mesh", "make_mesh", "batch_sharding", "replicated",
           "shard_batch", "pad_batch_to_devices", "shard_params_tp",
           "pipeline_apply", "stack_stage_params", "shard_pipeline_params",
           "DevicePrefetcher", "prefetched"]
