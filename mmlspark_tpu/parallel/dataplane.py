"""Distributed data plane: per-process DataFrame shards, SPMD execution.

The reference's unglamorous superpower is that *everything* runs
data-parallel over a cluster: every transform enters executors via
``DataFrame.mapPartitions`` (reference: cntk-model/.../CNTKModel.scala:255-261;
lightgbm/.../LightGBMClassifier.scala:35-47 coalesce→mapPartitions), so ETL,
featurization, and scoring scale out and never materialize the dataset on one
machine. This module is the TPU-native replacement:

  * N worker processes join one JAX runtime via ``parallel.distributed``
    (the MMLTPU_* env contract — the Spark-executor discovery analog);
  * each process holds a :class:`ShardedDataFrame` — ITS rows only, e.g.
    read from its share of the input files (:func:`shard_paths`);
  * row-wise transforms (the ``mapPartitions`` analog) are inherited
    unchanged and run on the local shard — embarrassingly parallel, zero
    communication, exactly like Spark executors;
  * global relational ops (groupBy/agg, distinct, join, limit) run as
    local partial aggregation + a host allgather + re-aggregation — the
    map-side-combine + shuffle shape, with the "shuffle" a single
    coordination-service collective because aggregates are small;
  * ``TpuLearner.fit`` / ``TpuModel.transform`` already consume per-process
    shards via ``mesh.put_global_batch`` (multi-host SPMD), so a sharded
    frame feeds training/scoring with no further glue.

Single-process mode degrades to the plain DataFrame behavior — same code
runs from a laptop to a pod, the framework-wide contract.
"""

from __future__ import annotations

import pickle
from typing import Optional, Sequence

import numpy as np

from ..core.dataframe import (DataFrame, GroupedData, _NULL_SENTINEL,
                              _copy_meta, _gather_with_nulls, _hashable)
from ..core.utils import get_logger, object_column
from .. import telemetry
from ..resilience import faults

log = get_logger("dataplane")

# fleet-collective telemetry: every host-side allgather/allreduce the data
# plane runs (pooled statistics, distinct/groupBy merges, stream lockstep)
_m_collective_bytes = telemetry.registry.counter(
    "mmlspark_dataplane_collective_bytes",
    "payload bytes this process contributed to host collectives")
_m_collectives = telemetry.registry.counter(
    "mmlspark_dataplane_collectives",
    "host collective operations issued (allgather_bytes calls)")


def nprocs() -> int:
    import jax
    from . import mesh as _meshlib
    return _meshlib.effective_process_count()


def pid() -> int:
    import jax
    from . import mesh as _meshlib
    # local-fit mode presents a single-process world: pid must be 0 when
    # nprocs() reports 1, or shard_paths-style arithmetic drops data
    return 0 if _meshlib.in_local_fit() else jax.process_index()


def shard_paths(paths: Sequence[str]) -> list[str]:
    """THIS process's share of an input file list (deterministic round-robin
    over the sorted list, so the fleet partitions the corpus exactly). The
    analog of Spark assigning input splits to executors."""
    return sorted(paths)[pid()::nprocs()]


def allgather_bytes(payload: bytes) -> list[bytes]:
    """Gather one bytes payload from every process (two fixed-shape
    collectives: lengths, then right-padded buffers)."""
    faults.inject("dataplane.allgather")
    if nprocs() == 1:
        return [payload]
    _m_collectives.inc()
    _m_collective_bytes.inc(len(payload))
    from jax.experimental import multihost_utils
    with telemetry.trace.span("dataplane/allgather", bytes=len(payload)):
        lens = multihost_utils.process_allgather(
            np.asarray(len(payload), np.int64))
        buf = np.frombuffer(payload, dtype=np.uint8)
        pad = int(lens.max()) - len(buf)
        if pad:
            buf = np.concatenate([buf, np.zeros(pad, np.uint8)])
        bufs = multihost_utils.process_allgather(buf)
    return [bufs[i, :int(lens[i])].tobytes() for i in range(len(lens))]


def allgather_pyobj(obj) -> list:
    """Gather an arbitrary picklable object from every process, in process
    order. The workhorse for merging fitted statistics (categorical level
    sets, imputation sums, partial aggregates) across the fleet."""
    return [pickle.loads(b) for b in allgather_bytes(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))]


def proportional_sample_cap(n_local: int, target: int) -> int:
    """How many of this process's ``n_local`` rows belong in a fleet-pooled
    sample of ~``target`` rows: contribution proportional to real shard
    size, so unbalanced shards are neither over- nor under-represented in
    pooled statistics (GBDT bin edges, init scores, EFB plans). One
    allgather; every process must call it together."""
    sizes = allgather_pyobj(int(n_local))
    total = max(1, sum(sizes))
    return max(1, int(round(target * n_local / total)))


def allreduce_sum(x):
    """Elementwise sum of a numeric array over all processes."""
    if nprocs() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(
        multihost_utils.process_allgather(np.asarray(x))).sum(axis=0)


def is_sharded(df) -> bool:
    """True when ``df`` is one process's shard of a fleet-wide frame AND the
    fleet has >1 process (single-process sharded frames behave plainly)."""
    return isinstance(df, ShardedDataFrame) and nprocs() > 1


def _gather_frames(df: DataFrame) -> DataFrame:
    """Union of every process's rows (replicated result on all processes).
    Only for results already reduced small — partial aggregates, distinct
    keys, broadcast-join sides — never the raw data plane."""
    parts = allgather_pyobj((df._cols, df._meta))
    out: Optional[DataFrame] = None
    for cols, meta in parts:
        part = DataFrame(dict(cols), metadata=meta)
        out = part if out is None else out.union(part)
    return out if out is not None else DataFrame({})


class ShardedDataFrame(DataFrame):
    """One process's shard of a fleet-wide DataFrame.

    Inherited row-wise ops (select/filter/withColumn/transform stages/…)
    run on the local rows — the mapPartitions analog. ``count()`` /
    ``collect()`` are the LOCAL shard (the SPMD contract: code runs
    per-process); use :meth:`globalCount` / :meth:`collectGlobal` for
    fleet-wide views. Relational ops with cross-row semantics (groupBy,
    distinct, join, limit) are overridden with distributed implementations.
    """

    @classmethod
    def fromLocal(cls, df: DataFrame) -> "ShardedDataFrame":
        out = cls({}, npartitions=df.npartitions)
        out._cols = dict(df._cols)
        out._n = df._n
        out._meta = _copy_meta(df._meta)
        return out

    def _derive(self, cols, meta) -> "ShardedDataFrame":
        df = ShardedDataFrame({}, npartitions=self.npartitions)
        df._cols = cols
        df._n = len(next(iter(cols.values()))) if cols else 0
        df._meta = meta
        return df

    def localFrame(self) -> DataFrame:
        """This shard as a plain (non-sharded) DataFrame."""
        df = DataFrame({}, npartitions=self.npartitions)
        df._cols = dict(self._cols)
        df._n = self._n
        df._meta = _copy_meta(self._meta)
        return df

    # ---- fleet-wide views ----
    def globalCount(self) -> int:
        return int(allreduce_sum(np.asarray(self._n, np.int64)))

    def collectGlobal(self) -> list[dict]:
        """All rows from all processes (explicit materialization — the one
        API that deliberately breaks the never-gather-the-data-plane rule,
        like Spark's collect())."""
        return [r for part in allgather_pyobj(self.collect()) for r in part]

    # ---- distributed relational ops ----
    def groupBy(self, *names: str) -> "ShardedGroupedData":
        return ShardedGroupedData(self, list(names))

    def distinct(self) -> DataFrame:
        """Global distinct: local distinct -> allgather -> re-distinct.
        Result is a REPLICATED plain DataFrame (identical on every
        process, in every fleet size — so single-process code can't grow a
        dependency on shardedness that a real fleet would break)."""
        local = super().distinct().localFrame()
        if nprocs() == 1:
            return local
        return _gather_frames(local).distinct()

    def limit(self, n: int) -> "ShardedDataFrame":
        """First ``n`` rows fleet-wide, in process order: process 0
        contributes up to n, process 1 the remainder, etc."""
        if nprocs() == 1:
            return super().limit(n)
        counts = allgather_pyobj(self._n)
        before = sum(counts[:pid()])
        take = max(0, min(self._n, n - before))
        return super().limit(take)

    def sort(self, name: str, ascending: bool = True):
        raise NotImplementedError(
            "global sort on a sharded frame is not supported (it would "
            "require a range shuffle); sort after aggregation — distributed "
            "groupBy/distinct return replicated plain DataFrames that sort "
            "normally — or call .localFrame().sort() for per-shard order")

    def join(self, other: DataFrame, on, how: str = "inner",
             suffix: str = "_right") -> "ShardedDataFrame":
        """Broadcast hash join: ``other`` (the small side — a dimension
        table, an aggregate) is gathered to every process, then each shard
        joins locally; the output stays sharded. For right/outer, right
        rows unmatched by ANY process's shard are emitted once (process 0),
        so global row multiplicity matches the single-frame semantics.

        The reference gets the same shape from Spark broadcast joins; the
        big-big shuffle join has no analog here — repartition by key
        upstream (e.g. at ingest) instead."""
        if nprocs() == 1:
            return ShardedDataFrame.fromLocal(super().join(
                other, on, how=how, suffix=suffix))
        right = (_gather_frames(other) if isinstance(other, ShardedDataFrame)
                 else other)
        keys = [on] if isinstance(on, str) else list(on)
        if how in ("right", "outer"):
            # which right rows does ANY shard match? (global decision)
            lkeys = {t for t in zip(*[[_hashable(v) for v in
                                       self.col(k).tolist()] for k in keys])}
            lkeys = set().union(*allgather_pyobj(lkeys))
            rk = list(zip(*[[_hashable(v) for v in right.col(k).tolist()]
                            for k in keys]))
            # null keys match nothing (SQL join semantics, core join rule)
            matched = np.array([_NULL_SENTINEL not in t and t in lkeys
                                for t in rk], dtype=bool)
            local_how = "left" if how == "outer" else "inner"
            out = super().join(right, on, how=local_how, suffix=suffix)
            if pid() == 0 and (~matched).any():
                extra = self._null_left_join_rows(right, keys, ~matched,
                                                  suffix, out.columns)
                out = out.union(extra)
            return ShardedDataFrame.fromLocal(out)
        out = super().join(right, on, how=how, suffix=suffix)
        return ShardedDataFrame.fromLocal(out)

    def _null_left_join_rows(self, right: DataFrame, keys, mask,
                             suffix: str, out_columns) -> DataFrame:
        """Rows for right-side records no shard matched: key columns from
        the right, every left non-key column null-filled."""
        ridx = np.flatnonzero(mask)
        cols: dict[str, np.ndarray] = {}
        for name in out_columns:
            if name in keys:
                cols[name] = right.col(name)[ridx]
            elif name.endswith(suffix) and name[:-len(suffix)] in right.columns \
                    and name[:-len(suffix)] in self.columns:
                cols[name] = right.col(name[:-len(suffix)])[ridx]
            elif name in right.columns and name not in self.columns:
                cols[name] = right.col(name)[ridx]
            else:  # left-only column: null-fill
                cols[name] = _gather_with_nulls(
                    self.col(name), np.full(len(ridx), -1, np.int64))
        return DataFrame(cols)


#: second-stage merge plan per aggregation fn: how per-process partial
#: aggregates combine into the global value. mean decomposes into sum+count.
_MERGEABLE = {"sum": "sum", "min": "min", "max": "max", "count": "sum",
              "first": "first"}


class ShardedGroupedData:
    """groupBy on a sharded frame: per-process partial aggregation (one
    GroupedData pass over the local shard — the map-side combine), an
    allgather of the small partial tables, and a re-aggregation. Result is
    a REPLICATED plain DataFrame, identical on every process."""

    def __init__(self, df: ShardedDataFrame, keys: list[str]):
        if not keys:
            raise ValueError("groupBy needs at least one key column")
        self._df = df
        self._keys = keys

    def _local(self) -> GroupedData:
        return GroupedData(self._df, self._keys)

    def agg(self, spec: Optional[dict] = None, /, **named) -> DataFrame:
        if nprocs() == 1:
            return self._local().agg(spec, **named)
        items: list[tuple[str, str, str]] = []
        for col, fn in (spec or {}).items():
            items.append((f"{fn}({col})", col, fn))
        for out, (col, fn) in named.items():
            items.append((out, col, fn))
        if not items:
            raise ValueError("agg needs at least one aggregation")
        clash = [out for out, _, _ in items if out in self._keys]
        if clash:  # same contract as the single-frame GroupedData.agg
            raise ValueError(
                f"aggregation output name(s) {clash} collide with group "
                f"key columns; pick different output names")
        # stage 1: local partials. mean -> (sum, count); collect_list stays
        # a list and flattens after the merge.
        partial_spec: dict[str, tuple[str, str]] = {}
        for i, (out, col, fn) in enumerate(items):
            if fn == "mean":
                partial_spec[f"__s{i}"] = (col, "sum")
                partial_spec[f"__c{i}"] = (col, "count")
            elif fn == "collect_list":
                partial_spec[f"__p{i}"] = (col, "collect_list")
            elif fn in _MERGEABLE:
                partial_spec[f"__p{i}"] = (col, fn)
            else:
                raise ValueError(f"unknown aggregation {fn!r}")
        local = self._local().agg(**partial_spec)
        merged = _gather_frames(local)
        g = merged.groupBy(*self._keys)
        # stage 2: merge partials across processes
        merge_spec: dict[str, tuple[str, str]] = {}
        for i, (out, col, fn) in enumerate(items):
            if fn == "mean":
                merge_spec[f"__s{i}"] = (f"__s{i}", "sum")
                merge_spec[f"__c{i}"] = (f"__c{i}", "sum")
            elif fn == "collect_list":
                merge_spec[f"__p{i}"] = (f"__p{i}", "collect_list")
            else:
                merge_spec[f"__p{i}"] = (f"__p{i}", _MERGEABLE[fn])
        out_df = g.agg(**merge_spec)
        cols = {k: out_df.col(k) for k in self._keys}
        for i, (out, col, fn) in enumerate(items):
            if fn == "mean":
                s = out_df.col(f"__s{i}")
                c = out_df.col(f"__c{i}")
                if s.dtype.kind == "O":  # vector cells
                    cols[out] = object_column(
                        [np.asarray(v) / n for v, n in zip(s, c)])
                else:
                    cols[out] = s.astype(np.float64) / c
            elif fn == "collect_list":  # flatten the per-process lists
                cols[out] = object_column(
                    [[x for part in nested for x in part]
                     for nested in out_df.col(f"__p{i}")])
            elif fn == "count":
                cols[out] = out_df.col(f"__p{i}").astype(np.int64)
            else:
                cols[out] = out_df.col(f"__p{i}")
        meta = {k: self._df._meta[k] for k in self._keys
                if k in self._df._meta}
        return DataFrame(cols, metadata=meta)

    def count(self) -> DataFrame:
        if "count" in self._keys:
            raise ValueError("a group key is named 'count'; use "
                             "agg(<name>=(key, 'count')) instead")
        out = self.agg(__n=(self._keys[0], "count"))
        return out.withColumnRenamed("__n", "count")

    def rowGroupIds(self) -> np.ndarray:
        """LOCAL rows' group ids (local numbering — fleet-wide group ids
        would require a key shuffle; local ids are what per-shard
        broadcast-back consumers need)."""
        return self._local().rowGroupIds()

    def _all_numeric(self, fn: str, names) -> DataFrame:
        names = list(names) or [c for c in self._df.columns
                                if c not in self._keys
                                and self._df.col(c).dtype.kind in "biuf"]
        if not names:
            return self.agg(__n=(self._keys[0], "count")).drop("__n")
        return self.agg({c: fn for c in names})

    def sum(self, *names: str) -> DataFrame:
        return self._all_numeric("sum", names)

    def mean(self, *names: str) -> DataFrame:
        return self._all_numeric("mean", names)

    avg = mean

    def min(self, *names: str) -> DataFrame:
        return self._all_numeric("min", names)

    def max(self, *names: str) -> DataFrame:
        return self._all_numeric("max", names)
