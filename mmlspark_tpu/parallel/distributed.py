"""Multi-host distributed backend: JAX coordination-service rendezvous.

Replaces both multi-node rendezvous mechanisms in the reference (SURVEY.md
§2.7): the LightGBM machine-list/port handshake assembled from Spark executor
discovery (reference: lightgbm/.../LightGBMUtils.scala:98-160 feeding
``LGBM_NetworkInit``, TrainUtils.scala:141-142) and the MPI hostfile written
for ssh'd ``mpirun`` (cntk-train/.../CommandBuilders.scala:135-147,241-243).

Here every host process calls ``initialize(...)`` (or ``initialize_from_env``
under a launcher that exports the coordinator address); JAX's coordination
service does the rendezvous over DCN, after which ``jax.devices()`` spans the
whole pod/slice and a single global ``Mesh`` drives ICI/DCN collectives — no
ssh, no hostfiles, no socket rings.

Single-process (local[*]-style) use needs no initialize call at all — the
same code paths run on the local devices, the analog of the reference's
partitions-as-workers local mode (SURVEY.md §4).

Failure model: a worker missing at rendezvous fails the fleet inside
MMLTPU_INIT_TIMEOUT (default 120 s, LightGBM's bound); a worker dying
BETWEEN collectives is caught by coordination-service heartbeats
(MMLTPU_HEARTBEAT_TIMEOUT) — the survivors terminate with an error inside
the bound instead of hanging in the next collective. Recovery = relaunch
the fleet and refit with the same checkpointDir: TpuLearner resumes from
the last complete epoch (the crash→relaunch→resume path has a real
two-process test in tests/test_parallel_depth.py).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Optional, Sequence

import jax

from .. import telemetry
from ..core.utils import get_logger
from . import mesh as meshlib

log = get_logger("distributed")

_m_generation = telemetry.registry.gauge(
    "mmlspark_rendezvous_generation",
    "the jax.distributed incarnation this process is currently joined "
    "to (bumped by every elastic re-rendezvous; 0 = never rendezvoused)")
_m_rendezvous = telemetry.registry.counter(
    "mmlspark_rendezvous_total",
    "re-rendezvous joins completed (coordinator-service restart + "
    "barrier re-entry into a new generation)")
_m_lease_term = telemetry.registry.gauge(
    "mmlspark_lease_term",
    "the leader-lease term this process last observed (bumped by every "
    "takeover; 0 = no lease yet)")
_m_lease_renewals = telemetry.registry.counter(
    "mmlspark_lease_renewals",
    "leader-lease renewals written by this process as the holder")
_m_lease_takeovers = telemetry.registry.counter(
    "mmlspark_lease_takeovers",
    "leader-lease acquisitions (fresh grants and expired-lease "
    "takeovers by the lowest-rank fresh host)")

# launcher-agnostic env contract (set by the Spark-executor / TPU-VM launcher)
ENV_COORDINATOR = "MMLTPU_COORDINATOR"       # "host:port" of process 0
ENV_NUM_PROCESSES = "MMLTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "MMLTPU_PROCESS_ID"
ENV_INIT_TIMEOUT = "MMLTPU_INIT_TIMEOUT"     # seconds to wait at rendezvous
ENV_HEARTBEAT_TIMEOUT = "MMLTPU_HEARTBEAT_TIMEOUT"  # dead-worker detection

# the reference's LightGBM rendezvous blocks at most 120 s
# (LightGBMConstants.scala:9-12 defaultListenTimeout); same bound here so a
# missing worker fails the job instead of hanging the fleet
DEFAULT_INIT_TIMEOUT = 120

_initialized = False


def _enable_cpu_collectives() -> None:
    """Multi-process CPU fleets need a real cross-process collective
    implementation: the plain CPU client raises "Multiprocess computations
    aren't implemented on the CPU backend" at the first allgather. jaxlib
    ships gloo TCP collectives behind a config knob — select them whenever
    the job will run on the CPU platform (the multiproc-CPU smoke, local
    fleet rehearsal, CI). Must run BEFORE the backend client is created;
    initialize() is the single choke point every launcher goes through.
    On TPU/GPU jobs the knob is irrelevant and skipped."""
    # platform must be decided WITHOUT touching jax.devices(): instantiating
    # the backend here would bake the collectives choice in before the knob
    # lands. The config value covers jax.config.update("jax_platforms",...)
    # callers (tests, the smoke workers); the env vars cover launchers.
    platforms = (getattr(jax.config, "jax_platforms", None)
                 or os.environ.get("JAX_PLATFORMS", "")
                 or os.environ.get("JAX_PLATFORM_NAME", "")).lower()
    if platforms != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:   # older jaxlib without the knob: leave as-was
        pass


def is_initialized() -> bool:
    return _initialized


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               init_timeout: Optional[int] = None,
               heartbeat_timeout: Optional[int] = None) -> None:
    """Join the global JAX runtime. Process 0's address is the rendezvous
    point (the machine-list/hostfile role); blocks until all processes check
    in, like LGBM_NetworkInit's 120s barrier — but heartbeated and reusable
    across every collective rather than per-training-job. A worker that
    never shows up fails the rendezvous after ``init_timeout`` (default
    120 s, the reference's bound); a worker that dies later is detected by
    missed heartbeats and takes the job down rather than hanging it."""
    global _initialized
    if _initialized:
        log.info("distributed runtime already initialized; skipping")
        return
    if init_timeout is None:
        init_timeout = int(os.environ.get(ENV_INIT_TIMEOUT,
                                          DEFAULT_INIT_TIMEOUT))
    kwargs = {}
    if heartbeat_timeout is None and ENV_HEARTBEAT_TIMEOUT in os.environ:
        heartbeat_timeout = int(os.environ[ENV_HEARTBEAT_TIMEOUT])
    if heartbeat_timeout is not None:
        kwargs["heartbeat_timeout_seconds"] = heartbeat_timeout
    _enable_cpu_collectives()
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids,
                               initialization_timeout=init_timeout,
                               **kwargs)
    _initialized = True
    log.info("distributed init: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def initialize_from_env() -> bool:
    """Initialize from the MMLTPU_* env contract when present (the launcher
    writes it per executor the way the reference's driver writes
    hostfile.txt). Returns True when distributed init ran; False means
    single-process mode — both are valid, same downstream code."""
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return False
    configure_xla_cache()
    initialize(coordinator_address=addr,
               num_processes=int(os.environ[ENV_NUM_PROCESSES]),
               process_id=int(os.environ[ENV_PROCESS_ID]))
    return True


_cache_configured = False


def configure_xla_cache() -> None:
    """Enable the persistent XLA compilation cache (HLO-hash keyed, so
    never stale). Fleet workers, CI runs AND first single-process fits
    recompile the same programs on every launch; the cache turns that into
    a disk read — worth minutes on small hosts (42 s of a cold 1M-row GBDT
    fit was recompile of cacheable programs, VERDICT round 4 weak #5).
    Called on entry to fit_gbdt and TpuLearner.fit as well as by the
    distributed init and tests/conftest.py. MMLTPU_XLA_CACHE="" opts out;
    this is the single source of the dir/threshold policy."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    cache = os.environ.get("MMLTPU_XLA_CACHE", "/tmp/mmlspark_tpu_xla_cache")
    if not cache:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    except Exception:  # cache is an optimization, never a requirement
        pass


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


# ---- elastic re-rendezvous -------------------------------------------------
#
# The fail-fast model above is right for fixed fleets: a dead peer takes
# the job down inside the heartbeat bound and the launcher relaunches at
# full size. Elastic fleets want the JAMPI barrier-re-entry shape instead
# (PAPERS.md arxiv 2007.01811): the survivors tear the coordination
# service down, restart it on the surviving lowest-rank host, and every
# member re-enters the rendezvous barrier under a NEW generation — so a
# kill -9'd process can relaunch and join the *same running fit*, and a
# straggler can be evicted without losing the fleet.
#
# The generation is carried by an atomically-renamed ``rendezvous.json``
# on the job's shared checkpoint storage (the same trust anchor the
# consensus checkpoints use): {generation, address, ranks}. Only the
# leader (lowest-rank surviving host) writes it; everyone else polls.
# A process may only ever JOIN a generation strictly newer than the one
# it last held AND that names it in ``ranks`` — a stale-generation
# process can therefore never join the wrong incarnation; it parks in
# the joining-heartbeat path until a future generation includes it.
#
# Teardown deliberately does NOT call client.shutdown(): with a dead
# peer the coordination-service shutdown barrier aborts the process
# (client.h LogFatal). Instead the dead generation's client/service are
# LEAKED (bounded by the number of re-rendezvous events), the cached XLA
# backends are dropped, and the new generation's client is built with a
# benign missed-heartbeat callback + shutdown_on_destruction=False so
# neither the leak nor a later peer death can terminate the process —
# the elastic runtime's own heartbeat verdicts are the failure signal.

RENDEZVOUS_DOC = "rendezvous.json"

ENV_HOST_ADDRESS = "MMLTPU_HOST_ADDRESS"     # advertised rendezvous addr
ENV_REJOIN_TIMEOUT = "MMLTPU_REJOIN_TIMEOUT"  # seconds to wait for a
DEFAULT_REJOIN_TIMEOUT = 120.0                # generation that names us

_leaked_incarnations: list = []   # dead generations' client/service pairs
_rdzv_coordinator: Optional["RendezvousCoordinator"] = None


class RendezvousError(RuntimeError):
    """A re-rendezvous attempt failed (proposal raced, barrier timed
    out, init refused). Retried with backoff by the caller; exhaustion
    falls back to relaunch-at-full-size (ElasticFleetLost)."""


def rendezvous_coordinator() -> Optional["RendezvousCoordinator"]:
    """The process-wide rendezvous coordinator, armed by
    :func:`elastic_initialize` (None = fixed-fleet mode: a member loss
    fails fast and the launcher relaunches)."""
    return _rdzv_coordinator


LEASE_DOC = "lease.json"
ENV_LEASE_TIMEOUT = "MMLTPU_LEASE_TIMEOUT"
DEFAULT_LEASE_TIMEOUT = 5.0


class LeaderLease:
    """A renewable leader lease over one shared-storage file.

    PR 10's rendezvous made the *generation* race-free but left the
    *proposer election* racy: "lowest-rank survivor proposes" is a rule
    each host evaluates from its own heartbeat view, and two hosts with
    briefly divergent views could both propose — bounded only by
    last-write-wins on the doc rename. The lease serializes proposals
    the way production control planes do:

    * ``lease.json`` carries ``{holder, term, seq, time}``. The holder
      renews it (``seq`` + 1, same ``term``) while it leads; every
      renewal is an atomic rename, so readers never see a torn doc.
    * Freshness is judged like PR 10's heartbeats: a reader tracks when
      the ``(term, seq)`` pair last *advanced on its own monotonic
      clock* — a skewed writer wall clock can neither fake freshness
      nor fake expiry. A lease that has not advanced for
      ``timeout`` seconds (``MMLTPU_LEASE_TIMEOUT``, default 5) is
      **expired**.
    * An expired (or absent) lease is taken over with ``term + 1`` by
      the lowest-rank fresh host (:meth:`RendezvousCoordinator.propose`
      enforces *who*); the takeover re-reads the file after its rename,
      so two racing takeovers resolve deterministically — exactly one
      proceeds, the loser raises and re-enters election as a follower.
    * A **stale leader can never publish**: its term is behind the
      file's, so :meth:`renew` refuses, ``propose`` re-validates the
      lease after the doc rename (a void proposal raises instead of
      standing), and followers refuse docs stamped with an old
      ``lease_term`` — the late-proposal race PR 10 bounded with
      retries is now refused by generation.
    """

    def __init__(self, directory: str, host_id: str,
                 timeout: Optional[float] = None):
        self.directory = directory
        self.host_id = host_id
        if timeout is None:
            timeout = float(os.environ.get(ENV_LEASE_TIMEOUT,
                                           DEFAULT_LEASE_TIMEOUT))
        self.timeout = float(timeout)
        #: the term THIS process last acquired (0 = never held). A
        #: relaunched process starts at 0 and must re-acquire — its old
        #: incarnation's file term is someone it can no longer speak for.
        self.term = 0
        self._seen: tuple[int, int] = (0, 0)   # last observed (term, seq)
        self._seen_at = time.monotonic()       # reader clock at last advance
        self._last_renewal = 0.0
        self._cache: tuple[float, Optional[dict]] = (0.0, None)

    @property
    def path(self) -> str:
        return os.path.join(self.directory, LEASE_DOC)

    def read(self) -> Optional[dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc.get("term"), int):
                return None
            return doc
        except (OSError, ValueError):
            return None

    def observe(self, max_age: float = 0.0) -> Optional[dict]:
        """Read the lease and advance the reader-side freshness clock
        whenever ``(term, seq)`` moved. Chaos site ``distributed.lease``
        covers every lease-file round-trip. ``max_age`` > 0 reuses the
        last read within that window (per-committed-step election must
        not turn into a per-step shared-FS read)."""
        if max_age > 0:
            at, doc = self._cache
            if time.monotonic() - at < max_age:
                return doc
        from ..resilience import faults
        faults.inject("distributed.lease")
        doc = self.read()
        self._cache = (time.monotonic(), doc)
        if doc is not None:
            key = (int(doc.get("term", 0)), int(doc.get("seq", 0)))
            if key != self._seen:
                self._seen = key
                self._seen_at = time.monotonic()
            _m_lease_term.set(key[0])
        return doc

    def expired(self, max_age: float = 0.0) -> bool:
        """True when the lease is absent, or its ``(term, seq)`` has not
        advanced for ``timeout`` seconds of THIS reader's monotonic
        clock. A reader that just started watching a stale file still
        waits out one full window — lease semantics require observing
        the silence, not just old metadata."""
        if self.observe(max_age=max_age) is None:
            return True
        return time.monotonic() - self._seen_at >= self.timeout

    def held(self) -> bool:
        """True while the file names this process as holder at the term
        it acquired (a relaunched process, term 0, never holds)."""
        doc = self.read()
        return (self.term > 0 and doc is not None
                and doc.get("holder") == self.host_id
                and int(doc.get("term", 0)) == self.term)

    def _write(self, term: int, seq: int):
        os.makedirs(self.directory, exist_ok=True)
        doc = {"holder": self.host_id, "term": term, "seq": seq,
               "time": time.time()}
        # unique tmp per process: racing takeovers must not clobber each
        # other's tmp files. No fsync before the rename ON PURPOSE (the
        # heartbeat posture): a lease needs READ atomicity, not crash
        # durability — a leader that crashes SHOULD lose its lease, and
        # an fsync per renewal would hammer the shared filesystem.
        tmp = f"{self.path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        # graftlint: disable=protocol-rename-before-fsync
        os.replace(tmp, self.path)
        self._seen = (term, seq)
        self._seen_at = time.monotonic()
        self._cache = (self._seen_at, doc)

    def renew(self):
        """Holder-side keep-alive: bump ``seq`` at the held term. Raises
        :class:`RendezvousError` when the lease moved on (takeover) —
        the caller has been deposed and must re-enter election."""
        from ..resilience import faults
        faults.inject("distributed.lease")
        doc = self.read()
        if (doc is None or doc.get("holder") != self.host_id
                or int(doc.get("term", 0)) != self.term or self.term == 0):
            raise RendezvousError(
                f"{self.host_id} lost the leader lease (now held by "
                f"{(doc or {}).get('holder')!r} at term "
                f"{(doc or {}).get('term')})")
        self._write(self.term, int(doc.get("seq", 0)) + 1)
        self._last_renewal = time.monotonic()
        _m_lease_renewals.inc()

    def maybe_renew(self):
        """Opportunistic holder keep-alive, throttled to a third of the
        timeout (callers can invoke it per committed step for free)."""
        if self.term == 0:
            return
        if time.monotonic() - self._last_renewal < self.timeout / 3.0:
            return
        try:
            self.renew()
        except RendezvousError:
            self.term = 0      # deposed: stop renewing a lost lease

    def acquire(self) -> dict:
        """Take (over) the lease at ``term + 1``. Refused while another
        holder is fresh; a write race is resolved by the post-rename
        re-read — exactly one contender's doc stands."""
        from ..resilience import faults
        faults.inject("distributed.lease")
        doc = self.observe()
        if (doc is not None and doc.get("holder") != self.host_id
                and not self.expired()):
            raise RendezvousError(
                f"leader lease is held fresh by {doc['holder']!r} (term "
                f"{doc['term']}); {self.host_id} must not take over")
        new_term = (int(doc.get("term", 0)) if doc else 0) + 1
        self._write(new_term, 1)
        cur = self.read()
        if (cur is None or cur.get("holder") != self.host_id
                or int(cur.get("term", 0)) != new_term):
            raise RendezvousError(
                f"lease takeover raced: {self.host_id} wrote term "
                f"{new_term} but the file now holds "
                f"{(cur or {}).get('holder')!r} at term "
                f"{(cur or {}).get('term')}")
        self.term = new_term
        self._last_renewal = time.monotonic()
        _m_lease_takeovers.inc()
        _m_lease_term.set(new_term)
        telemetry.trace.instant("lease/takeover", holder=self.host_id,
                                term=new_term)
        telemetry.flight.note("lease/takeover", holder=self.host_id,
                              term=new_term)
        log.warning("leader lease acquired by %s at term %d",
                    self.host_id, new_term)
        return cur


def _advertised_address() -> str:
    """The address peers can reach THIS host on (the new coordinator
    service binds here after a leader takeover)."""
    addr = os.environ.get(ENV_HOST_ADDRESS)
    if addr:
        return addr
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _init_elastic_client(address: str, num_processes: int, process_id: int,
                         init_timeout: int):
    """Stand up one generation's coordination service (leader) + client,
    with the survivable failure posture: the ELASTIC runtime's own file
    heartbeats are the failure detector, so the coordination service's
    redundant one is configured effectively inert (a peer death must
    never let the service flag an error back into surviving clients —
    the default client reaction to a polled error is process
    termination), and the client is built with shutdown_on_destruction
    off plus a log-only missed-heartbeat callback as the last line of
    defense."""
    from jax._src import distributed as dist_internal
    from jax._src.lib import xla_extension as xe
    st = dist_internal.global_state
    if st.client is not None:
        raise RendezvousError("previous incarnation still attached; "
                              "teardown_for_rendezvous() first")
    # ~11 days of missed heartbeats before the redundant detector acts
    hb_interval, hb_tolerance = 10, 100_000
    if process_id == 0:
        port = address.rsplit(":", 1)[1]
        st.service = xe.get_distributed_runtime_service(
            f"[::]:{port}", num_processes,
            heartbeat_interval=hb_interval,
            max_missing_heartbeats=hb_tolerance)

    def _on_peer_trouble(*status):
        log.warning("coordination-service error (peer died or network "
                    "trouble); elastic heartbeat verdicts drive the "
                    "recovery: %s", status)

    st.client = xe.get_distributed_runtime_client(
        address, process_id, init_timeout=init_timeout,
        shutdown_timeout=10,
        heartbeat_interval=hb_interval,
        max_missing_heartbeats=hb_tolerance,
        missed_heartbeat_callback=_on_peer_trouble,
        shutdown_on_destruction=False, use_compression=True)
    st.client.connect()
    st.process_id = process_id
    st.num_processes = num_processes
    st.coordinator_address = address
    _register_exit_detach()
    global _initialized
    _initialized = True


_exit_detach_registered = False


def _register_exit_detach():
    """jax registers an atexit ``clean_up`` that runs the coordination
    shutdown BARRIER — against an elastic fleet whose members exit at
    different times (a peer may be long dead) that barrier hangs or
    aborts. Our handler registers LATER, so it runs FIRST (atexit is
    LIFO): when the fleet looks healthy (every current-generation
    peer's heartbeat file is fresh — everyone is exiting through the
    same barrier), shut down gracefully inside the 10 s bound; when a
    peer is dead, DETACH instead — an abrupt disconnect must never let
    the coordination service flag an error back into this (or another)
    exiting process, because the error-poll callback crossing into
    Python during interpreter teardown aborts the process."""
    global _exit_detach_registered
    if _exit_detach_registered:
        return
    _exit_detach_registered = True
    import atexit
    from jax._src import distributed as dist_internal

    def _detach():
        st = dist_internal.global_state
        client, service = st.client, st.service
        st.client = None
        st.service = None
        st.preemption_sync_manager = None
        if client is None:
            return
        rdzv = _rdzv_coordinator
        healthy = True
        if rdzv is not None and rdzv.ranks:
            now = time.time()
            for h in rdzv.ranks:
                if h == rdzv.host_id:
                    continue
                try:
                    fresh = now - os.path.getmtime(os.path.join(
                        rdzv.directory, f"hb_{h}.json")) <= 10.0
                except OSError:
                    fresh = False
                if not fresh:
                    healthy = False
                    break
        if healthy:
            try:
                client.shutdown()
                if service is not None:
                    service.shutdown()
                return
            except Exception:
                pass
        _leaked_incarnations.append((client, service))

    atexit.register(_detach)


def teardown_for_rendezvous() -> None:
    """Detach from the current (dead) incarnation WITHOUT the shutdown
    barrier, and drop the cached XLA backends so the next collective
    program instantiates against the new generation's KV store. The old
    client/service objects are leaked on purpose — destroying them runs
    the fatal shutdown path."""
    from jax._src import distributed as dist_internal
    from jax._src import xla_bridge
    st = dist_internal.global_state
    _leaked_incarnations.append((st.client, st.service))
    st.client = None
    st.service = None
    st.preemption_sync_manager = None
    st.coordinator_address = None
    st.process_id = 0
    st.num_processes = 1
    xla_bridge._clear_backends()
    jax.clear_caches()
    global _initialized
    _initialized = False


class RendezvousCoordinator:
    """Generation-stamped membership + barrier re-entry for one elastic
    job (one instance per process; ``host_id`` is the process's STABLE
    identity — its launch rank — which survives re-ranking across
    generations)."""

    def __init__(self, directory: str, host_id: str,
                 init_timeout: Optional[int] = None,
                 lease_timeout: Optional[float] = None):
        self.directory = directory
        self.host_id = host_id
        self.generation = 0
        self.ranks: dict[str, int] = {}
        #: proposals are serialized by a leader lease — see LeaderLease
        self.lease = LeaderLease(directory, host_id,
                                 timeout=lease_timeout)
        #: the PROCESS-LEVEL heartbeat beacon (started by
        #: elastic_initialize, reused by the fit coordinator): the host
        #: must never go silent between joining a generation and the fit
        #: loop taking over, or peers re-issue a death verdict into the
        #: gap
        self.heartbeat = None
        self.init_timeout = (init_timeout if init_timeout is not None
                             else int(os.environ.get(
                                 ENV_INIT_TIMEOUT, DEFAULT_INIT_TIMEOUT)))

    @property
    def path(self) -> str:
        return os.path.join(self.directory, RENDEZVOUS_DOC)

    def read(self) -> Optional[dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc.get("generation"), int):
                return None
            return doc
        except (OSError, ValueError):
            return None

    def elect_leader(self, members, max_age: float = 0.05) -> str:
        """Lease-aware leader election over ``members``: the fresh lease
        holder when it is a member, else the lowest-rank member (who
        will take over the expired/absent lease at propose time)."""
        members = sorted(members)
        doc = self.lease.observe(max_age=max_age)
        if doc is not None and not self.lease.expired(max_age=max_age):
            holder = doc.get("holder")
            if holder in members:
                return holder
        return members[0] if members else self.host_id

    def propose(self, hosts, unwind_at: Optional[tuple] = None) -> dict:
        """Leader-side: mint the next generation over ``hosts`` (ranks
        assigned in sorted host order, so the lowest surviving host is
        rank 0 and carries the restarted coordinator service) and commit
        the doc atomically. ``unwind_at`` tells still-stepping members
        the (epoch, step) after which they must unwind and join —
        the deterministic grow/evict boundary.

        Proposals are serialized by the leader lease: the fresh holder
        renews and proposes; an absent/expired lease is taken over by
        the lowest-rank host of the proposal set; anyone else is
        refused. After the doc rename the lease is re-validated — a
        leader deposed mid-proposal raises instead of publishing, and a
        fresh leader whose doc was overwritten by a stale straggler
        rewrites it (the straggler cannot renew, so this converges)."""
        from ..resilience import faults
        faults.inject("distributed.rendezvous")
        hosts = sorted(set(hosts))
        if self.lease.held():
            self.lease.renew()
        else:
            lease_doc = self.lease.observe()
            if (lease_doc is not None
                    and lease_doc.get("holder") != self.host_id
                    and not self.lease.expired()):
                raise RendezvousError(
                    f"{self.host_id} proposed a generation but "
                    f"{lease_doc['holder']!r} holds a fresh leader lease "
                    f"(term {lease_doc['term']})")
            if self.host_id != hosts[0]:
                raise RendezvousError(
                    f"{self.host_id} proposed a generation but {hosts[0]} "
                    f"is the surviving leader (lowest-rank fresh host "
                    f"takes the expired lease)")
            self.lease.acquire()
        cur = self.read()
        gen = max(self.generation,
                  cur["generation"] if cur else 0) + 1
        doc = {"generation": gen,
               "address": f"{_advertised_address()}:{_free_port()}",
               "ranks": {h: i for i, h in enumerate(hosts)},
               "num_processes": len(hosts),
               "lease_term": self.lease.term,
               "time": time.time()}
        if unwind_at is not None:
            doc["unwind_at"] = list(unwind_at)
        os.makedirs(self.directory, exist_ok=True)
        for _attempt in range(8):
            # same commit discipline as checkpoints (fsync BEFORE the
            # atomic rename — lint-enforced by
            # protocol-rename-before-fsync): a torn rendezvous doc would
            # strand relaunched processes on a generation that never
            # existed
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            if not self.lease.held():
                raise RendezvousError(
                    f"{self.host_id} lost the leader lease during the "
                    f"proposal; generation {gen} is void (refused by "
                    f"generation at every follower)")
            stood = self.read()
            if (stood is not None
                    and stood.get("generation") == gen
                    and stood.get("address") == doc["address"]
                    and stood.get("lease_term") == self.lease.term):
                break
            log.warning("rendezvous doc overwritten by a stale proposal; "
                        "leaseholder %s rewrites generation %d",
                        self.host_id, gen)
        else:
            raise RendezvousError(
                f"rendezvous doc for generation {gen} would not stand "
                f"after 8 rewrites")
        log.warning("rendezvous generation %d proposed: %d host(s) %s at "
                    "%s (lease term %d)", gen, len(hosts), hosts,
                    doc["address"], self.lease.term)
        return doc

    def await_membership(self, min_generation: int,
                         timeout: Optional[float] = None) -> dict:
        """Follower-side: poll the doc until a generation >=
        ``min_generation`` names this host. A doc that omits us (we were
        evicted, or the leader hasn't seen our joining heartbeat yet)
        keeps us parked — the stale-generation guard."""
        from ..resilience import faults
        faults.inject("distributed.rendezvous")
        if timeout is None:
            timeout = float(os.environ.get(ENV_REJOIN_TIMEOUT,
                                           DEFAULT_REJOIN_TIMEOUT))
        deadline = time.monotonic() + timeout
        while True:
            doc = self.read()
            if doc is not None and "lease_term" in doc:
                # a stale leader's LATE proposal: stamped with a lease
                # term the fleet has moved past — refused by generation
                # (the fresh leaseholder rewrites the doc; keep polling)
                lease_doc = self.lease.read()
                if (lease_doc is not None
                        and int(doc["lease_term"])
                        < int(lease_doc.get("term", 0))):
                    doc = None
            if (doc and doc["generation"] >= min_generation
                    and self.host_id in doc.get("ranks", {})):
                return doc
            if time.monotonic() >= deadline:
                raise RendezvousError(
                    f"no rendezvous generation >= {min_generation} named "
                    f"{self.host_id} within {timeout:.0f}s")
            time.sleep(0.05)

    def join(self, doc: dict) -> None:
        """Tear down the old incarnation and enter ``doc``'s: restart /
        connect the coordination service, then barrier re-entry so every
        member is known present before the fit re-enters. Refuses a doc
        whose generation is not strictly newer than the one this process
        last held."""
        gen = int(doc["generation"])
        if gen <= self.generation:
            raise RendezvousError(
                f"stale generation {gen} (this process already held "
                f"{self.generation}) — refusing to join an old "
                f"incarnation")
        rank = doc["ranks"].get(self.host_id)
        if rank is None:
            raise RendezvousError(
                f"generation {gen} does not include {self.host_id}")
        with telemetry.trace.span("distributed/rendezvous",
                                  generation=gen, rank=rank,
                                  hosts=len(doc["ranks"])):
            # previous incarnation attached? detach WITHOUT touching
            # jax.devices()/process_count() — those would instantiate a
            # backend before the new generation's client exists
            from jax._src import distributed as dist_internal
            if dist_internal.global_state.client is not None:
                teardown_for_rendezvous()
            _enable_cpu_collectives()
            _init_elastic_client(doc["address"], int(doc["num_processes"]),
                                 int(rank), self.init_timeout)
            # barrier re-entry: every member of the new generation checks
            # in before anyone dispatches a collective
            dist_internal.global_state.client.wait_at_barrier(
                f"mmlspark-rdzv-{gen}", int(self.init_timeout * 1000))
        self.generation = gen
        self.ranks = dict(doc["ranks"])
        _m_generation.set(gen)
        _m_rendezvous.inc()
        telemetry.flight.note("distributed/rendezvous", generation=gen,
                              rank=rank, hosts=len(doc["ranks"]))
        log.warning("joined rendezvous generation %d as rank %d/%d "
                    "(%d local / %d global devices)", gen, rank,
                    int(doc["num_processes"]), jax.local_device_count(),
                    jax.device_count())


def _incarnation_live(directory: str, doc: dict, self_host: str,
                      window: float = 10.0) -> bool:
    """Is the doc's incarnation still running? True when any OTHER
    member's heartbeat file was modified within ``window`` seconds
    (reader-side FS mtime — no writer wall-clock trust). A ``joining``
    heartbeat does NOT count: it is a parked waiter, not a running
    member — two relaunched processes must not each mistake the other
    for a live fit and park forever."""
    now = time.time()
    for host in doc.get("ranks", {}):
        if host == self_host:
            continue
        path = os.path.join(directory, f"hb_{host}.json")
        try:
            mtime = os.path.getmtime(path)
            with open(path, "r", encoding="utf-8") as f:
                member_doc = json.load(f)
        except (OSError, ValueError):
            continue
        if now - mtime <= window and not member_doc.get("joining"):
            return True
    return False


def elastic_initialize(checkpoint_dir: str,
                       host_id: Optional[str] = None,
                       rejoin_timeout: Optional[float] = None) -> bool:
    """Elastic-fleet entry point: join (or REJOIN) the job's current
    incarnation through the shared-storage rendezvous protocol instead
    of the fixed-fleet env contract. Every launch and relaunch calls
    this; the three cases resolve themselves:

    * **fresh job** (no rendezvous doc): the env-contract leader
      (process 0) proposes generation 1 over the launch fleet; everyone
      joins it. Falls back to single-process mode (returns False) when
      the env contract is absent.
    * **rejoin** (doc present, incarnation live, we're not in it): this
      is a relaunched/evicted host. Write a ``joining`` heartbeat and
      park until the running fit's leader admits us into a future
      generation at a checkpoint boundary, then join it.
    * **full relaunch** (doc present, incarnation dead): the launcher
      restarted the whole fleet; process 0 proposes generation N+1 over
      the launch fleet and consensus-resume carries the run over.

    Returns True when a distributed incarnation was joined."""
    global _rdzv_coordinator
    addr = os.environ.get(ENV_COORDINATOR)
    n_env = int(os.environ.get(ENV_NUM_PROCESSES, "0") or 0)
    pid_env = int(os.environ.get(ENV_PROCESS_ID, "0") or 0)
    if host_id is None:
        host_id = meshlib.stable_host_id()
    from ..resilience.elastic import heartbeat_dir
    hb_dir = heartbeat_dir(checkpoint_dir)
    os.makedirs(hb_dir, exist_ok=True)
    configure_xla_cache()
    rdzv = RendezvousCoordinator(hb_dir, host_id)
    from ..resilience.elastic import (HostHeartbeat, _hb_interval_default,
                                      _grace_default)
    hb = HostHeartbeat(host_id, hb_dir,
                       _hb_interval_default(_grace_default()))
    doc = rdzv.read()
    launch_hosts = [f"host{i}" for i in range(n_env)]
    if doc is None:
        if not addr or n_env <= 1:
            return False                    # single-process mode
        if pid_env == 0:
            doc = rdzv.propose(launch_hosts)
        else:
            doc = rdzv.await_membership(1, timeout=rejoin_timeout)
        hb.start()
        rdzv.join(doc)
    elif _incarnation_live(hb_dir, doc, host_id):
        # REJOIN a running fit: park behind a joining heartbeat until a
        # generation names us (the grow path's checkpoint boundary).
        # Even when the live doc still names this host (killed and
        # relaunched before the leader noticed), the OLD incarnation's
        # connections are gone — only a fresh generation is joinable;
        # the joining flag self-reports the restart so the leader's
        # death pass drops the old membership promptly.
        hb.set_joining(True)
        hb.start()
        log.warning("rendezvous doc generation %d is live; %s parks "
                    "with a joining heartbeat until readmitted",
                    doc["generation"], host_id)
        target = rdzv.await_membership(doc["generation"] + 1,
                                       timeout=rejoin_timeout)
        rdzv.join(target)
        hb.set_joining(False)
    else:
        # dead incarnation: full-fleet relaunch over the env contract
        if not addr or n_env <= 1:
            return False
        if pid_env == 0:
            doc = rdzv.propose(launch_hosts)
        else:
            doc = rdzv.await_membership(doc["generation"] + 1,
                                        timeout=rejoin_timeout)
        hb.start()
        rdzv.join(doc)
    # the beacon OUTLIVES this call (the fit coordinator reuses it):
    # between generations and fits the host must keep proving liveness
    hb.set_generation(rdzv.generation)
    rdzv.heartbeat = hb
    _rdzv_coordinator = rdzv
    return True


def global_mesh(axes: Optional[dict[str, int]] = None) -> "jax.sharding.Mesh":
    """A mesh over ALL processes' devices. Default: 1-D ``data`` axis over
    every chip in the job (pure DP, the reference's only strategy); pass
    ``axes`` for dp x tp x sp x ep layouts. Put ``data`` outermost so DP
    gradient all-reduce crosses DCN once per step while tp/sp/ep ride ICI."""
    if axes is None:
        axes = {"data": jax.device_count()}
    return meshlib.make_mesh(axes, devices=jax.devices())


def process_barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (the role of the
    reference's blocking NetworkInit rendezvous) — a psum of 1 over a 1-D
    global mesh forces a cross-host collective."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    ones = jax.device_put(
        jnp.ones((jax.device_count(),), jnp.int32),
        NamedSharding(mesh, P("data")))

    @jax.jit
    def _sum(x):
        return x.sum()

    total = int(_sum(ones))
    assert total == jax.device_count(), (name, total)
