"""Multi-host distributed backend: JAX coordination-service rendezvous.

Replaces both multi-node rendezvous mechanisms in the reference (SURVEY.md
§2.7): the LightGBM machine-list/port handshake assembled from Spark executor
discovery (reference: lightgbm/.../LightGBMUtils.scala:98-160 feeding
``LGBM_NetworkInit``, TrainUtils.scala:141-142) and the MPI hostfile written
for ssh'd ``mpirun`` (cntk-train/.../CommandBuilders.scala:135-147,241-243).

Here every host process calls ``initialize(...)`` (or ``initialize_from_env``
under a launcher that exports the coordinator address); JAX's coordination
service does the rendezvous over DCN, after which ``jax.devices()`` spans the
whole pod/slice and a single global ``Mesh`` drives ICI/DCN collectives — no
ssh, no hostfiles, no socket rings.

Single-process (local[*]-style) use needs no initialize call at all — the
same code paths run on the local devices, the analog of the reference's
partitions-as-workers local mode (SURVEY.md §4).

Failure model: a worker missing at rendezvous fails the fleet inside
MMLTPU_INIT_TIMEOUT (default 120 s, LightGBM's bound); a worker dying
BETWEEN collectives is caught by coordination-service heartbeats
(MMLTPU_HEARTBEAT_TIMEOUT) — the survivors terminate with an error inside
the bound instead of hanging in the next collective. Recovery = relaunch
the fleet and refit with the same checkpointDir: TpuLearner resumes from
the last complete epoch (the crash→relaunch→resume path has a real
two-process test in tests/test_parallel_depth.py).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax

from ..core.utils import get_logger
from . import mesh as meshlib

log = get_logger("distributed")

# launcher-agnostic env contract (set by the Spark-executor / TPU-VM launcher)
ENV_COORDINATOR = "MMLTPU_COORDINATOR"       # "host:port" of process 0
ENV_NUM_PROCESSES = "MMLTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "MMLTPU_PROCESS_ID"
ENV_INIT_TIMEOUT = "MMLTPU_INIT_TIMEOUT"     # seconds to wait at rendezvous
ENV_HEARTBEAT_TIMEOUT = "MMLTPU_HEARTBEAT_TIMEOUT"  # dead-worker detection

# the reference's LightGBM rendezvous blocks at most 120 s
# (LightGBMConstants.scala:9-12 defaultListenTimeout); same bound here so a
# missing worker fails the job instead of hanging the fleet
DEFAULT_INIT_TIMEOUT = 120

_initialized = False


def _enable_cpu_collectives() -> None:
    """Multi-process CPU fleets need a real cross-process collective
    implementation: the plain CPU client raises "Multiprocess computations
    aren't implemented on the CPU backend" at the first allgather. jaxlib
    ships gloo TCP collectives behind a config knob — select them whenever
    the job will run on the CPU platform (the multiproc-CPU smoke, local
    fleet rehearsal, CI). Must run BEFORE the backend client is created;
    initialize() is the single choke point every launcher goes through.
    On TPU/GPU jobs the knob is irrelevant and skipped."""
    # platform must be decided WITHOUT touching jax.devices(): instantiating
    # the backend here would bake the collectives choice in before the knob
    # lands. The config value covers jax.config.update("jax_platforms",...)
    # callers (tests, the smoke workers); the env vars cover launchers.
    platforms = (getattr(jax.config, "jax_platforms", None)
                 or os.environ.get("JAX_PLATFORMS", "")
                 or os.environ.get("JAX_PLATFORM_NAME", "")).lower()
    if platforms != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:   # older jaxlib without the knob: leave as-was
        pass


def is_initialized() -> bool:
    return _initialized


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               init_timeout: Optional[int] = None,
               heartbeat_timeout: Optional[int] = None) -> None:
    """Join the global JAX runtime. Process 0's address is the rendezvous
    point (the machine-list/hostfile role); blocks until all processes check
    in, like LGBM_NetworkInit's 120s barrier — but heartbeated and reusable
    across every collective rather than per-training-job. A worker that
    never shows up fails the rendezvous after ``init_timeout`` (default
    120 s, the reference's bound); a worker that dies later is detected by
    missed heartbeats and takes the job down rather than hanging it."""
    global _initialized
    if _initialized:
        log.info("distributed runtime already initialized; skipping")
        return
    if init_timeout is None:
        init_timeout = int(os.environ.get(ENV_INIT_TIMEOUT,
                                          DEFAULT_INIT_TIMEOUT))
    kwargs = {}
    if heartbeat_timeout is None and ENV_HEARTBEAT_TIMEOUT in os.environ:
        heartbeat_timeout = int(os.environ[ENV_HEARTBEAT_TIMEOUT])
    if heartbeat_timeout is not None:
        kwargs["heartbeat_timeout_seconds"] = heartbeat_timeout
    _enable_cpu_collectives()
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids,
                               initialization_timeout=init_timeout,
                               **kwargs)
    _initialized = True
    log.info("distributed init: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def initialize_from_env() -> bool:
    """Initialize from the MMLTPU_* env contract when present (the launcher
    writes it per executor the way the reference's driver writes
    hostfile.txt). Returns True when distributed init ran; False means
    single-process mode — both are valid, same downstream code."""
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return False
    configure_xla_cache()
    initialize(coordinator_address=addr,
               num_processes=int(os.environ[ENV_NUM_PROCESSES]),
               process_id=int(os.environ[ENV_PROCESS_ID]))
    return True


_cache_configured = False


def configure_xla_cache() -> None:
    """Enable the persistent XLA compilation cache (HLO-hash keyed, so
    never stale). Fleet workers, CI runs AND first single-process fits
    recompile the same programs on every launch; the cache turns that into
    a disk read — worth minutes on small hosts (42 s of a cold 1M-row GBDT
    fit was recompile of cacheable programs, VERDICT round 4 weak #5).
    Called on entry to fit_gbdt and TpuLearner.fit as well as by the
    distributed init and tests/conftest.py. MMLTPU_XLA_CACHE="" opts out;
    this is the single source of the dir/threshold policy."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    cache = os.environ.get("MMLTPU_XLA_CACHE", "/tmp/mmlspark_tpu_xla_cache")
    if not cache:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    except Exception:  # cache is an optimization, never a requirement
        pass


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def global_mesh(axes: Optional[dict[str, int]] = None) -> "jax.sharding.Mesh":
    """A mesh over ALL processes' devices. Default: 1-D ``data`` axis over
    every chip in the job (pure DP, the reference's only strategy); pass
    ``axes`` for dp x tp x sp x ep layouts. Put ``data`` outermost so DP
    gradient all-reduce crosses DCN once per step while tp/sp/ep ride ICI."""
    if axes is None:
        axes = {"data": jax.device_count()}
    return meshlib.make_mesh(axes, devices=jax.devices())


def process_barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (the role of the
    reference's blocking NetworkInit rendezvous) — a psum of 1 over a 1-D
    global mesh forces a cross-host collective."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    ones = jax.device_put(
        jnp.ones((jax.device_count(),), jnp.int32),
        NamedSharding(mesh, P("data")))

    @jax.jit
    def _sum(x):
        return x.sum()

    total = int(_sum(ones))
    assert total == jax.device_count(), (name, total)
