"""Device mesh + sharding helpers: the framework's distributed substrate.

Replaces the reference's three communication mechanisms (SURVEY.md §2.7) with
one: XLA collectives over an explicit ``jax.sharding.Mesh``.
  * MPI ring over ssh (cntk-train/.../CommandBuilders.scala:149-267)  → data-
    parallel gradient all-reduce inserted by XLA when params are replicated
    and batches are sharded over the ``data`` axis;
  * LightGBM socket collective (TrainUtils.scala:141-142)             → psum
    of histograms over the mesh (models/gbdt);
  * ssh/scp data movement                                             → one
    ``jax.device_put`` of columnar batches with a NamedSharding.

Axis conventions (used across the framework):
  ``data``  — batch dimension (DP);
  ``model`` — tensor-parallel dimension (TP, e.g. wide dense kernels);
additional axes (pipeline/sequence/expert) compose the same way.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from ..resilience import faults

# host->HBM placement telemetry: every sharded batch/replicated-tree put
# made through this module (trainer feeds, GBDT bin uploads, serving
# batches). No-ops unless MMLSPARK_TPU_TELEMETRY=1.
_m_put_bytes = telemetry.registry.counter(
    "mmlspark_mesh_put_bytes",
    "host bytes handed to device placement (shard_batch/put_global_batch)")
_m_put_seconds = telemetry.registry.histogram(
    "mmlspark_mesh_put_seconds",
    "wall time of one device placement call (dispatch side — transfers "
    "may complete asynchronously)")


def _observe_put(t0: float, tree):
    _m_put_seconds.observe(time.perf_counter() - t0)
    _m_put_bytes.inc(sum(getattr(a, "nbytes", 0)
                         for a in jax.tree_util.tree_leaves(tree)))

# Collectives issued concurrently from multiple host threads can interleave
# across the same devices and deadlock (each device waits on a different
# collective). Any fit that runs a multi-device collective program while
# other fits may run on other threads (e.g. TuneHyperparameters' pool)
# must hold this lock; single-device fits need not. Reentrant so a stage
# can span feature-planning collectives AND the engine fit (which acquires
# it again) in one critical section — two separate acquisitions would let
# another thread's collectives interleave between them with a different
# order on each process.
collective_fit_lock = threading.RLock()

# ---- local-fit mode -------------------------------------------------------
# Embarrassingly-parallel search on a fleet (TuneHyperparameters) assigns
# whole trials to processes; each process then fits ITS trials with no
# cross-process collectives at all. Inside this mode every fit behaves as a
# single-process single-device program: effective_process_count() is 1 and
# create_mesh()/make_mesh() default to one local device. A module-level
# counter (not a contextvar) because the tuner's worker THREADS must see
# the flag set by the coordinating thread.
_local_fit_count = 0
_local_fit_guard = threading.Lock()


class local_fit_mode:
    """Context manager: fits inside run process-locally (no collectives)."""

    def __enter__(self):
        global _local_fit_count
        with _local_fit_guard:
            _local_fit_count += 1
        return self

    def __exit__(self, *exc):
        global _local_fit_count
        with _local_fit_guard:
            _local_fit_count -= 1
        return False


def in_local_fit() -> bool:
    return _local_fit_count > 0


def effective_process_count() -> int:
    """jax.process_count(), except 1 inside local-fit mode — the switch
    that steers every fleet-collective code path (pooled GBDT statistics,
    multi-host batch assembly, trainer rendezvous) to its single-process
    form."""
    return 1 if in_local_fit() else jax.process_count()


def create_mesh(data: Optional[int] = None, model: int = 1,
                devices: Optional[Sequence] = None,
                axis_names: tuple[str, ...] = ("data", "model")) -> Mesh:
    """Build a 2-D (data, model) mesh over the available devices.

    With a single chip this degrades to a 1x1 mesh and every sharding becomes
    a no-op — the same program runs unchanged from 1 chip to a pod, which is
    the core TPU-first contract (vs. the reference's separate single-node and
    MPI code paths, CommandBuilders.scala:90-100 vs :149-267).
    """
    if devices is None:
        devices = ([jax.local_devices()[0]] if in_local_fit()
                   else jax.devices())
    devices = list(devices)
    n = len(devices)
    if data is None:
        if model < 1 or n % model != 0:
            raise ValueError(
                f"model axis ({model}) must divide the device count ({n}) "
                f"— a silently-truncated mesh would train/serve on a "
                f"subset of the chips")
        data = n // model
    if data < 1 or model < 1:
        raise ValueError(f"mesh {data}x{model} is empty: {n} devices cannot "
                         f"host a model axis of {model}")
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data*model} devices, have {n}")
    dev_array = np.asarray(devices[:data * model]).reshape(data, model)
    return Mesh(dev_array, axis_names)


def make_mesh(axes: dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Build an N-D mesh from {axis_name: size}. Axis order = dict order
    (outermost first — put ``data`` outermost so DP collectives cross the
    slowest links and tp/sp/ep ride contiguous ICI neighbors)."""
    if devices is None:
        devices = ([jax.local_devices()[0]] if in_local_fit()
                   else jax.devices())
    devices = list(devices)
    sizes = list(axes.values())
    if any(s < 1 for s in sizes):
        raise ValueError(f"mesh axes must be >= 1, got {axes}")
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, "
                         f"have {len(devices)}")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def stable_host_id() -> str:
    """This process's STABLE elastic host identity: its LAUNCH rank
    (``MMLTPU_PROCESS_ID``) when the launcher's env contract set one,
    else the current ``jax.process_index()``. Heartbeat files, death/
    evict verdicts, and rendezvous ranks all key on this id — and it
    must survive re-ranking across rendezvous generations (a survivor
    that becomes rank 0 of a shrunken incarnation keeps the host id it
    launched with)."""
    import os
    v = os.environ.get("MMLTPU_PROCESS_ID", "")
    if v.isdigit():
        return f"host{int(v)}"
    return f"host{jax.process_index()}"


def host_device_groups(n_groups: int = 0) -> list[tuple[str, list]]:
    """Partition the visible devices into named "host" groups — the failure
    domains elastic training (resilience/elastic.py) supervises and
    re-meshes over.

    Default (``n_groups=0``): one group per JAX process (device.process_index
    — the real host boundary on a TPU fleet; a preempted VM takes exactly
    its process's chips with it). Single-process with ``n_groups>1``: split
    the local devices into ``n_groups`` contiguous chunks — simulated hosts
    for chaos testing and laptop rehearsal of the multi-host recovery path
    (the conftest 8-device CPU mesh plays a 4-host fleet). Group ids are
    stable across calls ("host0", "host1", ... in device order), which is
    what heartbeat files and death verdicts key on.
    """
    devices = list(jax.devices())
    if n_groups and n_groups > 1:
        if n_groups > len(devices):
            raise ValueError(f"cannot split {len(devices)} devices into "
                             f"{n_groups} host groups")
        per = len(devices) // n_groups
        groups = [(f"host{g}", devices[g * per:(g + 1) * per])
                  for g in range(n_groups)]
        # a non-divisible split must not silently strand chips: the tail
        # devices ride with the last host
        groups[-1][1].extend(devices[n_groups * per:])
        return groups
    by_proc: dict[int, list] = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    return [(f"host{p}", by_proc[p]) for p in sorted(by_proc)]


def batch_sharding(mesh: Mesh, batch_axis: str = "data") -> NamedSharding:
    """Shard dim 0 (batch) over the data axis, replicate the rest."""
    return NamedSharding(mesh, P(batch_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(arrays, mesh: Mesh, batch_axis: str = "data"):
    """device_put a pytree of host arrays with dim-0 sharded over `data` —
    the one host->HBM hop that replaces the reference's per-element JNI
    copies (CNTKModel.scala:67-74) and scp legs (CommandBuilders.scala:200-228).

    On a trivial (single-device) mesh the arrays are placed UNCOMMITTED
    (plain ``jnp.asarray``): committed / sharding-annotated inputs were
    measured 17-100x slower on single-chip tunnel backends (the plugin
    re-ships committed buffers per dispatch, and NamedShardings force jit
    through the SPMD partitioner) — and a 1-device sharding is
    semantically a no-op anyway."""
    if not telemetry.enabled():
        if mesh.size == 1:
            import jax.numpy as jnp
            return jax.tree_util.tree_map(jnp.asarray, arrays)
        sh = batch_sharding(mesh, batch_axis)
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh),
                                      arrays)
    t0 = time.perf_counter()
    if mesh.size == 1:
        import jax.numpy as jnp
        out = jax.tree_util.tree_map(jnp.asarray, arrays)
    else:
        sh = batch_sharding(mesh, batch_axis)
        out = jax.tree_util.tree_map(lambda a: jax.device_put(a, sh),
                                     arrays)
    _observe_put(t0, arrays)
    return out


def _pad_rows_to_multiple(arr: np.ndarray, mult: int) -> tuple[np.ndarray, int]:
    n = arr.shape[0]
    rem = (-n) % max(1, mult)
    if rem == 0:
        return arr, n
    pad = np.repeat(arr[-1:], rem, axis=0)
    return np.concatenate([arr, pad], axis=0), n


def pad_batch_to_devices(arr: np.ndarray, mesh: Mesh,
                         batch_axis: str = "data") -> tuple[np.ndarray, int]:
    """Pad dim 0 to a multiple of the data-axis size (XLA needs equal shards).
    Returns (padded, original_n)."""
    return _pad_rows_to_multiple(arr, mesh.shape[batch_axis])


def pad_batch_to_local_devices(arr: np.ndarray, mesh: Mesh,
                               batch_axis: str = "data") -> tuple[np.ndarray, int]:
    """Multi-host variant of pad_batch_to_devices: pad THIS process's local
    rows to a multiple of its share of the batch axis, so the per-process
    shards concatenate into an evenly divisible global batch. NOTE: in SPMD
    every process must end up with the SAME padded length — callers feed
    equal-length slices (models.trainer synchronizes the per-step row count)."""
    return _pad_rows_to_multiple(arr, mesh.shape[batch_axis]
                                 // effective_process_count())


def local_rows(global_array, n: Optional[int] = None) -> np.ndarray:
    """THIS process's contiguous rows of a dim-0-sharded global array
    (inverse of put_global_batch), optionally sliced to the first n real
    (unpadded) rows. Arrays replicated over an inner (model/seq) axis
    expose one addressable shard PER replica — dedupe by row range so a
    tp-sharded inference output doesn't repeat its rows."""
    shards = {}
    for s in global_array.addressable_shards:
        shards.setdefault(s.index[0].start or 0, s)
    out = np.concatenate([np.asarray(shards[k].data)
                          for k in sorted(shards)], axis=0)
    return out[:n] if n is not None else out


def put_global_batch(arr, mesh: Mesh, batch_axis: str = "data"):
    """Place a batch dim-0-sharded over `batch_axis`. Single-process: one
    device_put. Multi-process: `arr` is THIS process's local rows; the global
    array is assembled from every process's shard (the reference has no
    analog — its data stays in Spark partitions and is shipped per-worker
    over scp/JNI, CommandBuilders.scala:200-228)."""
    faults.inject("dataplane.put")
    if not telemetry.enabled():
        if effective_process_count() == 1:
            if mesh.size == 1:  # trivial mesh: stay off the SPMD path
                import jax.numpy as jnp
                return jnp.asarray(arr)
            return jax.device_put(arr, batch_sharding(mesh, batch_axis))
        return jax.make_array_from_process_local_data(
            batch_sharding(mesh, batch_axis), np.asarray(arr))
    t0 = time.perf_counter()
    if effective_process_count() == 1:
        if mesh.size == 1:
            import jax.numpy as jnp
            out = jnp.asarray(arr)
        else:
            out = jax.device_put(arr, batch_sharding(mesh, batch_axis))
    else:
        out = jax.make_array_from_process_local_data(
            batch_sharding(mesh, batch_axis), np.asarray(arr))
    _observe_put(t0, arr)
    return out


def put_replicated(tree, mesh: Mesh):
    """Replicate a pytree over the whole (possibly multi-host) mesh. Every
    process must hold identical values (same-seed init guarantees this).
    Trivial meshes skip the NamedSharding (see shard_batch)."""
    if mesh.size == 1:
        import jax.numpy as jnp
        return jax.tree_util.tree_map(jnp.asarray, tree)
    if effective_process_count() == 1:
        return jax.device_put(tree, replicated(mesh))
    sh = replicated(mesh)
    return jax.tree_util.tree_map(
        lambda a: jax.make_array_from_process_local_data(sh, np.asarray(a)),
        tree)


#: tensor-parallel placement rules shared by training (TpuLearner) and
#: inference (TpuModel): wide Dense kernels shard columns over ``model``,
#: every other kernel replicates. First match wins (shard_params_tp).
TP_PARAM_RULES = (("Dense", P(None, "model")), ("kernel", P()))


def require_inner_block_local(axes: dict):
    """Multi-host locality rule shared by fit()/fitStream()/transform():
    the inner parallel block (product of the non-data axes) must divide
    the LOCAL device count. make_mesh puts ``data`` outermost, so inner
    axes span contiguous device ranges — this keeps every
    seq/expert/model/pipe collective on within-host ICI while only the dp
    all-reduce crosses hosts, and keeps checkpointing and model export
    reading process-locally-complete params."""
    inner = int(np.prod([max(1, v) for v in axes.values()]))
    if inner <= 1:
        return
    n_local = jax.local_device_count()
    if inner > n_local or n_local % inner != 0:
        desc = "*".join(f"{nm}={v}" for nm, v in axes.items() if v > 1)
        raise ValueError(
            f"the inner parallel block ({desc} = {inner}) must divide the "
            f"LOCAL device count ({n_local}) on a multi-host mesh: "
            f"seq/expert/model/pipe axes must ride ICI within a host "
            f"while dp crosses hosts")


def shard_params_tp(params, mesh: Mesh, rules: Sequence[tuple[str, P]] = (),
                    default: Optional[P] = None):
    """Apply tensor-parallel shardings to a param pytree by path substring.

    rules: [(path_substring, PartitionSpec)] — first match wins; unmatched
    leaves are replicated. This is the declarative knob the trainer uses to
    put wide dense kernels on the ``model`` axis.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves, treedef = flat
    out = []
    def _divisible(leaf, spec: P) -> bool:
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            size = int(np.prod([mesh.shape[a] for a in axes]))
            if leaf.shape[dim] % size != 0:
                return False
        return True

    multiproc = effective_process_count() > 1
    for path, leaf in leaves:
        pstr = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        spec = default if default is not None else P()
        for sub, candidate in rules:
            if (sub in pstr and len(candidate) <= np.ndim(leaf)
                    and _divisible(leaf, candidate)):
                spec = candidate
                break
        sh = NamedSharding(mesh, spec)
        if multiproc:
            # process-spanning mesh: every process holds the identical full
            # value (same-seed init), so each addressable shard is a slice
            # of the local copy — device_put cannot target non-addressable
            # devices
            host = np.asarray(leaf)
            out.append(jax.make_array_from_callback(
                host.shape, sh, lambda idx, h=host: h[idx]))
        else:
            out.append(jax.device_put(leaf, sh))
    return jax.tree_util.tree_unflatten(treedef, out)
