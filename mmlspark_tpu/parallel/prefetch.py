"""Bounded asynchronous prefetching: overlap host batch prep + H2D transfer
with device compute.

The trainer's feed path and the serving loops are producer/consumer pairs
where the producer is HOST work (index gather, pad, weight-mask build,
``device_put``/``put_global_batch`` transfers, request-batch assembly) and
the consumer is a jitted device dispatch. Run serially, the device idles
through every host phase — the executor-feeds-accelerator stall MMLSpark's
CNTK layer solved with streaming minibatch sources (arXiv:1804.04031) and
TPU input pipelines solve with host-side double buffering. Here the host
work for step ``s+1..s+depth`` runs on a daemon thread while step ``s``
executes on device, so the consuming loop receives already-placed arrays.

Semantics (the contract the tests pin):

  * **bounded depth** — at most ``depth`` produced-but-unconsumed items
    exist at any moment (a semaphore slot is acquired BEFORE the producer
    runs, so prefetched device batches never hold more than ``depth``
    batches of HBM);
  * **in-order** — items arrive exactly in producer order (one worker
    thread, one FIFO queue), so a prefetched fit replays the synchronous
    loss trajectory bit for bit;
  * **exception propagation** — a producer error re-raises at the
    consuming ``next()``; the worker never dies silently and the consumer
    never deadlocks on a dead producer;
  * **prompt shutdown** — ``close()`` (or exiting the ``with`` block)
    wakes a blocked producer and joins the thread; safe to call from a
    consumer that exits early (divergence halt, serving stop).

Thread-safety note: JAX dispatch/`device_put` are thread-safe, but
*collective* programs issued from multiple threads can interleave across
processes and deadlock — producers must only do per-process work
(transfers, host prep). Callers with per-step host collectives (e.g.
fitStream's multi-host lockstep allgather) must stay synchronous.

Fit-side fusion note: under a fused featurize->train fit
(Pipeline.fusePipeline, docs/performance.md "Fit-side fusion") the
trainer's feed producer places the RAW wire-dtype columns (int8/int16/…)
instead of the f32-widened feature matrix — featurization happens inside
the consuming device dispatch. The prefetch window then bounds *wire*
bytes of HBM, which is strictly less than the staged window for any
sub-f32 input, so ``depth`` can be raised on narrow-dtype pipelines at no
extra HBM cost.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional, Union

from .. import telemetry

# prefetch telemetry (off-by-default no-ops; MMLSPARK_TPU_TELEMETRY=1)
_m_queue_depth = telemetry.registry.gauge(
    "mmlspark_prefetch_queue_depth",
    "prefetched items currently produced but not yet consumed")
_m_produce_time = telemetry.registry.histogram(
    "mmlspark_prefetch_produce_seconds",
    "host prep + device placement time per prefetched item (producer "
    "thread) — the work the prefetcher hides behind device compute")
_m_producer_stall = telemetry.registry.histogram(
    "mmlspark_prefetch_producer_stall_seconds",
    "time the producer spent blocked because `depth` items were already "
    "outstanding (consumer-bound; harmless)")
_m_consumer_stall = telemetry.registry.histogram(
    "mmlspark_prefetch_consumer_stall_seconds",
    "time the consumer spent waiting for the next prefetched item "
    "(host-bound; the stall the prefetcher exists to shrink)")

#: queue sentinels (kind tags; unique objects, compared by identity)
_ITEM, _DONE, _ERROR = object(), object(), object()


class DevicePrefetcher:
    """Iterator running ``source`` on a background thread, ``depth`` ahead.

    ``source`` is an iterable (or a zero-arg callable returning one) whose
    ``next()`` performs the per-item host work — build the batch AND place
    it on device there, so the consumer receives ready jax Arrays.

    ``depth=0`` is honored by :func:`prefetched`, which returns the plain
    iterator (the synchronous path); ``DevicePrefetcher`` itself requires
    ``depth >= 1``.
    """

    def __init__(self, source: Union[Iterable, Callable[[], Iterable]],
                 depth: int = 2, name: str = "prefetch",
                 span: Optional[str] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self.name = name
        #: items the producer thread has finished placing (monotonic);
        #: lets tests assert a prefetched run actually ran ahead instead
        #: of degenerating to lockstep
        self.items = 0
        self._span = span
        self._source = source
        # slots acquired BEFORE producing bound produced-but-unconsumed
        # items (and therefore prefetched HBM) at exactly `depth`; the
        # queue itself can stay unbounded
        self._slots = threading.Semaphore(depth)
        self._q: "queue.Queue[tuple[str, object]]" = queue.Queue()
        self._stop = threading.Event()
        # consumer-side cursor: thread-confined, never touched by the
        # producer thread (whose entry point is _work)
        self._finished = False   # guarded-by: !_work
        self._thread = threading.Thread(target=self._work, daemon=True,
                                        name=f"prefetch-{name}")
        self._thread.start()

    # ---- producer (worker thread) ----
    def _acquire_slot(self) -> bool:
        """Blocking slot acquire that stays responsive to close()."""
        t0 = time.perf_counter()
        while not self._stop.is_set():
            if self._slots.acquire(timeout=0.05):
                _m_producer_stall.observe(time.perf_counter() - t0)
                return True
        return False

    def _work(self):
        try:
            it = iter(self._source() if callable(self._source)
                      else self._source)
            while not self._stop.is_set():
                if not self._acquire_slot():
                    return              # closed while waiting for a slot
                t0 = time.perf_counter()
                if self._span:
                    with telemetry.trace.span(self._span, source=self.name):
                        item = next(it, _DONE)
                else:
                    item = next(it, _DONE)
                if item is _DONE:
                    break
                _m_produce_time.observe(time.perf_counter() - t0)
                self.items += 1
                self._q.put((_ITEM, item))
                _m_queue_depth.set(self._q.qsize())
        except BaseException as e:       # re-raised at the consumer's next()
            self._q.put((_ERROR, e))
        else:
            self._q.put((_DONE, None))

    # ---- consumer ----
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                kind, item = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                # belt-and-braces: the worker's except/else clauses always
                # enqueue a terminal record, but a worker killed without
                # running them (interpreter teardown) must not hang us
                if not self._thread.is_alive():
                    self._finished = True
                    raise RuntimeError(
                        f"prefetch worker {self.name!r} died without "
                        f"delivering") from None
        if kind is _ITEM:
            _m_consumer_stall.observe(time.perf_counter() - t0)
            _m_queue_depth.set(self._q.qsize())
            self._slots.release()
            return item
        self._finished = True
        if kind is _ERROR:
            self.close()
            raise item
        self._thread.join(timeout=5.0)
        raise StopIteration

    # ---- lifecycle ----
    def close(self):
        """Stop the producer and reclaim the thread. Idempotent; safe on
        early consumer exit (divergence halt, serving stop) — a producer
        blocked on a full prefetch window wakes within one slot-poll tick."""
        self._stop.set()
        self._finished = True
        # drain queued items so a producer blocked in q.put (unbounded
        # queue: never happens, but cheap) or mid-produce can finish
        try:
            while True:
                self._q.get_nowait()
                self._slots.release()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        _m_queue_depth.set(0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def prefetched(source: Union[Iterable, Callable[[], Iterable]],
               depth: int = 2, name: str = "prefetch",
               span: Optional[str] = None) -> Iterator:
    """``DevicePrefetcher`` when ``depth >= 1``, the plain (synchronous)
    iterator when ``depth == 0`` — the one switch call sites need. The
    returned iterator always supports ``close()`` so consumer ``finally``
    blocks are uniform."""
    if depth <= 0:
        it = iter(source() if callable(source) else source)
        return _SyncIter(it)
    return DevicePrefetcher(source, depth=depth, name=name, span=span)


class _SyncIter:
    """Plain iterator with a no-op close() (depth=0 fallback)."""

    __slots__ = ("_it",)

    def __init__(self, it: Iterator):
        self._it = it

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
