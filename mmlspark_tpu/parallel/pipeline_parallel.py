"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.7: data parallelism
only). TPU-first design: the pipeline is ONE jitted program under
``shard_map`` — each device along the ``pipe`` axis holds one stage's
parameters (a stacked pytree sharded on its leading axis), microbatch
activations hop stage-to-stage with ``lax.ppermute`` (neighbor-only ICI
traffic), and the whole schedule is a ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks. Differentiable end-to-end
(``ppermute``/``scan`` have transposes), so the same primitive serves
training — no hand-written backward schedule.

Composes with data parallelism: put ``pipe`` after ``data`` in the mesh and
shard the batch over ``data`` as usual.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map


def stack_stage_params(stage_params: list):
    """Stack per-stage pytrees (identical treedefs) along a new leading axis —
    the axis the ``pipe`` mesh dimension shards."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def shard_pipeline_params(stacked, mesh: Mesh, axis_name: str = "pipe"):
    """Place stacked stage params with leading axis sharded over ``pipe``."""
    sh = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), stacked)


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   axis_name: str = "pipe", n_microbatches: int = None,
                   batch_axis: str = None):
    """Run ``n_stages`` chained applications of ``stage_fn`` as a pipeline.

    stage_fn(params_i, h) -> h'   one stage; h and h' share a shape.
    stacked_params: pytree with leading axis n_stages (= mesh[axis_name]).
    x: global batch (N, ...); split into ``n_microbatches`` equal microbatches.
    batch_axis: optional mesh axis to also shard the batch over (DP x PP).

    Returns f(x) with shape (N, ...), equivalent to sequentially applying all
    stages. Tick t: stage 0 injects microbatch t; stage s processes what
    stage s-1 produced at t-1; the last stage's outputs are collected and
    replicated back via a masked psum.
    """
    n_stages = mesh.shape[axis_name]
    N = x.shape[0]
    M = n_microbatches or n_stages
    if N % M != 0:
        raise ValueError(f"batch {N} not divisible by n_microbatches {M}")
    mb = N // M
    x_mb = x.reshape(M, mb, *x.shape[1:])

    b = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    x_spec = P(None, b)                       # (M, mb, ...): mb over data
    p_spec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    out_spec = P(None, b)

    def local(params, xm):
        # params leaves: (1, ...) local stage slice; xm: (M, mb_local, ...)
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        s_idx = lax.axis_index(axis_name)
        last = n_stages - 1
        zero = jnp.zeros_like(xm[0])

        def tick(carry, t):
            state, outbuf = carry
            inject = xm[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where(s_idx == 0, inject, state)
            y = stage_fn(params, h_in)
            # rotate activations one stage forward around the ring
            state_next = lax.ppermute(
                y, axis_name, [(i, (i + 1) % n_stages) for i in range(n_stages)])
            # the last stage finished microbatch t-last at tick t
            slot = jnp.clip(t - last, 0, M - 1)
            write = jnp.logical_and(s_idx == last, t >= last)
            cur = lax.dynamic_index_in_dim(outbuf, slot, keepdims=False)
            upd = jnp.where(write, y.astype(outbuf.dtype), cur)
            outbuf = lax.dynamic_update_index_in_dim(outbuf, upd, slot, 0)
            return (state_next, outbuf), None

        outbuf0 = jnp.zeros((M,) + zero.shape, xm.dtype)
        (_, outbuf), _ = lax.scan(tick, (zero, outbuf0),
                                  jnp.arange(M + n_stages - 1))
        # only the last stage holds real outputs; replicate over the pipe axis
        outbuf = jnp.where(s_idx == last, outbuf, jnp.zeros_like(outbuf))
        return lax.psum(outbuf, axis_name)

    out = shard_map(local, mesh=mesh,
                    in_specs=(p_spec, x_spec), out_specs=out_spec,
                    check=False)(stacked_params, x_mb)
    return out.reshape(N, *out.shape[2:])


def transformer_pp_forward(cfg: dict, params, tokens, mesh: Mesh,
                           n_microbatches: int = None,
                           axis_name: str = "pipe",
                           batch_axis: str = "data"):
    """Forward pass of the transformer family with its encoder-block stack
    run as a GPipe pipeline over the ``pipe`` mesh axis.

    This is how ``TpuLearner.setPipelineParallel(k)`` trains: the embed and
    head (a few % of the FLOPs) run replicated across the pipe axis, the L
    encoder blocks split into ``k`` stages of L/k blocks each, and
    microbatch activations hop stage-to-stage over ``ppermute`` — one
    differentiable jitted program, so ``jax.grad`` of a loss on these
    logits yields the full pipelined backward with no hand-written
    schedule. ``params`` keeps the ORIGINAL flax layout (block subtrees are
    stacked inside the trace), so the optimizer, checkpoints, and TpuModel
    inference reuse the fitted tree unchanged.
    """
    import flax.linen as nn

    from ..models.modules import build_model

    enc = build_model(cfg)          # field access only (dtype, dims, attn)
    L, pp = enc.layers, mesh.shape[axis_name]
    if L % pp != 0:
        raise ValueError(f"layers ({L}) must divide by the pipe axis ({pp})")
    p = params["params"] if "params" in params else params
    B, T = tokens.shape
    emb = nn.Embed(enc.vocab_size, enc.d_model, dtype=enc.dtype).apply(
        {"params": p["Embed_0"]}, tokens)
    pos = nn.Embed(enc.max_len, enc.d_model, dtype=enc.dtype).apply(
        {"params": p["Embed_1"]}, jnp.arange(T)[None, :])
    h = (emb + pos).astype(enc.dtype)

    # stage j = blocks [j*k, (j+1)*k): leaf shapes (pp, k, ...)
    k = L // pp
    stages = [stack_stage_params([p[f"block{j * k + i}"] for i in range(k)])
              for j in range(pp)]
    stacked = stack_stage_params(stages)

    from ..models.modules import _EncoderBlock
    Block = nn.remat(_EncoderBlock) if enc.remat else _EncoderBlock
    block = Block(d_model=enc.d_model, heads=enc.heads,
                  mlp_ratio=enc.mlp_ratio, dtype=enc.dtype,
                  attention=enc._attention)

    def stage_fn(stage_params, hm):
        def body(hc, blk_p):
            return block.apply({"params": blk_p}, hc), None
        out, _ = lax.scan(body, hm, stage_params)
        return out

    h = pipeline_apply(stage_fn, stacked, h, mesh, axis_name=axis_name,
                       n_microbatches=n_microbatches or pp,
                       batch_axis=batch_axis)
    h = nn.LayerNorm(dtype=enc.dtype).apply(
        {"params": p["LayerNorm_0"]}, h)
    if enc.pool == "mean":
        h = jnp.mean(h, axis=1)
    logits = nn.Dense(enc.num_classes, dtype=enc.dtype).apply(
        {"params": p["Dense_0"]}, h)
    return logits.astype(jnp.float32)
