"""JAX API compatibility shims for the parallel layer.

The framework tracks JAX's public API, which moves under us: ``shard_map``
graduated from ``jax.experimental.shard_map`` to ``jax.shard_map`` and its
replication-check keyword renamed ``check_rep`` -> ``check_vma`` along the
way. Call sites that pin either spelling break on the other half of the
installed-version matrix — exactly the drift that turned the seq-parallel
and pipeline-parallel suites red. This module resolves the installed
spelling ONCE at import and exposes a single :func:`shard_map` the rest of
``parallel/`` (and the GBDT engine's collective tree builders) call, so the
next rename is a one-line fix here instead of a five-module sweep.
"""

from __future__ import annotations

import jax

# Resolve the installed shard_map entry point: prefer the public
# ``jax.shard_map`` (>= 0.6), fall back to the experimental module that
# hosted it through the 0.4/0.5 series.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _CHECK_KWARG = "check_vma"
else:  # pragma: no cover - exercised only on older jaxlib images
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KWARG = "check_rep"
    try:  # 0.4.30+ spells it check_rep; probe instead of version-sniffing
        import inspect
        if "check_rep" not in inspect.signature(_shard_map).parameters:
            _CHECK_KWARG = "check_vma"
    except (TypeError, ValueError):
        pass


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside a ``shard_map`` body.

    ``lax.axis_size`` where the installed JAX has it; the 0.4-series
    equivalent (``jax.core.axis_frame`` resolves the bound axis env and
    yields the size as a plain int) otherwise. Must stay static — callers
    unroll ring schedules and ppermute tables from it.
    """
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as _core
    return _core.axis_frame(axis_name)


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    """Version-portable ``shard_map``.

    ``check`` maps onto whichever replication/varying-manual-axes check
    keyword the installed JAX spells (``check_rep`` before the rename,
    ``check_vma`` after). The framework always passes False: its collective
    bodies (ring attention, GPipe ticks, masked-psum tree builders) use
    per-device ``axis_index`` branches the static checker cannot prove
    replicated.
    """
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KWARG: check})
