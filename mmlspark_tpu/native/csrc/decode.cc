// Image decode: JPEG via libjpeg, PNG via libpng simplified API, BMP and
// PPM(P6) by hand. Output is HWC uint8, BGR channel order — the layout the
// reference gets from OpenCV Imgcodecs.imdecode (Image.scala:58-75), so the
// Python ImageSchema path is byte-compatible with the cv2 fallback.

#include "mmltpu.h"

#include <cctype>
#include <csetjmp>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <jpeglib.h>
#include <png.h>

extern "C" void mmltpu_free(void *p) { free(p); }

namespace {

// ---- JPEG ----

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr *err = reinterpret_cast<JpegErr *>(cinfo->err);
  longjmp(err->jump, 1);
}

int decode_jpeg(const uint8_t *data, size_t len,
                uint8_t **out, int *h, int *w, int *c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  // volatile: both are written after setjmp and read after the longjmp
  uint8_t *volatile buf = nullptr;
  uint8_t *volatile row = nullptr;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    free(buf);
    free(row);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char *>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;  // grayscale is upconverted for us
  jpeg_start_decompress(&cinfo);
  const int W = cinfo.output_width, H = cinfo.output_height;
  const int C = cinfo.output_components;  // 3 after JCS_RGB
  if (C != 3) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  buf = static_cast<uint8_t *>(malloc(static_cast<size_t>(H) * W * 3));
  row = static_cast<uint8_t *>(malloc(static_cast<size_t>(W) * 3));
  if (!buf || !row) {
    jpeg_destroy_decompress(&cinfo);
    free(buf);
    free(row);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t *dst = buf + static_cast<size_t>(cinfo.output_scanline) * W * 3;
    JSAMPROW rows[1] = {row};
    jpeg_read_scanlines(&cinfo, rows, 1);
    for (int x = 0; x < W; ++x) {  // RGB -> BGR
      dst[x * 3 + 0] = row[x * 3 + 2];
      dst[x * 3 + 1] = row[x * 3 + 1];
      dst[x * 3 + 2] = row[x * 3 + 0];
    }
  }
  free(row);
  row = nullptr;
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out = buf; *h = H; *w = W; *c = 3;
  return 0;
}

// ---- PNG (simplified libpng 1.6 API) ----

int decode_png(const uint8_t *data, size_t len,
               uint8_t **out, int *h, int *w, int *c) {
  png_image image;
  memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, data, len)) return -1;
  image.format = PNG_FORMAT_BGR;  // alpha composited onto black? no: dropped
  const size_t stride = PNG_IMAGE_ROW_STRIDE(image);
  const size_t size = PNG_IMAGE_BUFFER_SIZE(image, stride);
  uint8_t *buf = static_cast<uint8_t *>(malloc(size));
  if (!buf) {
    png_image_free(&image);
    return -1;
  }
  if (!png_image_finish_read(&image, nullptr, buf,
                             static_cast<png_int_32>(stride), nullptr)) {
    png_image_free(&image);
    free(buf);
    return -1;
  }
  *out = buf; *h = image.height; *w = image.width; *c = 3;
  return 0;
}

// ---- BMP (uncompressed 24/32-bit BITMAPINFOHEADER) ----

uint32_t rd32(const uint8_t *p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | (static_cast<uint32_t>(p[3]) << 24);
}
uint16_t rd16(const uint8_t *p) { return p[0] | (p[1] << 8); }

int decode_bmp(const uint8_t *data, size_t len,
               uint8_t **out, int *h, int *w, int *c) {
  if (len < 54) return -1;
  const uint32_t off = rd32(data + 10);
  const uint32_t hdr = rd32(data + 14);
  if (hdr < 40) return -1;
  const int32_t W = static_cast<int32_t>(rd32(data + 18));
  int32_t H = static_cast<int32_t>(rd32(data + 22));
  const uint16_t bpp = rd16(data + 28);
  const uint32_t comp = rd32(data + 30);
  if (W <= 0 || H == 0 || comp != 0 || (bpp != 24 && bpp != 32)) return -1;
  const bool flip = H > 0;  // positive height = bottom-up rows
  if (H < 0) H = -H;
  const size_t bytespp = bpp / 8;
  const size_t row_sz = (static_cast<size_t>(W) * bytespp + 3) & ~size_t(3);
  if (off + row_sz * H > len) return -1;
  uint8_t *buf = static_cast<uint8_t *>(malloc(static_cast<size_t>(H) * W * 3));
  if (!buf) return -1;
  for (int y = 0; y < H; ++y) {
    const uint8_t *src = data + off + row_sz * (flip ? (H - 1 - y) : y);
    uint8_t *dst = buf + static_cast<size_t>(y) * W * 3;
    for (int x = 0; x < W; ++x) {  // BMP pixels are already BGR(A)
      dst[x * 3 + 0] = src[x * bytespp + 0];
      dst[x * 3 + 1] = src[x * bytespp + 1];
      dst[x * 3 + 2] = src[x * bytespp + 2];
    }
  }
  *out = buf; *h = H; *w = W; *c = 3;
  return 0;
}

// ---- PPM P6 (maxval <= 255) ----

int decode_ppm(const uint8_t *data, size_t len,
               uint8_t **out, int *h, int *w, int *c) {
  size_t pos = 2;  // past "P6"
  long vals[3];
  for (int i = 0; i < 3; ++i) {
    while (pos < len &&
           (isspace(data[pos]) || data[pos] == '#')) {
      if (data[pos] == '#')
        while (pos < len && data[pos] != '\n') ++pos;
      else
        ++pos;
    }
    long v = 0;
    bool any = false;
    while (pos < len && data[pos] >= '0' && data[pos] <= '9') {
      v = v * 10 + (data[pos] - '0');
      ++pos;
      any = true;
    }
    if (!any) return -1;
    vals[i] = v;
  }
  if (pos >= len || !isspace(data[pos])) return -1;
  ++pos;  // single whitespace before raster
  const long W = vals[0], H = vals[1], maxv = vals[2];
  if (W <= 0 || H <= 0 || maxv <= 0 || maxv > 255) return -1;
  const size_t need = static_cast<size_t>(W) * H * 3;
  if (pos + need > len) return -1;
  uint8_t *buf = static_cast<uint8_t *>(malloc(need));
  if (!buf) return -1;
  const uint8_t *src = data + pos;
  for (size_t i = 0; i < static_cast<size_t>(W) * H; ++i) {  // RGB -> BGR
    buf[i * 3 + 0] = src[i * 3 + 2];
    buf[i * 3 + 1] = src[i * 3 + 1];
    buf[i * 3 + 2] = src[i * 3 + 0];
  }
  *out = buf; *h = static_cast<int>(H); *w = static_cast<int>(W); *c = 3;
  return 0;
}

}  // namespace

extern "C" int mmltpu_decode_image(const uint8_t *data, size_t len,
                                   uint8_t **out, int *h, int *w, int *c) {
  if (!data || len < 8) return -1;
  if (data[0] == 0xFF && data[1] == 0xD8) return decode_jpeg(data, len, out, h, w, c);
  if (data[0] == 0x89 && data[1] == 'P' && data[2] == 'N' && data[3] == 'G')
    return decode_png(data, len, out, h, w, c);
  if (data[0] == 'B' && data[1] == 'M') return decode_bmp(data, len, out, h, w, c);
  if (data[0] == 'P' && data[1] == '6') return decode_ppm(data, len, out, h, w, c);
  return -1;
}
