// Parallel CSV -> row-major float32 matrix.
//
// The GBDT ingest fast path: the reference feeds LightGBM by converting
// Spark rows to dense C buffers per partition (LightGBMUtils.scala:192-222);
// here a delimited file is chunked on newline boundaries and parsed by a
// thread per chunk with a hand-rolled float scanner (strtod fallback for
// exotic forms), producing one contiguous matrix ready for jnp.asarray.

#include "mmltpu.h"

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Fast float parse over [p, end); advances *p to the first unconsumed char.
// Handles [+-]digits[.digits][eE[+-]digits], inf/nan; falls back to strtod
// when the fast path cannot represent the value exactly enough.
float parse_float(const char **pp, const char *end) {
  const char *p = *pp;
  const char *start = p;
  bool neg = false;
  if (p < end && (*p == '+' || *p == '-')) neg = (*p++ == '-');
  double mant = 0.0;
  int digits = 0, frac = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    mant = mant * 10.0 + (*p - '0');
    ++p; ++digits;
  }
  if (p < end && *p == '.') {
    ++p;
    while (p < end && *p >= '0' && *p <= '9') {
      mant = mant * 10.0 + (*p - '0');
      ++p; ++digits; ++frac;
    }
  }
  if (digits == 0) {  // inf / nan / garbage -> strtod
    char tmp[64];
    const size_t n = std::min<size_t>(end - start, sizeof(tmp) - 1);
    memcpy(tmp, start, n);
    tmp[n] = '\0';
    char *stop = nullptr;
    const double v = strtod(tmp, &stop);
    if (stop == tmp) { *pp = start; return NAN; }
    *pp = start + (stop - tmp);
    return static_cast<float>(v);
  }
  int exp = 0;
  if (p < end && (*p == 'e' || *p == 'E')) {
    const char *ep = p + 1;
    bool eneg = false;
    if (ep < end && (*ep == '+' || *ep == '-')) eneg = (*ep++ == '-');
    int ev = 0, edig = 0;
    while (ep < end && *ep >= '0' && *ep <= '9') {
      ev = ev * 10 + (*ep - '0');
      ++ep; ++edig;
    }
    if (edig) { exp = eneg ? -ev : ev; p = ep; }
  }
  const double v = mant * pow(10.0, exp - frac);
  *pp = p;
  return static_cast<float>(neg ? -v : v);
}

// Parse one line into out[0..cols); returns fields actually seen.
int parse_line(const char *p, const char *end, char delim,
               float *out, int64_t cols) {
  int64_t f = 0;
  while (p < end && f < cols) {
    while (p < end && *p == ' ') ++p;
    const char *before = p;
    const float v = parse_float(&p, end);
    out[f++] = (p == before) ? NAN : v;
    while (p < end && *p != delim) ++p;  // trailing junk in the field
    if (p < end) ++p;                    // skip delimiter
  }
  for (int64_t i = f; i < cols; ++i) out[i] = NAN;
  return static_cast<int>(f);
}

}  // namespace

extern "C" int mmltpu_csv_parse(const char *path, int skip_header, char delim,
                                int n_threads, float **out,
                                int64_t *out_rows, int64_t *out_cols) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  const long fsz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<char> text(static_cast<size_t>(std::max(0L, fsz)));
  if (fsz > 0 && fread(text.data(), 1, text.size(), f) != text.size()) {
    fclose(f);
    return -1;
  }
  fclose(f);
  const char *p = text.data();
  const char *end = p + text.size();

  if (skip_header) {
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  if (p >= end) { *out = nullptr; *out_rows = 0; *out_cols = 0; return 0; }

  // column count from the first data row
  int64_t cols = 1;
  for (const char *q = p; q < end && *q != '\n'; ++q)
    if (*q == delim) ++cols;

  // newline-boundary chunking
  const int nt = std::max(1, n_threads);
  std::vector<const char *> cuts{p};
  for (int i = 1; i < nt; ++i) {
    const char *q = p + (end - p) * static_cast<int64_t>(i) / nt;
    while (q < end && *q != '\n') ++q;
    if (q < end) ++q;
    cuts.push_back(q);
  }
  cuts.push_back(end);

  std::vector<std::vector<float>> parts(nt);
  std::vector<std::thread> threads;
  for (int t = 0; t < nt; ++t) {
    threads.emplace_back([&, t] {
      const char *q = cuts[t];
      const char *stop = cuts[t + 1];
      auto &vals = parts[t];
      while (q < stop) {
        const char *eol = q;
        while (eol < stop && *eol != '\n') ++eol;
        const char *trim = eol;
        if (trim > q && trim[-1] == '\r') --trim;
        if (trim > q) {  // skip blank lines
          vals.resize(vals.size() + cols);
          parse_line(q, trim, delim, vals.data() + vals.size() - cols, cols);
        }
        q = (eol < stop) ? eol + 1 : stop;
      }
    });
  }
  for (auto &th : threads) th.join();

  int64_t total = 0;
  for (auto &v : parts) total += static_cast<int64_t>(v.size());
  float *mat = static_cast<float *>(malloc(sizeof(float) *
                                           std::max<int64_t>(total, 1)));
  if (!mat) return -1;
  int64_t off = 0;
  for (auto &v : parts) {
    memcpy(mat + off, v.data(), v.size() * sizeof(float));
    off += static_cast<int64_t>(v.size());
  }
  *out = mat;
  *out_rows = total / cols;
  *out_cols = cols;
  return 0;
}
