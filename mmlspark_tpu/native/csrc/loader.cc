// Threaded prefetching batch loader.
//
// Worker threads claim batch indices in order, read + decode + resize each
// file, and pack a contiguous [batch, H, W, 3] uint8 buffer; finished
// batches sit in a bounded reorder window until the consumer pops them in
// sequence. This is the host half of the ingest path (SURVEY.md §7 phase
// 2): the Python side copies each batch into a persistent numpy staging
// buffer and jax.device_put's it, overlapping disk/decode with TPU compute —
// replacing the reference's per-element JNI copies (CNTKModel.scala:67-74)
// and scp/getmerge data movement (CommandBuilders.scala:200-228).

#include "mmltpu.h"

#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<uint8_t> data;  // batch*H*W*3, zero-filled padding/failures
  std::vector<uint8_t> ok;    // per-slot decode success
  int count = 0;              // valid rows (< batch only in the final batch)
};

struct Loader {
  std::vector<std::string> paths;
  int batch, out_h, out_w, n_batches, max_prefetch;
  std::vector<std::thread> workers;

  std::mutex mu;
  std::condition_variable cv_produced, cv_space;
  std::map<int, Batch> ready;   // reorder window keyed by batch index
  int next_claim = 0;           // next batch index a worker takes
  int next_emit = 0;            // next batch index the consumer needs
  bool stopping = false;

  size_t batch_bytes() const {
    return static_cast<size_t>(batch) * out_h * out_w * 3;
  }

  void fill_slot(const std::string &path, uint8_t *dst, uint8_t *ok) {
    *ok = 0;
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return;
    fseek(f, 0, SEEK_END);
    const long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    if (sz <= 0) { fclose(f); return; }
    std::vector<uint8_t> raw(static_cast<size_t>(sz));
    const size_t got = fread(raw.data(), 1, raw.size(), f);
    fclose(f);
    if (got != raw.size()) return;
    uint8_t *img = nullptr;
    int h, w, c;
    if (mmltpu_decode_image(raw.data(), raw.size(), &img, &h, &w, &c) != 0)
      return;
    if (h == out_h && w == out_w)
      memcpy(dst, img, static_cast<size_t>(out_h) * out_w * 3);
    else
      mmltpu_resize_bilinear(img, h, w, 3, dst, out_h, out_w);
    mmltpu_free(img);
    *ok = 1;
  }

  void work() {
    for (;;) {
      int bi;
      {
        std::unique_lock<std::mutex> lk(mu);
        // bound in-flight batches so memory stays O(prefetch window)
        cv_space.wait(lk, [&] {
          return stopping || (next_claim < n_batches &&
                              next_claim - next_emit < max_prefetch);
        });
        if (stopping || next_claim >= n_batches) return;
        bi = next_claim++;
      }
      Batch b;
      b.data.assign(batch_bytes(), 0);
      b.ok.assign(batch, 0);
      const int lo = bi * batch;
      const int hi = std::min<int>(lo + batch, paths.size());
      b.count = hi - lo;
      const size_t slot = static_cast<size_t>(out_h) * out_w * 3;
      for (int i = lo; i < hi; ++i)
        fill_slot(paths[i], b.data.data() + (i - lo) * slot, &b.ok[i - lo]);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (stopping) return;
        ready.emplace(bi, std::move(b));
      }
      cv_produced.notify_all();
    }
  }
};

}  // namespace

extern "C" void *mmltpu_loader_create(const char *const *paths, int n_paths,
                                      int batch, int out_h, int out_w,
                                      int n_threads, int max_prefetch) {
  if (n_paths < 0 || batch <= 0 || out_h <= 0 || out_w <= 0) return nullptr;
  Loader *ld = new Loader();
  ld->paths.reserve(n_paths);
  for (int i = 0; i < n_paths; ++i) ld->paths.emplace_back(paths[i]);
  ld->batch = batch;
  ld->out_h = out_h;
  ld->out_w = out_w;
  ld->n_batches = (n_paths + batch - 1) / batch;
  const int nt = std::max(1, std::min(n_threads, ld->n_batches == 0 ? 1
                                                 : ld->n_batches));
  // workers claim whole batches, so in-flight window must cover the thread
  // pool or threads beyond the window would never run
  ld->max_prefetch = std::max(std::max(1, max_prefetch), nt);
  for (int i = 0; i < nt; ++i)
    ld->workers.emplace_back([ld] { ld->work(); });
  return ld;
}

extern "C" int mmltpu_loader_next(void *handle, uint8_t *out, uint8_t *ok,
                                  int *out_count) {
  Loader *ld = static_cast<Loader *>(handle);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    if (ld->next_emit >= ld->n_batches) return 0;
    ld->cv_produced.wait(lk, [&] {
      return ld->ready.count(ld->next_emit) > 0;
    });
    auto it = ld->ready.find(ld->next_emit);
    b = std::move(it->second);
    ld->ready.erase(it);
    ld->next_emit++;
  }
  ld->cv_space.notify_all();  // window advanced: workers may claim again
  memcpy(out, b.data.data(), b.data.size());
  memcpy(ok, b.ok.data(), b.ok.size());
  *out_count = b.count;
  return 1;
}

extern "C" void mmltpu_loader_destroy(void *handle) {
  Loader *ld = static_cast<Loader *>(handle);
  {
    std::lock_guard<std::mutex> lk(ld->mu);
    ld->stopping = true;
  }
  ld->cv_space.notify_all();
  ld->cv_produced.notify_all();
  for (auto &t : ld->workers) t.join();
  delete ld;
}
